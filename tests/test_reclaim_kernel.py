"""Reclaim dense-formulation equivalence: the packed numpy reference
(ops/reclaim_pack.py) must reproduce the host ReclaimAction's evictions
and pipelined placements exactly — the same bindings-equivalence
discipline as the preempt pack (tests/test_preempt_kernel.py)."""

from __future__ import annotations

import numpy as np
import pytest

from volcano_tpu.actions.reclaim import ReclaimAction
from volcano_tpu.api import TaskStatus
from volcano_tpu.framework.framework import close_session, open_session
from volcano_tpu.ops.reclaim_pack import pack_reclaim_session, reclaim_dense

from tests.builders import (
    build_node,
    build_pod,
    build_pod_group,
    build_queue,
)
from tests.scheduler_helpers import make_cache, tiers


FULL_TIERS = tiers(
    ["priority", "gang", "conformance"],
    ["drf", "predicates", "proportion", "nodeorder", "binpack"],
)


def _run_host(cache):
    ssn = open_session(cache, FULL_TIERS, [])
    pk = pack_reclaim_session(ssn)
    ReclaimAction().execute(ssn)
    pipelined = {}
    for job in ssn.jobs.values():
        for t in job.task_status_index.get(TaskStatus.Pipelined, {}).values():
            pipelined[t.uid] = t.node_name
    close_session(ssn)
    return set(cache.evictor.evicts), pipelined, pk


def _assert_case(cache):
    host_ev, host_pipe, pk = _run_host(cache)
    evicted, pnode = reclaim_dense(pk)
    dense_ev = {pk.vic_names[i] for i in np.nonzero(evicted)[0]}
    dense_pipe = {
        pk.ptask_uids[p]: pk.node_names[pnode[p]]
        for p in range(pk.base.n_tasks)
        if pnode[p] >= 0
    }
    assert dense_ev == host_ev
    assert dense_pipe == host_pipe
    return host_ev, host_pipe


def _two_queue_case(greedy_pods=4, node_cpu="4", seed=0, weights=(1, 1)):
    """q-greedy holds the whole node; q-starved has pending work —
    reclaim must evict greedy victims for the underserved queue."""
    rng = np.random.RandomState(seed)
    nodes = [build_node("n000", {"cpu": node_cpu, "memory": "16G"})]
    pods, pgs = [], []
    for i in range(greedy_pods):
        pods.append(
            build_pod("ns", f"greedy-{i}", "n000",
                      {"cpu": "1", "memory": f"{1 + int(rng.randint(0, 2))}G"},
                      phase="Running", group=f"gpg{i % 2}")
        )
    pgs += [build_pod_group("ns", f"gpg{g}", 1, queue="q-greedy") for g in range(2)]
    pods.append(
        build_pod("ns", "starved-0", "", {"cpu": "1", "memory": "1G"}, group="spg")
    )
    pgs.append(build_pod_group("ns", "spg", 1, queue="q-starved"))
    return make_cache(
        nodes=nodes, pods=pods, pod_groups=pgs,
        queues=[build_queue("q-greedy", weight=weights[0]),
                build_queue("q-starved", weight=weights[1])],
    )


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_dense_matches_host_cross_queue(seed):
    host_ev, host_pipe = _assert_case(_two_queue_case(seed=seed))
    assert host_ev and host_pipe  # the scenario actually reclaims


def test_dense_matches_host_same_queue_untouchable():
    """Victims in the reclaimer's own queue are never reclaimed."""
    cache = make_cache(
        nodes=[build_node("n000", {"cpu": "2", "memory": "4G"})],
        pods=[
            build_pod("ns", "r1", "n000", {"cpu": "2", "memory": "2G"},
                      phase="Running", group="pg1"),
            build_pod("ns", "s1", "", {"cpu": "1", "memory": "1G"}, group="pg2"),
        ],
        pod_groups=[
            build_pod_group("ns", "pg1", 1, queue="q1"),
            build_pod_group("ns", "pg2", 1, queue="q1"),
        ],
        queues=[build_queue("q1", weight=1)],
    )
    host_ev, host_pipe = _assert_case(cache)
    assert host_ev == set() and host_pipe == {}


def test_dense_matches_host_gang_guard():
    """Victim job at its minAvailable floor: gang vetoes reclaim."""
    cache = make_cache(
        nodes=[build_node("n000", {"cpu": "2", "memory": "4G"})],
        pods=[
            build_pod("ns", "r1", "n000", {"cpu": "1", "memory": "1G"},
                      phase="Running", group="pg1"),
            build_pod("ns", "r2", "n000", {"cpu": "1", "memory": "1G"},
                      phase="Running", group="pg1"),
            build_pod("ns", "s1", "", {"cpu": "1", "memory": "1G"}, group="pg2"),
        ],
        pod_groups=[
            build_pod_group("ns", "pg1", 2, queue="q1"),
            build_pod_group("ns", "pg2", 1, queue="q2"),
        ],
        queues=[build_queue("q1", weight=1), build_queue("q2", weight=1)],
    )
    host_ev, host_pipe = _assert_case(cache)
    assert host_ev == set()


def test_dense_matches_host_overused_queue_skipped():
    """A queue already over its deserved share does not reclaim."""
    cache = make_cache(
        nodes=[build_node("n000", {"cpu": "8", "memory": "16G"})],
        pods=[
            # q1 hogs 6 of 8 cpus (deserved 4 with equal weights)
            build_pod("ns", "hog-0", "n000", {"cpu": "3", "memory": "2G"},
                      phase="Running", group="pg1"),
            build_pod("ns", "hog-1", "n000", {"cpu": "3", "memory": "2G"},
                      phase="Running", group="pg1"),
            build_pod("ns", "hog-p", "", {"cpu": "1", "memory": "1G"}, group="pg1"),
            build_pod("ns", "victim", "n000", {"cpu": "1", "memory": "1G"},
                      phase="Running", group="pg2"),
            # q2 demand keeps q1's deserved pinned at its weight share
            *[
                build_pod("ns", f"q2-pend-{i}", "", {"cpu": "1", "memory": "1G"},
                          group="pg2p")
                for i in range(6)
            ],
        ],
        pod_groups=[
            build_pod_group("ns", "pg1", 1, queue="q1"),
            build_pod_group("ns", "pg2", 1, queue="q2"),
            build_pod_group("ns", "pg2p", 1, queue="q2"),
        ],
        # q1 weight 1 vs q2 weight 7 with real q2 demand: deserved(q1)
        # ≈ 1 cpu, allocated 6 → q1 is overused and must not reclaim
        queues=[build_queue("q1", weight=1), build_queue("q2", weight=7)],
    )
    host_ev, host_pipe = _assert_case(cache)
    assert "ns/victim" not in host_ev


def test_dense_matches_host_multi_queue_rotation(seed=3):
    """Three queues, mixed victims: the dynamic share-ordered rotation
    must match the host's PriorityQueue behavior exactly."""
    rng = np.random.RandomState(seed)
    nodes = [build_node(f"n{i:03d}", {"cpu": "4", "memory": "8G"}) for i in range(3)]
    pods, pgs, queues = [], [], []
    for q in range(3):
        queues.append(build_queue(f"q{q}", weight=q + 1))
    fid = 0
    for i in range(3):
        for k in range(3):
            q = fid % 3
            pods.append(
                build_pod("ns", f"run-{fid:02d}", f"n{i:03d}",
                          {"cpu": "1", "memory": "1G"},
                          phase="Running", group=f"rpg{q}")
            )
            fid += 1
    for q in range(3):
        pgs.append(build_pod_group("ns", f"rpg{q}", 1, queue=f"q{q}"))
        pgs.append(build_pod_group("ns", f"spg{q}", 1, queue=f"q{q}"))
        pods.append(
            build_pod("ns", f"pend-{q}", "",
                      {"cpu": "1", "memory": "1G"}, group=f"spg{q}")
        )
    cache = make_cache(nodes=nodes, pods=pods, pod_groups=pgs, queues=queues)
    _assert_case(cache)


# ---- JaxReclaimAction: dense-dispatched action ≡ host action ----


def _run_action(cache, action):
    ssn = open_session(cache, FULL_TIERS, [])
    action.execute(ssn)
    pipelined = {}
    for job in ssn.jobs.values():
        for t in job.task_status_index.get(TaskStatus.Pipelined, {}).values():
            pipelined[f"{t.namespace}/{t.name}"] = t.node_name
    close_session(ssn)
    return set(cache.evictor.evicts), pipelined


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_jax_reclaim_action_matches_host(seed):
    from volcano_tpu.actions.jax_reclaim import JaxReclaimAction

    host = _run_action(_two_queue_case(seed=seed), ReclaimAction())
    dense = _run_action(_two_queue_case(seed=seed), JaxReclaimAction())
    assert dense == host
    assert host[0]  # scenario actually reclaims


def test_jax_reclaim_tier_fallback():
    """A session without proportion state routes to the host action."""
    from volcano_tpu.actions.jax_reclaim import JaxReclaimAction

    bare = tiers(["gang", "conformance"])  # no proportion plugin
    cache = _two_queue_case(seed=0)
    ssn = open_session(cache, bare, [])
    JaxReclaimAction().execute(ssn)  # must not raise
    close_session(ssn)
    host_cache = _two_queue_case(seed=0)
    hssn = open_session(host_cache, bare, [])
    ReclaimAction().execute(hssn)
    close_session(hssn)
    assert set(cache.evictor.evicts) == set(host_cache.evictor.evicts)


def test_both_roles_multi_job_queue_refused_and_falls_back():
    """A job that is both reclaimer and victim source in a queue with
    other starving jobs makes the frozen order unsound: pack refuses and
    the action falls back to the host with identical results."""
    from volcano_tpu.actions.jax_reclaim import JaxReclaimAction

    def mk():
        return make_cache(
            nodes=[build_node("n000", {"cpu": "4", "memory": "8G"})],
            pods=[
                # pg-mixed: running victims AND a pending task (both roles)
                build_pod("ns", "mx-r", "n000", {"cpu": "2", "memory": "2G"},
                          phase="Running", group="pg-mixed"),
                build_pod("ns", "mx-p", "", {"cpu": "1", "memory": "1G"},
                          group="pg-mixed"),
                # second starving job in the SAME queue → order hazard
                build_pod("ns", "sib-p", "", {"cpu": "1", "memory": "1G"},
                          group="pg-sib"),
                # cross-queue reclaimer
                build_pod("ns", "other-p", "", {"cpu": "1", "memory": "1G"},
                          group="pg-other"),
            ],
            pod_groups=[
                build_pod_group("ns", "pg-mixed", 1, queue="qa"),
                build_pod_group("ns", "pg-sib", 1, queue="qa"),
                build_pod_group("ns", "pg-other", 1, queue="qb"),
            ],
            queues=[build_queue("qa", weight=1), build_queue("qb", weight=1)],
        )

    ca = mk()
    ssn = open_session(ca, FULL_TIERS, [])
    with pytest.raises(ValueError, match="both reclaimer and victim source"):
        pack_reclaim_session(ssn)
    close_session(ssn)

    host = _run_action(mk(), ReclaimAction())
    dense = _run_action(mk(), JaxReclaimAction())
    assert dense == host


def test_synthetic_reclaim_pressure_invariants():
    """generate_reclaim_packed: every starved reclaimer lands by
    reclaiming greedy victims; evictions stay within gang floors; the
    incremental prefilter (reclaim_dense) keeps exact per-node drains."""
    from volcano_tpu.ops.synthetic import generate_reclaim_packed

    pk = generate_reclaim_packed(n_victims=900, n_nodes=100,
                                 n_reclaimers=100)
    evicted, pipelined = reclaim_dense(pk)
    assert (pipelined >= 0).all()  # pressure shape: everyone reclaims in
    # every pipelined node had at least one eviction backing it
    ev_nodes = set(pk.vic_node[np.nonzero(evicted)[0]])
    assert set(pipelined.tolist()) <= ev_nodes
    # gang floors respected: no victim job evicted below min_available —
    # the generator puts ~20% of victim jobs ONE eviction above their
    # floor, so this bites (and the incremental gang-flip path runs)
    ready = pk.job_ready0.copy()
    for v in np.nonzero(evicted)[0]:
        ready[pk.vic_job[v]] -= 1
    vjobs = set(pk.vic_job.tolist())
    # the gang guard's `min_available == 1` escape admits eviction below
    # the floor for min-1 jobs (host semantics, pinned by the
    # equivalence tests above); the floor binds only for min > 1
    assert all(ready[j] >= pk.job_min_avail[j]
               for j in vjobs if pk.job_min_avail[j] > 1)
    blocked = [j for j in vjobs if pk.job_min_avail[j] > 1]
    assert blocked, "generator produced no gang-blocked victim jobs"
    # at least one blocked job was driven exactly TO its floor, proving
    # the mid-pass eligibility flip engaged
    assert any(ready[j] == pk.job_min_avail[j] for j in blocked)
