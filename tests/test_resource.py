"""Resource arithmetic tests.

Mirrors the reference's table-driven resource tests
(pkg/scheduler/api/resource_info_test.go).
"""

import pytest

from volcano_tpu.api.resource import (
    MIN_MEMORY,
    MIN_MILLI_CPU,
    min_resource,
    Resource,
    share,
)


def res(cpu=0.0, mem=0.0, **scalars):
    return Resource(cpu, mem, scalars or None)


class TestConstruction:
    def test_from_resource_list_units(self):
        r = Resource.from_resource_list(
            {"cpu": "2", "memory": "4Gi", "pods": 110, "nvidia.com/gpu": 1}
        )
        assert r.milli_cpu == 2000
        assert r.memory == 4 * 1024**3
        assert r.max_task_num == 110
        assert r.scalars["nvidia.com/gpu"] == 1000

    def test_from_resource_list_milli_cpu(self):
        assert Resource.from_resource_list({"cpu": "250m"}).milli_cpu == 250

    def test_clone_is_deep(self):
        r = res(1000, 2**30, **{"nvidia.com/gpu": 2000})
        c = r.clone()
        c.scalars["nvidia.com/gpu"] = 0
        assert r.scalars["nvidia.com/gpu"] == 2000


class TestPredicates:
    def test_is_empty_tolerance(self):
        assert res(MIN_MILLI_CPU - 1, MIN_MEMORY - 1).is_empty()
        assert not res(MIN_MILLI_CPU, 0).is_empty()
        assert not res(0, MIN_MEMORY).is_empty()
        assert not res(0, 0, **{"nvidia.com/gpu": 10}).is_empty()

    def test_is_zero(self):
        r = res(5, 0)
        assert r.is_zero("cpu")
        assert r.is_zero("memory")
        assert r.is_zero("nvidia.com/gpu")


class TestArithmetic:
    def test_add_sub(self):
        a = res(1000, 1024, **{"nvidia.com/gpu": 1000})
        b = res(500, 512, **{"nvidia.com/gpu": 500})
        a.add(b)
        assert (a.milli_cpu, a.memory, a.scalars["nvidia.com/gpu"]) == (1500, 1536, 1500)
        a.sub(b)
        assert (a.milli_cpu, a.memory, a.scalars["nvidia.com/gpu"]) == (1000, 1024, 1000)

    def test_sub_insufficient_asserts(self, monkeypatch):
        """Env-gated like the reference's util/assert: fatal only under
        the panic env var (tests/test_race_discipline.py covers the
        lenient default)."""
        from volcano_tpu.utils import asserts

        monkeypatch.setenv(asserts.ENV_PANIC, "1")
        with pytest.raises(AssertionError):
            res(100).sub(res(500))

    def test_multi(self):
        r = res(1000, 1000, **{"x": 10}).multi(1.5)
        assert (r.milli_cpu, r.memory, r.scalars["x"]) == (1500, 1500, 15)

    def test_set_max(self):
        r = res(100, 5000).set_max(res(500, 1000, **{"x": 7}))
        assert (r.milli_cpu, r.memory, r.scalars["x"]) == (500, 5000, 7)

    def test_diff(self):
        inc, dec = res(1000, 100).diff(res(400, 300))
        assert (inc.milli_cpu, inc.memory) == (600, 0)
        assert (dec.milli_cpu, dec.memory) == (0, 200)


class TestComparisons:
    def test_less_equal_within_tolerance(self):
        # Equal-within-tolerance counts as LessEqual (resource_info.go:292).
        assert res(1000 + MIN_MILLI_CPU - 1, 0).less_equal(res(1000, 0))
        assert not res(1000 + MIN_MILLI_CPU, 0).less_equal(res(1000, 0))

    def test_less_equal_ignores_negligible_scalars(self):
        assert res(100, 0, **{"x": 5}).less_equal(res(100, 0))
        assert not res(100, 0, **{"x": 500}).less_equal(res(100, 0))

    def test_less_strict_all_dims(self):
        assert res(1, 1).less(res(2, 2))
        assert not res(1, 2).less(res(2, 2))

    def test_less_equal_strict(self):
        assert res(2, 2).less_equal_strict(res(2, 2))
        assert not res(3, 2).less_equal_strict(res(2, 2))


def test_min_resource():
    m = min_resource(res(100, 500, **{"x": 5}), res(200, 300, **{"x": 9}))
    assert (m.milli_cpu, m.memory, m.scalars["x"]) == (100, 300, 5)


def test_share_conventions():
    assert share(0, 0) == 0
    assert share(5, 0) == 1
    assert share(1, 4) == 0.25
