"""Scale equivalence gate (VERDICT r2 #6): seeded large-shape identity
between the session formulations, so perf work can't silently break
exactness.  The plain lax.scan (run_packed) is the reference formulation
— proven bindings-identical to the host action at small shapes in
tests/test_jax_allocate.py — so chaining these identities extends host
equivalence to scale:

  plain ≡ blocked ≡ sharded(8-device mesh)   at 10k tasks × 1k nodes
  plain ≡ pallas(interpret)                  at 2k tasks × 1k nodes
  blocked ≡ sharded                          at 4k tasks × 10k nodes
                                             (≥10k nodes, VERDICT #3)

Real-TPU compiled-Mosaic equivalence at the full 50k × 10k headline
shape is asserted every round by bench.py's identical_bindings field
(the driver records it in BENCH_rN.json); interpret mode here covers the
kernel logic itself on CPU CI."""

from __future__ import annotations

import jax
import numpy as np
import pytest
from jax.sharding import Mesh

from volcano_tpu.ops.blocked import run_packed_blocked
from volcano_tpu.ops.kernels import run_packed
from volcano_tpu.ops.sharded import run_packed_sharded
from volcano_tpu.ops.synthetic import (
    generate_preempt_packed,
    generate_snapshot,
)

pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def mesh():
    devices = jax.devices()
    if len(devices) < 2:
        pytest.skip("needs a multi-device backend")
    return Mesh(np.array(devices).reshape(len(devices)), ("nodes",))


def test_plain_blocked_sharded_10k_tasks_1k_nodes(mesh):
    snap = generate_snapshot(
        n_tasks=10_000, n_nodes=1_000, gang_size=4, seed=7,
        label_classes=4, taint_fraction=0.1,
    )
    plain = run_packed(snap)
    assert np.array_equal(plain, run_packed_blocked(snap))
    assert np.array_equal(plain, run_packed_sharded(snap, mesh))
    assert (plain >= 0).sum() > 5_000  # the scenario actually places


def test_pallas_interpret_matches_plain_2k_tasks_1k_nodes():
    from volcano_tpu.ops.pallas_session import run_packed_pallas

    snap = generate_snapshot(
        n_tasks=2_048, n_nodes=1_000, gang_size=8, seed=11,
        label_classes=4, taint_fraction=0.1,
    )
    plain = run_packed(snap)
    pallas = run_packed_pallas(snap, interpret=True)
    assert np.array_equal(plain, pallas)
    assert (plain >= 0).sum() > 1_000


def test_sharded_10k_nodes(mesh):
    """VERDICT #3 done criterion: the sharded mesh kernel reproduces the
    fast single-chip formulation exactly at ≥10k nodes."""
    snap = generate_snapshot(n_tasks=4_096, n_nodes=10_000, gang_size=8, seed=3)
    assert np.array_equal(run_packed_blocked(snap), run_packed_sharded(snap, mesh))


def test_preempt_dense_native_pallas_mid_scale():
    """Preempt formulations agree at a mid scale with queue spread and
    gang-blocked victim jobs (the bench asserts the same at 100k/10k on
    real TPU every round)."""
    from volcano_tpu import native
    from volcano_tpu.ops.preempt_pack import preempt_dense
    from volcano_tpu.ops.preempt_pallas import run_preempt_pallas

    pk = generate_preempt_packed(
        n_victims=3_600, n_nodes=400, n_preemptors=400, seed=5
    )
    ev_d, pipe_d = preempt_dense(pk)
    ev_n, pipe_n = native.baseline_preempt(pk)
    assert np.array_equal(ev_d, ev_n) and np.array_equal(pipe_d, pipe_n)
    ev_p, pipe_p = run_preempt_pallas(pk, interpret=True)
    assert np.array_equal(ev_d, ev_p) and np.array_equal(pipe_d, pipe_p)
    assert ev_d.sum() > 100  # real preemption pressure
