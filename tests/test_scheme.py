"""Dual-version scheduling API: v1alpha1 shims convert through the
scheme to the hub, and the cache's v1alpha1 handler set schedules
v1alpha1-created objects identically (cache.go:393-424)."""

from __future__ import annotations

from volcano_tpu.apis import core, scheduling
from volcano_tpu.apis.scheme import (
    pod_group_hub_to_v1alpha1,
    pod_group_v1alpha1_to_hub,
    PodGroupV1alpha1,
    queue_hub_to_v1alpha1,
    queue_v1alpha1_to_hub,
    QueueSpecV1alpha1,
    QueueV1alpha1,
)

from tests.builders import build_node, build_pod
from tests.scheduler_helpers import make_cache


class TestConversions:
    def test_queue_v1alpha1_roundtrip_defaults_state_open(self):
        q1 = QueueV1alpha1(
            metadata=core.ObjectMeta(name="q", namespace=""),
            spec=QueueSpecV1alpha1(weight=4, capability={"cpu": "100"}),
        )
        hub = queue_v1alpha1_to_hub(q1)
        assert hub.spec.state == scheduling.QUEUE_STATE_OPEN
        assert hub.spec.weight == 4 and hub.spec.capability == {"cpu": "100"}
        back = queue_hub_to_v1alpha1(hub)
        assert back.spec.weight == 4
        assert not hasattr(back.spec, "state")  # v1alpha1 has no QueueState

    def test_pod_group_roundtrip(self):
        pg1 = PodGroupV1alpha1(
            metadata=core.ObjectMeta(name="pg", namespace="ns"),
            spec=scheduling.PodGroupSpec(min_member=3, queue="q"),
        )
        hub = pod_group_v1alpha1_to_hub(pg1)
        assert hub.kind == "PodGroup"
        assert hub.spec.min_member == 3
        back = pod_group_hub_to_v1alpha1(hub)
        assert back.spec.queue == "q"

    def test_hub_to_v1alpha1_drops_v2_only_status(self):
        hub = scheduling.Queue(
            metadata=core.ObjectMeta(name="q", namespace=""),
            status=scheduling.QueueStatus(state="Open", inqueue=7, running=2),
        )
        back = queue_hub_to_v1alpha1(hub)
        assert back.status.running == 2
        assert not hasattr(back.status, "inqueue")


class TestCacheDualVersionHandlers:
    def test_v1alpha1_objects_schedule_identically(self):
        """Feed the cache through the v1alpha1 handler set; the session
        must see a normal hub queue/podgroup and place the pod."""
        cache = make_cache(
            nodes=[build_node("n0", {"cpu": "4", "memory": "8G"})],
            pods=[], pod_groups=[], queues=[],
        )
        cache.add_queue_v1alpha1(
            QueueV1alpha1(metadata=core.ObjectMeta(name="q1", namespace=""))
        )
        cache.add_pod_group_v1alpha1(
            PodGroupV1alpha1(
                metadata=core.ObjectMeta(name="pg1", namespace="ns"),
                spec=scheduling.PodGroupSpec(min_member=1, queue="q1"),
                status=scheduling.PodGroupStatus(
                    phase=scheduling.POD_GROUP_INQUEUE
                ),
            )
        )
        cache.add_pod(build_pod("ns", "p1", "", {"cpu": "1", "memory": "1G"},
                                group="pg1"))

        from volcano_tpu.actions.allocate import AllocateAction
        from volcano_tpu.framework.framework import close_session, open_session
        from tests.scheduler_helpers import tiers

        ssn = open_session(
            cache,
            tiers(["priority", "gang", "conformance"],
                  ["drf", "predicates", "proportion", "nodeorder", "binpack"]),
            [],
        )
        assert "q1" in ssn.queues
        AllocateAction().execute(ssn)
        close_session(ssn)
        assert cache.binder.binds  # the v1alpha1-created pg scheduled

    def test_v1alpha1_update_delete_handlers(self):
        cache = make_cache(nodes=[], pods=[], pod_groups=[], queues=[])
        q = QueueV1alpha1(metadata=core.ObjectMeta(name="q2", namespace=""))
        cache.add_queue_v1alpha1(q)
        assert "q2" in cache.queues
        q.spec.weight = 9
        cache.update_queue_v1alpha1(None, q)
        assert cache.queues["q2"].weight == 9
        cache.delete_queue_v1alpha1(q)
        assert "q2" not in cache.queues


class TestDualInformerWire:
    def test_raw_v1alpha1_objects_on_the_bus_schedule(self):
        """A legacy writer stores RAW v1alpha1 objects (no converting
        client): the scheduler's dual informer set must still feed the
        cache and schedule the pod — the cache.go:393-424 behavior."""
        import time

        from volcano_tpu.cmd import SchedulerDaemon
        from volcano_tpu.client import APIServer, KubeClient
        from tests.builders import build_node as bn

        api = APIServer()
        kube = KubeClient(api)
        kube.create_node(bn("n0", {"cpu": "8", "memory": "16Gi"}))
        scheduler = SchedulerDaemon(api, schedule_period=0.05).start()
        try:
            api.create(QueueV1alpha1(
                metadata=core.ObjectMeta(name="raw-q", namespace="")))
            # starts PENDING: enqueue must promote it through the
            # versioned-kind status writeback, then allocate binds
            api.create(PodGroupV1alpha1(
                metadata=core.ObjectMeta(name="raw-pg", namespace="ns"),
                spec=scheduling.PodGroupSpec(min_member=1, queue="raw-q"),
            ))
            kube.create_pod(build_pod("ns", "raw-pod", "",
                                      {"cpu": "1", "memory": "1Gi"},
                                      group="raw-pg"))
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                p = kube.get_pod("ns", "raw-pod")
                if p.spec.node_name:
                    break
                time.sleep(0.05)
            assert kube.get_pod("ns", "raw-pod").spec.node_name == "n0"
            # status wrote back to the RAW kind (not silently dropped).
            # The bind is API-visible mid-cycle but the status writeback
            # lands at close_session, a few ms later — poll rather than
            # racing that window, on a FRESH deadline (the bind wait
            # above may have consumed the first one).
            deadline = time.monotonic() + 10
            stored = api.get("PodGroupV1alpha1", "ns", "raw-pg")
            while (
                stored.status.phase == scheduling.POD_GROUP_PENDING
                and time.monotonic() < deadline
            ):
                time.sleep(0.05)
                stored = api.get("PodGroupV1alpha1", "ns", "raw-pg")
            assert stored.status.phase in (
                scheduling.POD_GROUP_INQUEUE, scheduling.POD_GROUP_RUNNING
            )
        finally:
            scheduler.stop()
