"""Edge cases of the /metrics read side (metrics/scrape.py) — the
parser, cross-member histogram merge, the bucket-quantile answer, and
the windowed delta that ``vtctl top --interval`` and the burn-rate
watchdog's TimeSeriesRing both stand on.  These paths see hostile
input by construction (half-scraped exposition text, restarted
processes, members on different build's bucket bounds), so each edge
is pinned explicitly."""

from __future__ import annotations

import pytest

from volcano_tpu.metrics.scrape import (
    delta,
    histogram_quantile,
    merge_histograms,
    parse_metrics,
)


class TestParseMetrics:
    def test_skips_comments_blanks_and_malformed_lines(self):
        s = parse_metrics(
            "# HELP volcano_x_total help text\n"
            "# TYPE volcano_x_total counter\n"
            "\n"
            "volcano_x_total 3\n"
            "volcano_y_total not-a-number\n"
            "}{ garbage line\n"
            'volcano_z{queue="q1"} 2.5\n'
        )
        assert s.value("volcano_x_total") == 3.0
        assert s.value("volcano_y_total") == 0.0
        assert s.value("volcano_z", queue="q1") == 2.5

    def test_value_partial_match_sums_across_series(self):
        s = parse_metrics(
            'volcano_pods{daemon="sched",shard="a"} 1\n'
            'volcano_pods{daemon="sched",shard="b"} 2\n'
            'volcano_pods{daemon="ctrl"} 10\n'
        )
        assert s.value("volcano_pods") == 13.0
        assert s.value("volcano_pods", daemon="sched") == 3.0
        assert s.value("volcano_pods", shard="b") == 2.0
        assert s.value("volcano_pods", shard="nope") == 0.0

    def test_histogram_reassembles_sum_count_and_sorted_buckets(self):
        s = parse_metrics(
            'volcano_lat_milliseconds_bucket{le="+Inf"} 4\n'
            'volcano_lat_milliseconds_bucket{le="10"} 3\n'
            'volcano_lat_milliseconds_bucket{le="5"} 1\n'
            "volcano_lat_milliseconds_sum 21.0\n"
            "volcano_lat_milliseconds_count 4\n"
        )
        h = s.histogram("volcano_lat_milliseconds")
        assert h == {"buckets": [("5", 1.0), ("10", 3.0), ("+Inf", 4.0)],
                     "sum": 21.0, "count": 4.0}

    def test_orphan_sum_count_without_buckets_stay_plain_series(self):
        # _sum/_count lines with no _bucket sibling are somebody
        # else's counter, not a histogram fragment
        s = parse_metrics("volcano_thing_count 7\n")
        assert s.histogram("volcano_thing") is None
        assert s.value("volcano_thing_count") == 7.0


class TestMergeHistograms:
    def test_empty_input_merges_to_empty(self):
        assert merge_histograms([]) == {"buckets": [], "sum": 0.0,
                                        "count": 0.0}

    def test_same_shape_merges_pointwise(self):
        h1 = {"buckets": [("5", 1.0), ("+Inf", 2.0)],
              "sum": 12.0, "count": 2.0}
        h2 = {"buckets": [("5", 3.0), ("+Inf", 3.0)],
              "sum": 9.0, "count": 3.0}
        assert merge_histograms([h1, h2]) == {
            "buckets": [("5", 4.0), ("+Inf", 5.0)],
            "sum": 21.0, "count": 5.0,
        }

    def test_mismatched_boundaries_merge_by_bound(self):
        # a member on different bucket bounds must not corrupt the
        # fleet merge: stray bounds interleave, +Inf sorts last
        h1 = {"buckets": [("5", 3.0), ("+Inf", 4.0)],
              "sum": 20.0, "count": 4.0}
        h2 = {"buckets": [("10", 2.0), ("+Inf", 2.0)],
              "sum": 12.0, "count": 2.0}
        assert merge_histograms([h1, h2]) == {
            "buckets": [("5", 3.0), ("10", 2.0), ("+Inf", 6.0)],
            "sum": 32.0, "count": 6.0,
        }

    def test_missing_keys_default_to_zero(self):
        assert merge_histograms([{}, {"sum": 1.0}]) == {
            "buckets": [], "sum": 1.0, "count": 0.0}


class TestHistogramQuantile:
    def test_empty_or_missing_histogram_is_zero(self):
        assert histogram_quantile(None, 0.99) == 0.0
        assert histogram_quantile({"buckets": [], "count": 0.0}, 0.5) == 0.0
        assert histogram_quantile(merge_histograms([]), 0.99) == 0.0

    def test_linear_interpolation_within_winning_bucket(self):
        h = {"buckets": [("10", 5.0), ("20", 10.0), ("+Inf", 10.0)],
             "sum": 0.0, "count": 10.0}
        assert histogram_quantile(h, 0.5) == pytest.approx(10.0)
        assert histogram_quantile(h, 0.75) == pytest.approx(15.0)
        assert histogram_quantile(h, 0.25) == pytest.approx(5.0)

    def test_inf_winning_bucket_answers_its_lower_bound(self):
        h = {"buckets": [("10", 5.0), ("+Inf", 10.0)],
             "sum": 0.0, "count": 10.0}
        # the observation is somewhere past the last finite bound —
        # the only honest answer is that bound, not infinity
        assert histogram_quantile(h, 0.99) == 10.0

    def test_all_mass_in_inf_bucket_answers_zero(self):
        h = {"buckets": [("+Inf", 10.0)], "sum": 0.0, "count": 10.0}
        assert histogram_quantile(h, 0.5) == 0.0

    def test_empty_middle_bucket_does_not_divide_by_zero(self):
        # cum == prev_cum in the winning bucket: interpolation would
        # divide by zero — the quantile falls back to the lower bound
        h = {"buckets": [("10", 0.0), ("20", 0.0), ("+Inf", 4.0)],
             "sum": 0.0, "count": 4.0}
        assert histogram_quantile(h, 0.5) == 20.0


class TestDelta:
    def test_counters_subtract_gauges_keep_later_value(self):
        earlier = parse_metrics(
            "volcano_binds_total 10\nvolcano_repl_lag_entries 100\n")
        later = parse_metrics(
            "volcano_binds_total 15\nvolcano_repl_lag_entries 3\n")
        d = delta(later, earlier)
        assert d.value("volcano_binds_total") == 5.0
        assert d.value("volcano_repl_lag_entries") == 3.0

    def test_counter_regression_reads_as_restart(self):
        # a restarted member resets its counters to zero: the later
        # value IS the window, never a negative rate
        earlier = parse_metrics("volcano_binds_total 1000\n")
        later = parse_metrics("volcano_binds_total 7\n")
        assert delta(later, earlier).value("volcano_binds_total") == 7.0

    def test_histogram_delta_clamps_regressions_to_zero(self):
        earlier = parse_metrics(
            'volcano_lat_ms_bucket{le="5"} 8\n'
            'volcano_lat_ms_bucket{le="+Inf"} 9\n'
            "volcano_lat_ms_sum 50.0\n"
            "volcano_lat_ms_count 9\n"
        )
        later = parse_metrics(
            'volcano_lat_ms_bucket{le="5"} 2\n'
            'volcano_lat_ms_bucket{le="+Inf"} 12\n'
            "volcano_lat_ms_sum 40.0\n"
            "volcano_lat_ms_count 12\n"
        )
        h = delta(later, earlier).histogram("volcano_lat_ms")
        assert h == {"buckets": [("5", 0.0), ("+Inf", 3.0)],
                     "sum": 0.0, "count": 3.0}

    def test_series_missing_from_earlier_scrape_counts_whole(self):
        earlier = parse_metrics("")
        later = parse_metrics("volcano_binds_total 4\n")
        assert delta(later, earlier).value("volcano_binds_total") == 4.0
