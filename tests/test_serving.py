"""Serving surface e2e (VERDICT r2 #4): daemons with real HTTP
healthz/metrics and ConfigMap-lock leader election.

Mirrors the reference's binary behavior: metrics server
(cmd/scheduler/app/server.go:96-99), healthz (:101), leader election
with standby takeover (:110-156)."""

from __future__ import annotations

import time
import urllib.request


from volcano_tpu.apis import batch, core, scheduling
from volcano_tpu.client import APIServer, KubeClient, VolcanoClient
from volcano_tpu.cmd import AdmissionDaemon, ControllersDaemon, SchedulerDaemon
from volcano_tpu.metrics import metrics
from volcano_tpu.serving import LeaderElector

from tests.builders import build_node


def _get(port: int, path: str) -> str:
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}", timeout=5) as r:
        return r.read().decode()


def _mk_cluster():
    api = APIServer()
    kube = KubeClient(api)
    vc = VolcanoClient(api)
    for i in range(3):
        kube.create_node(build_node(f"node-{i}", {"cpu": "8", "memory": "16Gi"}))
    vc.create_queue(
        scheduling.Queue(metadata=core.ObjectMeta(name="default", namespace=""))
    )
    return api, kube, vc


def _submit(vc, name="srv-job", replicas=2):
    task = batch.TaskSpec(
        name="worker",
        replicas=replicas,
        template=core.PodTemplateSpec(
            spec=core.PodSpec(
                containers=[
                    core.Container(
                        image="registry.k8s.io/pause:3.9",
                        resources={"requests": {"cpu": "1", "memory": "1Gi"}})
                ]
            )
        ),
    )
    return vc.create_job(
        batch.Job(
            metadata=core.ObjectMeta(name=name, namespace="default"),
            spec=batch.JobSpec(min_available=replicas, tasks=[task]),
        )
    )


def _wait(predicate, timeout=15.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False


class TestServingSurface:
    def test_healthz_and_metrics_scrape_over_http(self):
        """Start the three daemons, schedule a real job, scrape a real
        counter from the scheduler's /metrics over HTTP."""
        metrics.registry.reset()
        api, kube, vc = _mk_cluster()
        admission = AdmissionDaemon(api).start()
        controllers = ControllersDaemon(api, period=0.05).start()
        scheduler = SchedulerDaemon(api, schedule_period=0.05).start()
        try:
            for daemon in (admission, controllers, scheduler):
                assert _get(daemon.serving.port, "/healthz") == "ok"

            _submit(vc)
            assert _wait(
                lambda: any(
                    p.spec.node_name for p in kube.list_pods("default")
                )
            ), "job pods never got bound"

            body = _get(scheduler.serving.port, "/metrics")
            assert "volcano_e2e_scheduling_latency_milliseconds_count" in body
            count_line = [
                ln for ln in body.splitlines()
                if ln.startswith("volcano_e2e_scheduling_latency_milliseconds_count")
            ][0]
            assert float(count_line.split()[-1]) > 0
        finally:
            scheduler.stop()
            controllers.stop()
            admission.stop()

    def test_leader_election_single_winner_and_takeover(self):
        """Two scheduler daemons, one lock: only the leader schedules;
        killing the leader (no lease release) hands over after expiry."""
        api, kube, vc = _mk_cluster()
        a = SchedulerDaemon(
            api, schedule_period=0.05, leader_elect=True, identity="sched-a",
            lease_duration=0.5, retry_period=0.05,
        ).start()
        assert _wait(lambda: a.elector.is_leader), "first daemon never led"
        b = SchedulerDaemon(
            api, schedule_period=0.05, leader_elect=True, identity="sched-b",
            lease_duration=0.5, retry_period=0.05,
        ).start()
        try:
            _submit(vc, name="le-job")
            # give b time to (wrongly) schedule if election were broken
            time.sleep(0.5)
            assert a.elector.is_leader and not b.elector.is_leader
            assert a.cycles > 0 and b.cycles == 0

            # crash the leader: no graceful release → expiry takeover
            a.stop(crash=True)
            assert _wait(lambda: b.elector.is_leader, timeout=10), (
                "standby never took over after leader crash"
            )
            before = b.cycles
            assert _wait(lambda: b.cycles > before), "new leader never scheduled"
        finally:
            b.stop()

    def test_elector_cas_prevents_double_leadership(self):
        """Direct elector race: two candidates, one ConfigMap — the CAS
        guarantees at most one holds the lease at any moment."""
        api = APIServer()
        e1 = LeaderElector(api, "lock", "id-1", lease_duration=0.5, retry_period=0.02).start()
        e2 = LeaderElector(api, "lock", "id-2", lease_duration=0.5, retry_period=0.02).start()
        try:
            assert _wait(lambda: e1.is_leader or e2.is_leader)
            for _ in range(20):
                assert not (e1.is_leader and e2.is_leader)
                time.sleep(0.02)
            # graceful release hands over quickly
            leader, standby = (e1, e2) if e1.is_leader else (e2, e1)
            leader.stop(release=True)
            assert _wait(lambda: standby.is_leader, timeout=5)
        finally:
            e1.stop()
            e2.stop()


def test_debug_stacks_endpoint():
    """The pprof-goroutine analogue: /debug/stacks dumps live thread
    stacks for hang forensics."""
    from volcano_tpu.serving import ServingServer

    srv = ServingServer().start()
    try:
        body = _get(srv.port, "/debug/stacks")
        assert "MainThread" in body
        assert "---" in body
    finally:
        srv.stop()


def test_debug_stacks_gating():
    """ADVICE r3: /debug/stacks must not serve non-loopback clients
    unless explicitly enabled — a cluster-exposed metrics port must not
    also expose thread-stack forensics."""
    from volcano_tpu.serving.http import debug_allowed

    assert debug_allowed(False, "127.0.0.1")
    assert debug_allowed(False, "::1")
    assert not debug_allowed(False, "10.1.2.3")
    assert debug_allowed(True, "10.1.2.3")


def test_leader_renew_time_is_wall_clock():
    """ADVICE r3: renewTime is written by one candidate and judged by
    others — it must be a wall-clock timestamp, not a process-local
    monotonic reading.  A record stamped with wall time by 'another
    process' must hold off a standby until it expires."""
    import json as _json
    import time as _time

    from volcano_tpu.client.apiserver import APIServer
    from volcano_tpu.apis import core
    from volcano_tpu.serving.leader import LEASE_KEY, LeaderElector

    api = APIServer()
    # a live lease written by a foreign process, wall-clock stamped
    api.create(core.ConfigMap(
        metadata=core.ObjectMeta(name="lock", namespace="volcano-system"),
        data={LEASE_KEY: _json.dumps({
            "holderIdentity": "other-process",
            "leaseDurationSeconds": 2.0,
            "renewTime": _time.time(),
        })},
    ))
    e = LeaderElector(api, "lock", "standby", lease_duration=0.5, retry_period=0.05)
    assert not e._try_acquire_or_renew(), "stole a live foreign lease"
    # an expired foreign lease (wall-clock in the past) must be taken
    cm, _ = e._read()
    cm.data = {LEASE_KEY: _json.dumps({
        "holderIdentity": "other-process",
        "leaseDurationSeconds": 0.2,
        "renewTime": _time.time() - 5.0,
    })}
    api.compare_and_update(cm, cm.metadata.resource_version)
    assert e._try_acquire_or_renew(), "did not take an expired lease"


def test_event_aggregation_key_excludes_message():
    """ADVICE r3: repeats of (object, type, reason) with varying
    messages must aggregate into ONE Event with a bumped count, like the
    k8s correlator — otherwise a stuck object mints unbounded Events."""
    from volcano_tpu.client.apiserver import APIServer
    from volcano_tpu.client.clients import SchedulerClient

    api = APIServer()
    clients = SchedulerClient(api)
    obj = {"kind": "Pod", "namespace": "ns", "name": "p1"}
    clients.record_event("ns", obj, "Warning", "FailedScheduling",
                         "failed to bind to n1: full")
    ev = clients.record_event("ns", obj, "Warning", "FailedScheduling",
                              "failed to bind to n2: full")
    events = api.list("Event", "ns")
    assert len(events) == 1
    assert ev.count == 2
    # message refreshes to the latest occurrence (k8s correlator behavior)
    assert ev.message == "failed to bind to n2: full"
