"""Sharded session kernel: node-axis sharding over a mesh must reproduce
the single-chip kernel's assignments exactly (deterministic cross-shard
argmax reduction)."""

from __future__ import annotations

import jax
import numpy as np
import pytest
from jax.sharding import Mesh

from volcano_tpu.api import TaskStatus
from volcano_tpu.ops import pack_session, run_packed
from volcano_tpu.ops.sharded import run_packed_sharded

from tests.builders import build_node, build_pod, build_pod_group, build_queue
from tests.scheduler_helpers import make_cache


def _snap(n_nodes=16, n_jobs=3, tasks_per_job=8, cpu="8", taint_some=False):
    from volcano_tpu.apis import core

    nodes = []
    for i in range(n_nodes):
        taints = []
        if taint_some and i % 4 == 0:
            taints = [core.Taint(key="dedicated", value="x", effect="NoSchedule")]
        nodes.append(
            build_node(f"n{i:03d}", {"cpu": cpu, "memory": "16Gi"}, taints=taints)
        )
    pods, pgs = [], []
    for j in range(n_jobs):
        pgs.append(build_pod_group("ns", f"pg{j}", 2, queue="q"))
        for i in range(tasks_per_job):
            pods.append(
                build_pod("ns", f"j{j}-t{i:02d}", "", {"cpu": "2", "memory": "2Gi"}, group=f"pg{j}")
            )
    cache = make_cache(nodes=nodes, pods=pods, pod_groups=pgs, queues=[build_queue("q")])
    snapshot = cache.snapshot()
    jobs = sorted(snapshot.jobs.values(), key=lambda j: j.uid)
    tasks = [
        t
        for job in jobs
        for t in sorted(
            job.task_status_index.get(TaskStatus.Pending, {}).values(),
            key=lambda t: t.uid,
        )
    ]
    nodes = [snapshot.nodes[n] for n in sorted(snapshot.nodes)]
    return pack_session(tasks, jobs, nodes)


@pytest.fixture(scope="module")
def mesh():
    devices = jax.devices()
    if len(devices) < 2:
        pytest.skip("needs a multi-device backend")
    return Mesh(np.array(devices).reshape(len(devices)), ("nodes",))


def test_sharded_matches_single_chip(mesh):
    snap = _snap()
    assert (run_packed(snap) == run_packed_sharded(snap, mesh)).all()


def test_sharded_matches_single_chip_with_taints(mesh):
    snap = _snap(taint_some=True)
    assert (run_packed(snap) == run_packed_sharded(snap, mesh)).all()


def test_sharded_matches_single_chip_gang_discard(mesh):
    """Over-subscribed: some gangs must be discarded identically."""
    snap = _snap(n_nodes=4, n_jobs=6, tasks_per_job=4, cpu="4")
    single = run_packed(snap)
    sharded = run_packed_sharded(snap, mesh)
    assert (single == sharded).all()
    assert (single == -1).any()  # scenario actually exercises discards


def test_dispatch_selects_sharded_on_mesh():
    """VERDICT r4 item 5: the production dispatcher must route big
    multi-device sessions to the sharded formulation (node width over
    the threshold, pallas unavailable off-TPU) and produce the same
    bindings as the reference scan."""
    from volcano_tpu.ops.dispatch import (
        _SHARD_MIN_NODES,
        run_packed_auto,
        select_executor,
    )
    from volcano_tpu.ops.synthetic import generate_snapshot

    assert len(jax.devices()) >= 2  # conftest forces the 8-device mesh
    snap = generate_snapshot(
        n_tasks=1_024, n_nodes=max(2_048, _SHARD_MIN_NODES), gang_size=4,
        seed=3, label_classes=4,
    )
    assert select_executor(snap) == "sharded"
    assert (run_packed_auto(snap) == run_packed(snap)).all()


def test_dispatch_small_session_stays_single_chip():
    from volcano_tpu.ops.dispatch import run_packed_auto, select_executor
    from volcano_tpu.ops.synthetic import generate_snapshot

    snap = generate_snapshot(n_tasks=128, n_nodes=64, gang_size=4, seed=1)
    assert select_executor(snap) in ("native", "xla-scan")
    assert (run_packed_auto(snap) == run_packed(snap)).all()
