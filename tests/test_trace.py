"""volcano_tpu/trace — recorder, journal, replay, export, endpoint, CLI.

Fast (tier-1) coverage of the cycle record/replay subsystem:
  * NullRecorder really is a no-op (and cheap);
  * journal JSONL + npz snapshot round-trips exactly;
  * replay.verify reproduces recorded bindings for the jax (and, when
    the toolchain is present, native) executors and flags an injected
    perturbation;
  * Chrome trace export emits schema-valid trace_event JSON;
  * /trace/last serves the last cycle; 404 before any cycle;
  * vtctl trace record|replay|diff|export end-to-end;
  * a live Scheduler.run_once journals its decision set.
"""

from __future__ import annotations

import io
import json
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from volcano_tpu import trace
from volcano_tpu.ops.packing import load_snapshot, save_snapshot
from volcano_tpu.ops.synthetic import generate_snapshot
from volcano_tpu.trace.journal import Journal
from volcano_tpu.trace.recorder import NullRecorder, TraceRecorder
from volcano_tpu.trace.replay import run_snapshot, verify

from tests.builders import build_pod, build_pod_group, build_queue
from tests.scheduler_helpers import make_cache


@pytest.fixture(autouse=True)
def _reset_global_recorder():
    yield
    trace.disable()


# ---- recorder ----


def test_default_recorder_is_null():
    rec = trace.get_recorder()
    assert isinstance(rec, NullRecorder)
    assert not rec.enabled
    assert rec.begin_cycle() == -1
    with rec.span("x", "y"):
        pass
    rec.event("x")
    rec.decision("bind", "t0", "n0")
    rec.end_cycle()
    assert rec.last_cycle() is None


def test_null_recorder_overhead_is_negligible():
    """The disabled path must stay cheap enough that instrumented hot
    loops never notice it: 100k guarded emissions well under a second."""
    rec = NullRecorder()
    t0 = time.perf_counter()
    for _ in range(100_000):
        if rec.enabled:
            rec.event("never")
    assert time.perf_counter() - t0 < 1.0


def test_recorder_cycle_assembly():
    rec = TraceRecorder()
    assert rec.begin_cycle() == 0
    rec.event("hello", "cat", answer=42)
    with rec.span("work", "action"):
        pass
    rec.decision("bind", "task-1", "node-1")
    rec.end_cycle(duration_s=0.5)

    record = rec.last_cycle()
    assert record["cycle"] == 0
    assert record["duration_ms"] == pytest.approx(500.0)
    names = [e["name"] for e in record["events"]]
    assert names == ["hello", "work"]
    span = record["events"][1]
    assert span["ph"] == "X" and span["dur"] >= 0
    (decision,) = record["decisions"]
    assert decision["kind"] == "bind"
    assert decision["task"] == "task-1"
    assert decision["node"] == "node-1"
    assert decision["ts"] >= record["start_us"]
    # next cycle starts clean
    assert rec.begin_cycle() == 1
    rec.end_cycle()
    assert rec.last_cycle()["events"] == []


# ---- journal ----


def test_journal_roundtrip_and_ring(tmp_path):
    journal = Journal(str(tmp_path), keep=3)
    rec = TraceRecorder(journal=journal)
    for i in range(5):
        rec.begin_cycle()
        rec.event("e", "c", i=i)
        rec.decision("bind", f"t{i}", f"n{i}")
        rec.end_cycle(duration_s=0.001 * (i + 1))
    # ring keeps only the newest 3 cycles
    assert journal.cycles() == [2, 3, 4]
    record = journal.read_cycle(4)
    assert record["cycle"] == 4
    assert record["events"][0]["args"] == {"i": 4}
    (decision,) = record["decisions"]
    assert (decision["kind"], decision["task"], decision["node"]) == (
        "bind", "t4", "n4",
    )
    assert record["duration_ms"] == pytest.approx(5.0)


def test_journal_ignores_foreign_files(tmp_path):
    """Non-numeric cycle-*.npz names (a user-renamed backup) must be
    ignored by the strict filename match, not crash every caller."""
    (tmp_path / "cycle-keep.npz").write_bytes(b"")
    (tmp_path / "cycle-00000002.npz").write_bytes(b"")
    journal = Journal(str(tmp_path))
    assert journal.snapshot_cycles() == [2]
    rec = TraceRecorder(journal=journal)
    rec.begin_cycle()
    rec.end_cycle()  # _prune walks snapshot_cycles; must not raise
    assert rec.last_cycle()["cycle"] == 3


def test_recorder_resumes_cycle_ids_from_journal(tmp_path):
    """A second recorder over the same journal directory appends after
    the newest recorded cycle instead of overwriting from 0."""
    journal = Journal(str(tmp_path))
    rec = TraceRecorder(journal=journal)
    for _ in range(3):
        rec.begin_cycle()
        rec.end_cycle()
    assert journal.cycles() == [0, 1, 2]

    rec2 = TraceRecorder(journal=Journal(str(tmp_path)))
    assert rec2.begin_cycle() == 3
    rec2.end_cycle()
    assert journal.cycles() == [0, 1, 2, 3]


def test_recorder_resumes_past_orphan_snapshot(tmp_path):
    """A crash between snapshot capture and end_cycle leaves an .npz
    with no .jsonl; the next run must not reuse that cycle id (replay
    would pair the stale snapshot with the new run's event log)."""
    journal = Journal(str(tmp_path))
    snap = generate_snapshot(n_tasks=8, n_nodes=4, seed=0)
    journal.write_snapshot(5, snap, np.zeros(8, dtype=np.int32))
    assert journal.last_cycle() is None  # no event logs at all

    rec = TraceRecorder(journal=journal)
    assert rec.begin_cycle() == 6


def test_journal_write_failure_does_not_raise(tmp_path):
    """Forensics must never break scheduling: a failing journal write
    (here: the root path is a file) is logged and swallowed, and the
    in-memory last_cycle record survives.  Same for snapshot capture,
    which runs inside the allocate action."""
    blocked = tmp_path / "not-a-dir"
    blocked.write_text("")
    rec = TraceRecorder(journal=Journal(str(blocked)), snapshot_every=1)
    rec.begin_cycle()
    rec.event("x")
    snap = generate_snapshot(n_tasks=8, n_nodes=4, seed=0)
    rec.capture(snap, np.zeros(8, dtype=np.int32))  # OSError swallowed
    rec.end_cycle(0.01)
    assert rec.last_cycle()["cycle"] == 0


def test_event_cap_bounds_buffer():
    """Events past max_events_per_cycle are dropped and counted — bounds
    memory when a process emits events without running the cycle loop."""
    rec = TraceRecorder()
    rec.max_events_per_cycle = 5
    rec.begin_cycle()
    for i in range(9):
        rec.event(f"e{i}")
    rec.end_cycle()
    record = rec.last_cycle()
    assert len(record["events"]) == 5
    assert record["n_dropped"] == 4


def test_crashed_open_session_cycle_is_journaled(tmp_path, monkeypatch):
    """A cycle that dies in open_session (plugin on_session_open is the
    likeliest site) still lands in the journal instead of leaving a
    cycle-id gap."""
    import volcano_tpu.scheduler.scheduler as sched_mod
    from volcano_tpu.scheduler.scheduler import Scheduler

    trace.enable(str(tmp_path))

    def boom(*args, **kwargs):
        raise RuntimeError("plugin open crashed")

    monkeypatch.setattr(sched_mod, "open_session", boom)
    with pytest.raises(RuntimeError, match="plugin open crashed"):
        Scheduler(_tiny_cluster_cache()).run_once()
    assert Journal(str(tmp_path)).cycles() == [0]


def test_snapshot_npz_roundtrip(tmp_path):
    snap = generate_snapshot(n_tasks=64, n_nodes=16, gang_size=4, seed=3)
    path = str(tmp_path / "snap.npz")
    save_snapshot(snap, path, assignment=np.arange(64, dtype=np.int32))
    loaded, extras = load_snapshot(path)

    assert loaded.n_tasks == snap.n_tasks
    assert loaded.n_nodes == snap.n_nodes
    assert loaded.n_jobs == snap.n_jobs
    assert loaded.resource_names == snap.resource_names
    assert loaded.task_uids == snap.task_uids
    assert loaded.node_names == snap.node_names
    assert loaded.memory_exact == snap.memory_exact
    np.testing.assert_array_equal(loaded.task_resreq, snap.task_resreq)
    np.testing.assert_array_equal(loaded.node_idle, snap.node_idle)
    np.testing.assert_array_equal(loaded.job_min_available, snap.job_min_available)
    np.testing.assert_array_equal(extras["assignment"], np.arange(64))


# ---- replay ----


def _record_one_cycle(tmp_path, executor="jax", n_tasks=128, n_nodes=32):
    journal = Journal(str(tmp_path))
    rec = TraceRecorder(journal=journal, snapshot_every=1)
    snap = generate_snapshot(
        n_tasks=n_tasks, n_nodes=n_nodes, gang_size=4, seed=7
    )
    rec.begin_cycle()
    assignment = run_snapshot(snap, executor=executor)
    rec.capture(snap, assignment, executor=executor)
    rec.end_cycle(duration_s=0.01)
    return journal, snap, assignment


def test_replay_verify_identical_jax(tmp_path):
    journal, _, _ = _record_one_cycle(tmp_path, executor="jax")
    result = verify(journal, executor="jax")
    assert result.match
    assert result.n_diffs == 0
    assert result.n_tasks == 128
    assert result.recorded_executor == "jax"
    assert "IDENTICAL" in result.summary()


def test_replay_verify_native_matches_recorded_jax(tmp_path):
    from volcano_tpu import native

    if native.load() is None:
        pytest.skip("native executor unavailable")
    journal, _, _ = _record_one_cycle(tmp_path, executor="jax")
    result = verify(journal, executor="native")
    assert result.match, result.diffs[:5]


def test_replay_flags_perturbed_snapshot(tmp_path):
    journal, snap, assignment = _record_one_cycle(tmp_path, executor="jax")
    # inject a perturbation: claim a different binding for one placed task
    tampered = np.asarray(assignment, dtype=np.int32).copy()
    placed = np.nonzero(tampered[: snap.n_tasks] >= 0)[0]
    idx = int(placed[0])
    tampered[idx] = (tampered[idx] + 1) % snap.n_nodes
    journal.write_snapshot(0, snap, tampered, executor="jax")

    result = verify(journal, executor="jax")
    assert not result.match
    assert result.n_diffs == 1
    task_idx, recorded_node, replayed_node = result.diffs[0]
    assert task_idx == idx
    assert recorded_node != replayed_node
    assert "DIFF" in result.summary()


def test_replay_uses_recorded_kernel_params(tmp_path):
    """A capture made with non-default weights/gang_rounds must replay
    with those same parameters, not the defaults."""
    from volcano_tpu.ops.kernels import ScoreWeights

    weights = ScoreWeights(binpack_weight=3.0, least_requested_weight=0.25)
    journal = Journal(str(tmp_path))
    rec = TraceRecorder(journal=journal, snapshot_every=1)
    snap = generate_snapshot(n_tasks=96, n_nodes=24, gang_size=4, seed=11)
    rec.begin_cycle()
    assignment = run_snapshot(snap, executor="jax", weights=weights, gang_rounds=5)
    rec.capture(snap, assignment, executor="jax", weights=weights, gang_rounds=5)
    rec.end_cycle()

    _, extras = journal.read_snapshot(0)
    lanes = [float(v) for v in np.asarray(extras["weights"]).ravel()]
    assert lanes[: len(ScoreWeights._fields) - 1] == [
        float(v) for v in tuple(weights)[:-1]
    ]
    assert int(extras["gang_rounds"]) == 5
    assert verify(journal, executor="jax").match


def test_replay_accepts_directory_path(tmp_path):
    _record_one_cycle(tmp_path, executor="jax")
    assert verify(str(tmp_path), executor="jax").match


def test_replay_without_snapshot_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        verify(str(tmp_path))


# ---- chrome export ----


def test_chrome_trace_schema(tmp_path):
    journal = Journal(str(tmp_path))
    rec = TraceRecorder(journal=journal)
    rec.begin_cycle()
    rec.event("instant", "cat")
    with rec.span("region", "action", detail="x"):
        pass
    rec.decision("bind", "t0", "n0")
    rec.end_cycle(duration_s=0.002)

    from volcano_tpu.trace.export import export_chrome_trace

    text = export_chrome_trace(journal, cycle=0)
    obj = json.loads(text)
    assert set(obj) == {"traceEvents", "displayTimeUnit", "metadata"}
    assert obj["metadata"]["cycle"] == 0
    assert obj["metadata"]["n_decisions"] == 1
    phases = {}
    for e in obj["traceEvents"]:
        assert {"name", "cat", "ph", "ts", "pid", "tid"} <= set(e)
        phases.setdefault(e["ph"], []).append(e)
    assert len(phases["X"]) == 1  # the span, with a duration
    assert "dur" in phases["X"][0]
    assert any(e["cat"] == "decision" for e in phases["i"])

    out = tmp_path / "trace.json"
    export_chrome_trace(journal, cycle=0, path=str(out))
    assert json.loads(out.read_text()) == obj


# ---- live scheduler cycle ----


def _tiny_cluster_cache():
    from tests.builders import build_node

    nodes = [build_node(f"n{i}", {"cpu": "8", "memory": "16Gi"}) for i in range(2)]
    pods = [
        build_pod("ns1", f"p{i}", "", {"cpu": "1", "memory": "1Gi"}, group="pg1")
        for i in range(3)
    ]
    pg = build_pod_group("ns1", "pg1", min_member=3, queue="q1")
    queue = build_queue("q1", weight=1)
    return make_cache(nodes=nodes, pods=pods, pod_groups=[pg], queues=[queue])


def test_scheduler_cycle_records_decisions(tmp_path):
    from volcano_tpu.scheduler.scheduler import Scheduler

    rec = trace.enable(str(tmp_path), snapshot_every=0)
    cache = _tiny_cluster_cache()
    Scheduler(cache).run_once()

    record = rec.last_cycle()
    assert record is not None and record["cycle"] == 0
    names = [e["name"] for e in record["events"]]
    assert "open_session" in names
    assert "close_session" in names
    assert any(n.startswith("action:") for n in names)
    assert any(n.startswith("plugin:") for n in names)
    binds = [d for d in record["decisions"] if d["kind"] == "bind"]
    assert len(binds) == 3  # the whole gang placed
    assert {d["node"] for d in binds} <= {"n0", "n1"}
    # journaled too
    assert Journal(str(tmp_path)).read_cycle(0)["decisions"]


def test_disabled_recording_changes_nothing():
    from volcano_tpu.scheduler.scheduler import Scheduler

    cache = _tiny_cluster_cache()
    Scheduler(cache).run_once()
    assert trace.get_recorder().last_cycle() is None
    assert len(cache.binder.binds) == 3


# ---- /trace/last endpoint ----


def test_trace_last_endpoint(tmp_path):
    from volcano_tpu.scheduler.scheduler import Scheduler
    from volcano_tpu.serving.http import ServingServer

    server = ServingServer().start()
    try:
        url = f"http://127.0.0.1:{server.port}/trace/last"
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(url)
        assert err.value.code == 404
        err.value.close()  # the HTTPError holds the response socket

        trace.enable(str(tmp_path))
        Scheduler(_tiny_cluster_cache()).run_once()

        with urllib.request.urlopen(url) as resp:
            assert resp.status == 200
            assert resp.headers["Content-Type"] == "application/json"
            obj = json.loads(resp.read())
        assert obj["metadata"]["cycle"] == 0
        assert obj["metadata"]["n_decisions"] == 3
        assert any(
            e["name"].startswith("action:") for e in obj["traceEvents"]
        )
    finally:
        server.stop()


# ---- vtctl trace CLI ----


def _vtctl(args):
    from volcano_tpu.cli.vtctl import main

    out = io.StringIO()
    rc = main(args, out=out)
    return rc, out.getvalue()


def test_vtctl_trace_end_to_end(tmp_path):
    d = str(tmp_path / "journal")
    rc, text = _vtctl(
        ["trace", "record", "--dir", d, "--tasks", "64", "--nodes", "16",
         "--cycles", "2", "--snapshot-every", "1"]
    )
    assert rc == 0, text
    assert "recorded 2 cycle(s)" in text

    rc, text = _vtctl(["trace", "replay", "--dir", d, "--executor", "jax"])
    assert rc == 0, text
    assert "IDENTICAL" in text

    rc, text = _vtctl(["trace", "diff", "--dir", d, "--cycle", "0"])
    assert rc == 0, text

    out_file = str(tmp_path / "chrome.json")
    rc, text = _vtctl(["trace", "export", "--dir", d, "--out", out_file])
    assert rc == 0, text
    obj = json.loads(open(out_file).read())
    assert obj["traceEvents"]


def test_vtctl_trace_diff_reports_perturbation(tmp_path):
    d = str(tmp_path / "journal")
    rc, _ = _vtctl(
        ["trace", "record", "--dir", d, "--tasks", "64", "--nodes", "16"]
    )
    assert rc == 0
    journal = Journal(d)
    snap, extras = journal.read_snapshot(0)
    tampered = np.asarray(extras["assignment"], dtype=np.int32).copy()
    tampered[0] = (tampered[0] + 1) % snap.n_nodes
    journal.write_snapshot(0, snap, tampered, executor="jax")

    rc, text = _vtctl(["trace", "diff", "--dir", d])
    assert rc == 1
    assert "task[0]" in text


# ---- satellite regressions (this PR) ----


def test_cascade_delete_spares_recreated_child():
    """apiserver cascade must re-verify ownership: a child deleted and
    re-created under the same key with a different controller must
    survive the old owner's cascade (the k8s GC matches by UID)."""
    from volcano_tpu.apis import core
    from volcano_tpu.client import APIServer

    api = APIServer()

    def make_job(uid):
        return core.ConfigMap(  # any kinded object works; use two kinds
            metadata=core.ObjectMeta(name="owner", namespace="d", uid=uid)
        )

    def make_child(owner_uid):
        return core.Pod(
            metadata=core.ObjectMeta(
                name="child",
                namespace="d",
                uid=f"pod-of-{owner_uid}",
                owner_references=[
                    core.OwnerReference(
                        kind="ConfigMap", name="owner", uid=owner_uid,
                        controller=True,
                    )
                ],
            )
        )

    api.create(make_job("uid-1"))
    api.create(make_child("uid-1"))
    # child deleted directly, then re-created under the SAME key but
    # owned by a NEW incarnation of the owner
    api.delete("Pod", "d", "child")
    api.create(make_child("uid-2"))
    api.delete("ConfigMap", "d", "owner")
    # stale _owned entry must not take the new child down
    assert api.get("Pod", "d", "child") is not None
    # the new incarnation's cascade still works
    api.create(make_job("uid-2"))
    api.delete("ConfigMap", "d", "owner")
    assert api.get("Pod", "d", "child") is None


def test_frozen_resource_rejects_inplace_mutation():
    from volcano_tpu.api.job_info import new_task_info
    from volcano_tpu.api.resource import Resource

    pod = build_pod("ns1", "p0", "", {"cpu": "1", "memory": "1Gi"})
    task = new_task_info(pod)
    delta = Resource(milli_cpu=100.0)
    for mutator in (task.resreq.add, task.resreq.sub_unchecked,
                    task.resreq.set_max, task.init_resreq.add):
        with pytest.raises(AssertionError):
            mutator(delta)
    # a clone is mutable again, and aliases stay shared across task clones
    task.resreq.clone().add(delta)
    assert task.clone().resreq is task.resreq


def test_admission_volume_names():
    from volcano_tpu.admission.jobs import _validate_task_template
    from volcano_tpu.apis import batch, core

    def job_task(volumes, mounts=()):
        return batch.TaskSpec(
            name="t",
            replicas=1,
            template=core.PodTemplateSpec(
                spec=core.PodSpec(
                    containers=[
                        core.Container(
                            name="c",
                            volume_mounts=[
                                core.VolumeMount(name=n, mount_path=f"/m{i}")
                                for i, n in enumerate(mounts)
                            ],
                        )
                    ],
                    volumes=[core.Volume(name=n) for n in volumes],
                )
            ),
        )

    # two unnamed volumes: flagged once each as invalid, NOT as duplicates
    msgs = _validate_task_template(job_task(["", ""]), 0)
    assert sum("DNS-1123" in m for m in msgs) == 2
    assert not any("duplicate volume name" in m for m in msgs)
    # a mount referencing the invalid name is NOT treated as declared
    msgs = _validate_task_template(job_task([""], mounts=[""]), 0)
    assert any("not declared" in m for m in msgs)
    # valid duplicates still flagged exactly once
    msgs = _validate_task_template(job_task(["vol", "vol"]), 0)
    assert sum("duplicate volume name" in m for m in msgs) == 1
