"""VBUS v6 ``txn_commit`` — atomic multi-object transactions (ISSUE 11).

The cross-shard gang-assembly primitive: N conditional binds
(``cas_bind`` semantics) checked and applied all-or-nothing under ONE
store lock hold.  Pins:

* **Atomicity** — every precondition is evaluated before any effect;
  one stale claim aborts the whole transaction with per-item results
  (the caller learns exactly which claim went stale) and ZERO binds
  land.
* **Wire parity** — the in-process, ``--bus``, and old-peer paths
  agree; a pre-v6 server degrades the client to an ABORT (reported
  ``unsupported``), never a per-object replay — version skew costs the
  feature, never the no-partial-gang invariant.
* **Durability** — on a persistent store the whole transaction is ONE
  WAL record (riding the atomic ``commit_batch`` path): recovery
  replays it whole, an aborted transaction logs nothing, and a WAL
  write failure rolls every in-memory bind back before the caller sees
  the error.
* **Replication** — the record ships to followers as a unit, so every
  replica holds the gang whole or not at all.
"""

import socket
import time

import pytest

from volcano_tpu import faults
from volcano_tpu.apis import core
from volcano_tpu.bus import protocol
from volcano_tpu.bus.remote import RemoteAPIServer
from volcano_tpu.bus.replication import ReplicaManager
from volcano_tpu.bus.server import BusServer
from volcano_tpu.bus.wal import (
    WAL_FILE,
    PersistentAPIServer,
    WalError,
    read_records,
)
from volcano_tpu.client import APIServer
from volcano_tpu.client.apiserver import ApiError


def _wait(pred, timeout=10.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _pod(name, ns="ns"):
    return core.Pod(
        metadata=core.ObjectMeta(name=name, namespace=ns),
        spec=core.PodSpec(
            containers=[core.Container(name="c", image="img")]
        ),
        status=core.PodStatus(phase="Pending"),
    )


def _binds(api, names, hosts=None):
    """Bind items stamped with each pod's CURRENT resourceVersion —
    the broker's read-back discipline."""
    out = []
    for i, name in enumerate(names):
        pod = api.get("Pod", "ns", name)
        out.append({
            "namespace": "ns", "name": name,
            "hostname": (hosts or {}).get(name, f"n{i}"),
            "expected_rv": pod.metadata.resource_version,
        })
    return out


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.configure(None)
    yield
    faults.configure(None)


class TestTxnCommitInProcess:
    def test_commits_all_under_one_transaction(self):
        api = APIServer()
        for i in range(4):
            api.create(_pod(f"p{i}"))
        result = api.txn_commit(_binds(api, [f"p{i}" for i in range(4)]))
        assert result["committed"] is True
        assert result["results"] == [None] * 4
        assert [o.spec.node_name for o in result["objects"]] == [
            "n0", "n1", "n2", "n3"
        ]
        for i in range(4):
            assert api.get("Pod", "ns", f"p{i}").spec.node_name == f"n{i}"

    def test_one_stale_claim_aborts_all_with_per_item_results(self):
        """The load-bearing atomicity pin: a single already-bound member
        aborts the WHOLE transaction — zero binds land — and the
        results name exactly the stale item, so the broker can discard
        the assembly and retry against fresh truth."""
        api = APIServer()
        for name in ("a", "b", "c"):
            api.create(_pod(name))
        binds = _binds(api, ["a", "b", "c"])
        api.cas_bind("ns", "b", "raced-elsewhere")  # the foreign winner
        result = api.txn_commit(binds)
        assert result["committed"] is False
        assert result["objects"] == []
        assert result["results"][0] is None
        assert "Conflict" in result["results"][1]
        assert result["results"][2] is None
        # preconditions are swept, not short-circuited — and nothing
        # bound: the state a partially-applied gang would corrupt
        assert api.get("Pod", "ns", "a").spec.node_name == ""
        assert api.get("Pod", "ns", "c").spec.node_name == ""

    def test_stale_resource_version_aborts(self):
        api = APIServer()
        api.create(_pod("a"))
        api.create(_pod("b"))
        binds = _binds(api, ["a", "b"])
        touched = api.get("Pod", "ns", "b")
        touched.metadata.labels["x"] = "y"
        api.update(touched)  # rv moves, pod still unbound
        result = api.txn_commit(binds)
        assert result["committed"] is False
        assert "resourceVersion" in result["results"][1]
        assert api.get("Pod", "ns", "a").spec.node_name == ""

    def test_missing_member_aborts(self):
        api = APIServer()
        api.create(_pod("a"))
        result = api.txn_commit([
            {"namespace": "ns", "name": "a", "hostname": "n0"},
            {"namespace": "ns", "name": "ghost", "hostname": "n1"},
        ])
        assert result["committed"] is False
        assert "NotFound" in result["results"][1]
        assert api.get("Pod", "ns", "a").spec.node_name == ""

    def test_duplicate_claims_for_one_pod_abort(self):
        """Two claims for the same pod in one transaction abort: the
        sequential cas_bind equivalent would conflict on the second,
        and committing last-write-wins would let a buggy planner
        believe two gang slots landed when one did."""
        api = APIServer()
        api.create(_pod("a"))
        api.create(_pod("dup"))
        result = api.txn_commit([
            {"namespace": "ns", "name": "a", "hostname": "n0"},
            {"namespace": "ns", "name": "dup", "hostname": "n1"},
            {"namespace": "ns", "name": "dup", "hostname": "n2"},
        ])
        assert result["committed"] is False
        assert result["results"][0] is None
        assert result["results"][1] is None
        assert "duplicate claim" in result["results"][2]
        assert api.get("Pod", "ns", "a").spec.node_name == ""
        assert api.get("Pod", "ns", "dup").spec.node_name == ""

    def test_missing_hostname_aborts_before_any_effect(self):
        """A malformed item (no hostname — the wire hands client
        payloads straight to the store) must abort in the precondition
        SWEEP: failing in the apply loop would land earlier binds
        first, creating a durable partial gang."""
        api = APIServer()
        api.create(_pod("a"))
        api.create(_pod("b"))
        result = api.txn_commit([
            {"namespace": "ns", "name": "a", "hostname": "n0"},
            {"namespace": "ns", "name": "b"},
        ])
        assert result["committed"] is False
        assert result["results"][0] is None
        assert "hostname" in result["results"][1]
        assert api.get("Pod", "ns", "a").spec.node_name == ""
        assert api.get("Pod", "ns", "b").spec.node_name == ""

    def test_empty_transaction_commits_trivially(self):
        result = APIServer().txn_commit([])
        assert result == {"committed": True, "results": [], "objects": []}


class TestTxnCommitOverBus:
    def test_wire_parity_commit_and_abort(self):
        api = APIServer()
        srv = BusServer(api).start()
        client = RemoteAPIServer(f"tcp://127.0.0.1:{srv.port}", timeout=5)
        try:
            assert client.wait_ready(5)
            for name in ("a", "b"):
                client.create(_pod(name))
            result = client.txn_commit(_binds(api, ["a", "b"]))
            assert result["committed"] is True
            assert [o.spec.node_name for o in result["objects"]] == [
                "n0", "n1"
            ]
            assert api.get("Pod", "ns", "a").spec.node_name == "n0"
            # abort parity: stale claims come back per-item, zero binds
            for name in ("c", "d"):
                client.create(_pod(name))
            binds = _binds(api, ["c", "d"])
            api.cas_bind("ns", "d", "raced")
            result = client.txn_commit(binds)
            assert result["committed"] is False
            assert result["results"][0] is None
            assert "Conflict" in result["results"][1]
            assert api.get("Pod", "ns", "c").spec.node_name == ""
        finally:
            client.close()
            srv.stop()

    def test_old_server_aborts_never_partial(self, monkeypatch):
        """A pre-v6 server answers ``unknown bus op`` — the client
        degrades PERMANENTLY (per connection) to an ABORT with every
        item marked unsupported.  There is deliberately NO per-object
        fallback: a replay of single binds could die halfway and strand
        a partial gang, the exact state the op exists to forbid."""
        real_execute = BusServer._execute

        def v5_execute(self, conn, req_id, payload, op):
            if op == "txn_commit":
                raise ApiError("unknown bus op 'txn_commit'")
            return real_execute(self, conn, req_id, payload, op)

        monkeypatch.setattr(BusServer, "_execute", v5_execute)
        api = APIServer()
        srv = BusServer(api).start()
        client = RemoteAPIServer(f"tcp://127.0.0.1:{srv.port}", timeout=5)
        try:
            assert client.wait_ready(5)
            for name in ("a", "b"):
                client.create(_pod(name))
            result = client.txn_commit(_binds(api, ["a", "b"]))
            assert result["committed"] is False
            assert result["reason"] == "unsupported"
            assert len(result["results"]) == 2
            assert all("unsupported" in r for r in result["results"])
            assert client._no_txn_commit is True
            # and NOTHING bound — no partial replay happened
            assert api.get("Pod", "ns", "a").spec.node_name == ""
            assert api.get("Pod", "ns", "b").spec.node_name == ""
        finally:
            client.close()
            srv.stop()


class TestTxnCommitDurability:
    def test_whole_transaction_is_one_wal_record_replayed_whole(
        self, tmp_path
    ):
        data_dir = str(tmp_path / "wal")
        api = PersistentAPIServer(data_dir, snapshot_every=10_000)
        try:
            for i in range(3):
                api.create(_pod(f"p{i}"))
            wal = str(tmp_path / "wal" / WAL_FILE)
            before = len(read_records(wal)[0])
            result = api.txn_commit(_binds(api, ["p0", "p1", "p2"]))
            assert result["committed"] is True
            records = read_records(wal)[0]
            assert len(records) == before + 1, (
                "the gang must be ONE atomic record, not one per bind"
            )
            last = protocol.decode_record(records[-1])
            assert len(last["events"]) == 3
            assert all(e[1] == "MODIFIED" for e in last["events"])
        finally:
            api.close()
        # recovery replays the record whole: all three bound
        recovered = PersistentAPIServer(data_dir, snapshot_every=10_000)
        try:
            for i in range(3):
                pod = recovered.get("Pod", "ns", f"p{i}")
                assert pod.spec.node_name == f"n{i}"
        finally:
            recovered.close()

    def test_abort_logs_nothing(self, tmp_path):
        api = PersistentAPIServer(str(tmp_path / "wal"),
                                  snapshot_every=10_000)
        try:
            api.create(_pod("a"))
            api.create(_pod("b"))
            binds = _binds(api, ["a", "b"])
            api.cas_bind("ns", "b", "raced")
            wal = str(tmp_path / "wal" / WAL_FILE)
            before = len(read_records(wal)[0])
            result = api.txn_commit(binds)
            assert result["committed"] is False
            assert len(read_records(wal)[0]) == before
            assert api.get("Pod", "ns", "a").spec.node_name == ""
        finally:
            api.close()

    def test_wal_write_failure_rolls_back_every_bind(self, tmp_path):
        """The crash shape in between: the transaction's record never
        became durable, so the op is NOT acked and the in-memory binds
        are rolled back — a reader can never observe a gang a restart
        would erase (half or whole)."""
        api = PersistentAPIServer(str(tmp_path / "wal"),
                                  snapshot_every=10_000)
        try:
            for name in ("a", "b"):
                api.create(_pod(name))
            binds = _binds(api, ["a", "b"])
            faults.configure("seed=1;wal.write_fail=1:count=1")
            with pytest.raises(WalError):
                api.txn_commit(binds)
            faults.configure(None)
            assert api.get("Pod", "ns", "a").spec.node_name == ""
            assert api.get("Pod", "ns", "b").spec.node_name == ""
            # the store is healthy again: the same transaction commits
            result = api.txn_commit(_binds(api, ["a", "b"]))
            assert result["committed"] is True
        finally:
            api.close()


class TestTxnCommitReplication:
    def test_gang_is_one_atomic_record_on_every_replica(self, tmp_path):
        """3-replica group: a txn_commit issued through a FOLLOWER
        connection (proxied to the leader) lands on every replica as
        one record carrying all the binds — no replica can ever hold
        half the gang, which is what makes the gang survive failover
        whole."""
        ports = [_free_port() for _ in range(3)]
        endpoints = [f"tcp://127.0.0.1:{p}" for p in ports]
        replicas = []
        for i in range(3):
            store = PersistentAPIServer(str(tmp_path / f"r{i}"),
                                        snapshot_every=10_000)
            mgr = ReplicaManager(store, endpoints, i, lease_ttl=1.0)
            bus = BusServer(store, port=ports[i], replica=mgr)
            bus.start()
            mgr.start()
            replicas.append((store, mgr, bus))
        cli = None
        try:
            def roles():
                return [m.role for _s, m, _b in replicas]

            assert _wait(
                lambda: roles().count("leader") == 1
                and roles().count("follower") == 2,
                timeout=20.0,
            ), roles()
            fidx = next(i for i, (_s, m, _b) in enumerate(replicas)
                        if m.role == "follower")
            cli = RemoteAPIServer(endpoints[fidx], timeout=10)
            assert cli.wait_ready(10)
            for name in ("g0", "g1", "g2"):
                cli.create(_pod(name))
            binds = []
            for i, name in enumerate(("g0", "g1", "g2")):
                pod = cli.get("Pod", "ns", name)
                binds.append({
                    "namespace": "ns", "name": name, "hostname": f"n{i}",
                    "expected_rv": pod.metadata.resource_version,
                })
            result = cli.txn_commit(binds)
            assert result["committed"] is True, result

            def all_replicated():
                for store, _m, _b in replicas:
                    for i in range(3):
                        pod = store.get("Pod", "ns", f"g{i}")
                        if pod is None or pod.spec.node_name != f"n{i}":
                            return False
                return True

            assert _wait(all_replicated, timeout=10.0), (
                "gang did not replicate whole"
            )
            # the transaction is one record in every replica's WAL
            for i in range(3):
                wal = str(tmp_path / f"r{i}" / WAL_FILE)
                gang_records = [
                    rec for rec in (
                        protocol.decode_record(p)
                        for p in read_records(wal)[0]
                    )
                    if any(
                        (e[3] or {}).get("metadata", {}).get("name")
                        == "g0"
                        and e[1] == "MODIFIED"
                        # membership-config records (the elected
                        # leader's seed) carry no store events
                        for e in rec.get("events", ())
                    )
                ]
                assert len(gang_records) == 1, (
                    f"replica {i}: gang bind spread over "
                    f"{len(gang_records)} records"
                )
                assert len(gang_records[0]["events"]) == 3
        finally:
            if cli is not None:
                cli.close()
            for _store, mgr, bus in replicas:
                try:
                    mgr.stop()
                    bus.stop()
                    _store.close()
                except Exception:  # noqa: BLE001 — teardown best-effort
                    pass


class TestOpRegistry:
    def test_txn_commit_is_version_registered(self):
        """The PR 7 machine-checked rule's anchor: the op is declared at
        v6 and the protocol speaks v6."""
        from volcano_tpu.bus import protocol

        assert protocol.OP_VERSIONS["txn_commit"] == 6
        assert protocol.VERSION >= 6
