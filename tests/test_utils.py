"""Priority queue, serde, and quantity tests."""

from volcano_tpu.apis import core, serde
from volcano_tpu.apis.batch import Job, JobSpec, TaskSpec
from volcano_tpu.apis.quantity import parse_quantity
from volcano_tpu.utils import PriorityQueue


class TestPriorityQueue:
    def test_orders_by_less_fn(self):
        pq = PriorityQueue(lambda a, b: a < b)
        for x in [5, 1, 3]:
            pq.push(x)
        assert [pq.pop(), pq.pop(), pq.pop()] == [1, 3, 5]

    def test_stable_for_equal_items(self):
        pq = PriorityQueue(lambda a, b: False)  # everything equal
        for x in ["a", "b", "c"]:
            pq.push(x)
        assert [pq.pop(), pq.pop(), pq.pop()] == ["a", "b", "c"]

    def test_empty_pop_returns_none(self):
        pq = PriorityQueue(lambda a, b: a < b)
        assert pq.empty()
        assert pq.pop() is None


class TestQuantity:
    def test_suffixes(self):
        assert parse_quantity("100m") == 0.1
        assert parse_quantity("1Gi") == 1024**3
        assert parse_quantity("2k") == 2000
        assert parse_quantity(3) == 3.0
        assert parse_quantity("1.5") == 1.5


class TestSerde:
    def test_pod_round_trip(self):
        pod = core.Pod(
            metadata=core.ObjectMeta(name="p1", namespace="ns", labels={"a": "b"}),
            spec=core.PodSpec(
                containers=[core.Container(resources={"requests": {"cpu": "1"}})],
                node_selector={"disk": "ssd"},
                tolerations=[core.Toleration(key="k", effect="NoSchedule")],
            ),
        )
        data = pod.to_dict()
        assert data["kind"] == "Pod"
        assert data["spec"]["nodeSelector"] == {"disk": "ssd"}
        back = core.Pod.from_dict(data)
        assert back.metadata.name == "p1"
        assert back.spec.tolerations[0].key == "k"
        assert back.spec.containers[0].resources["requests"]["cpu"] == "1"

    def test_camel_case_input(self):
        job = Job.from_dict(
            {
                "metadata": {"name": "j", "namespace": "ns"},
                "spec": {
                    "minAvailable": 3,
                    "tasks": [{"name": "worker", "replicas": 3}],
                    "maxRetry": 5,
                },
            }
        )
        assert job.spec.min_available == 3
        assert job.spec.tasks[0].replicas == 3
        assert job.spec.max_retry == 5

    def test_clone_is_deep(self):
        job = Job(spec=JobSpec(tasks=[TaskSpec(name="t", replicas=1)]))
        c = job.clone()
        c.spec.tasks[0].replicas = 9
        assert job.spec.tasks[0].replicas == 1
