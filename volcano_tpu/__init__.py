"""volcano_tpu — a TPU-native batch-scheduling framework.

A ground-up rebuild of the capabilities of Volcano (kube-batch): gang
scheduling, multi-queue weighted fair share, DRF, priority /
preemption / reclaim / backfill, a Job CRD with a lifecycle state
machine and distributed-job plugins, admission webhooks, and a CLI.

The control plane is an event-driven host layer (see
``volcano_tpu.controllers``, ``volcano_tpu.scheduler``); the per-session
O(tasks × nodes) predicate/score/assign hot path is packed into device
tensors and executed as JAX/XLA kernels on TPU (``volcano_tpu.ops``),
sharded over a device mesh for large sessions (``volcano_tpu.parallel``).

Layer map (mirrors the reference architecture, re-designed TPU-first):

- ``volcano_tpu.apis``       — self-contained Kubernetes-style object model
                               (reference: pkg/apis + core k8s types).
- ``volcano_tpu.client``     — in-memory API server, informers, listers
                               (reference: pkg/client).
- ``volcano_tpu.api``        — the scheduler's internal pure data model
                               (reference: pkg/scheduler/api).
- ``volcano_tpu.ops``        — device kernels: snapshot packing, predicate
                               masks, scoring, greedy gang assignment.
- ``volcano_tpu.parallel``   — mesh/sharding for multi-chip sessions.
- ``volcano_tpu.framework``  — session, statement, plugin/action registries
                               (reference: pkg/scheduler/framework).
- ``volcano_tpu.actions``    — enqueue/allocate/backfill/preempt/reclaim.
- ``volcano_tpu.plugins``    — gang/drf/proportion/priority/predicates/
                               nodeorder/binpack/conformance/task-topology.
- ``volcano_tpu.scheduler``  — cache, session loop, metrics.
- ``volcano_tpu.controllers``— job/queue/podgroup/gc controllers.
- ``volcano_tpu.admission``  — validating/mutating webhook handlers.
- ``volcano_tpu.cli``        — ``vtctl``.
"""

__version__ = "0.1.0"
