"""Action registry — mirrors the blank-import registration in
cmd/scheduler/main.go:36-38."""

from volcano_tpu.framework.interface import register_action

from volcano_tpu.actions import (
    allocate,
    backfill,
    enqueue,
    jax_allocate,
    jax_preempt,
    jax_reclaim,
    preempt,
    reclaim,
)


def register_all() -> None:
    register_action(enqueue.new())
    register_action(allocate.new())
    register_action(backfill.new())
    register_action(preempt.new())
    register_action(reclaim.new())
    register_action(jax_allocate.new())
    register_action(jax_preempt.new())
    register_action(jax_reclaim.new())


register_all()
