"""Allocate action — THE hot path: namespace→queue→job→task nested
priority queues; per task predicate→prioritize→select→allocate/pipeline;
gang commit/discard at job granularity.

Reference: pkg/scheduler/actions/allocate/allocate.go:42-241.

The host path below preserves reference semantics exactly.  When a device
backend is attached (see volcano_tpu.actions.jax_allocate), the per-task
predicate+score loop is replaced by the fused TPU kernel; results are
applied through the same Statement so gang semantics and plugin event
handlers stay intact.
"""

from __future__ import annotations

from typing import Dict

from volcano_tpu.api import FitError, TaskInfo, TaskStatus
from volcano_tpu.api import unschedule_info as reasons
from volcano_tpu.apis import scheduling
from volcano_tpu.framework.interface import Action
from volcano_tpu.framework.session import Session
from volcano_tpu.scheduler import util as sched_util
from volcano_tpu.utils.priority_queue import PriorityQueue


class AllocateAction(Action):
    def name(self) -> str:
        return "allocate"

    def execute(self, ssn: Session) -> None:
        namespaces = PriorityQueue(ssn.namespace_order_fn)
        # namespace -> queue uid -> PriorityQueue of jobs (allocate.go:56-58)
        jobs_map: Dict[str, Dict[str, PriorityQueue]] = {}

        for job in sorted(ssn.jobs.values(), key=lambda j: j.uid):
            if (
                job.pod_group is not None
                and job.pod_group.status.phase == scheduling.POD_GROUP_PENDING
            ):
                continue
            vr = ssn.job_valid(job)
            if vr is not None and not vr.pass_:
                continue
            if job.queue not in ssn.queues:
                continue

            namespace = job.namespace
            queue_map = jobs_map.get(namespace)
            if queue_map is None:
                namespaces.push(namespace)
                queue_map = {}
                jobs_map[namespace] = queue_map
            queue_map.setdefault(job.queue, PriorityQueue(ssn.job_order_fn)).push(job)

        pending_tasks: Dict[str, PriorityQueue] = {}
        all_nodes = sched_util.get_node_list(ssn.nodes)

        def predicate_fn(task: TaskInfo, node) -> None:
            """Resource-fit check prepended to plugin predicates
            (allocate.go:100-107)."""
            if not task.init_resreq.less_equal(node.future_idle()):
                raise FitError(task, node, reasons.NODE_RESOURCE_FIT_FAILED)
            ssn.predicate_fn(task, node)

        while not namespaces.empty():
            namespace = namespaces.pop()
            queue_in_namespace = jobs_map[namespace]

            # Pick the least-share non-overused queue (allocate.go:128-145).
            queue = None
            for queue_id in list(queue_in_namespace):
                current_queue = ssn.queues[queue_id]
                if ssn.overused(current_queue):
                    del queue_in_namespace[queue_id]
                    continue
                if queue is None or ssn.queue_order_fn(current_queue, queue):
                    queue = current_queue
            if queue is None:
                continue

            jobs = queue_in_namespace.get(queue.uid)
            if jobs is None or jobs.empty():
                continue

            job = jobs.pop()
            if job.uid not in pending_tasks:
                tasks = PriorityQueue(ssn.task_order_fn)
                for task in sorted(
                    job.task_status_index.get(TaskStatus.Pending, {}).values(),
                    key=lambda t: t.uid,
                ):
                    # Skip BestEffort tasks in allocate (allocate.go:158-167).
                    if task.resreq.is_empty():
                        continue
                    tasks.push(task)
                pending_tasks[job.uid] = tasks
            tasks = pending_tasks[job.uid]

            stmt = ssn.statement()

            while not tasks.empty():
                task = tasks.pop()

                # Stale fit-delta reset (allocate.go:187-189).
                if job.nodes_fit_delta:
                    job.nodes_fit_delta = {}

                predicate_nodes, fit_errors = sched_util.predicate_nodes(
                    task, all_nodes, predicate_fn
                )
                if not predicate_nodes:
                    job.nodes_fit_errors[task.uid] = fit_errors
                    break

                node_scores = sched_util.prioritize_nodes(
                    task,
                    predicate_nodes,
                    ssn.batch_node_order_fn,
                    ssn.node_order_map_fn,
                    ssn.node_order_reduce_fn,
                )
                node = sched_util.select_best_node(node_scores)
                if node is None:
                    break

                if task.init_resreq.less_equal(node.idle):
                    # Fits in idle → allocate (allocate.go:201-207).
                    stmt.allocate(task, node.name)
                else:
                    # Record shortfall, then pipeline onto future idle
                    # (allocate.go:208-224).
                    delta = node.idle.clone()
                    delta.fit_delta(task.init_resreq)
                    job.nodes_fit_delta[node.name] = delta
                    if task.init_resreq.less_equal(node.future_idle()):
                        stmt.pipeline(task, node.name)

                if ssn.job_ready(job):
                    jobs.push(job)
                    break

            if ssn.job_ready(job):
                stmt.commit()
            else:
                stmt.discard()

            namespaces.push(namespace)


def new() -> AllocateAction:
    return AllocateAction()
