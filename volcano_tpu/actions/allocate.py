"""Allocate action — THE hot path: namespace→queue→job→task nested
priority queues; per task predicate→prioritize→select→allocate/pipeline;
gang commit/discard at job granularity.

Reference: pkg/scheduler/actions/allocate/allocate.go:42-241.

``drive_allocate_loop`` is the single copy of the control-flow skeleton;
it is shared by the host action below, the device-backed
jax-allocate action, and its order-replay phase (actions/jax_allocate.py),
so the replay-order == host-order premise cannot drift.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

from volcano_tpu.api import FitError, JobInfo, NodeInfo, TaskInfo, TaskStatus
from volcano_tpu.api import unschedule_info as reasons
from volcano_tpu.apis import scheduling
from volcano_tpu.framework.interface import Action
from volcano_tpu.framework.session import Session
from volcano_tpu.metrics import metrics
from volcano_tpu.scheduler import util as sched_util
from volcano_tpu.utils.priority_queue import PriorityQueue


def eligible_jobs(ssn: Session):
    """Jobs allocate considers (allocate.go:60-92): not PodGroupPending,
    valid, and in a known queue.  Sorted by uid for determinism (the Go
    map iteration is random; bindings equivalence needs a fixed order)."""
    for job in sorted(ssn.jobs.values(), key=lambda j: j.uid):
        if (
            job.pod_group is not None
            and job.pod_group.status.phase == scheduling.POD_GROUP_PENDING
        ):
            continue
        vr = ssn.job_valid(job)
        if vr is not None and not vr.pass_:
            continue
        if job.queue not in ssn.queues:
            continue
        yield job


def build_pending_task_queue(ssn: Session, job: JobInfo) -> PriorityQueue:
    """Pending, non-best-effort tasks by TaskOrderFn (allocate.go:156-169)."""
    tasks = PriorityQueue(ssn.task_order_fn)
    for task in sorted(
        job.task_status_index.get(TaskStatus.Pending, {}).values(),
        key=lambda t: t.uid,
    ):
        if task.resreq.is_empty():
            continue
        tasks.push(task)
    return tasks


def drive_allocate_loop(
    ssn: Session,
    begin_job: Callable[[JobInfo], object],
    place_task: Callable[[object, TaskInfo, JobInfo], bool],
    end_job: Callable[[object, JobInfo], None],
) -> None:
    """The namespace→queue→job→task skeleton (allocate.go:112-240).

    ``place_task(ctx, task, job)`` returns False to stop the job's task
    loop (the reference's break on predicate failure)."""
    namespaces = PriorityQueue(ssn.namespace_order_fn)
    jobs_map: Dict[str, Dict[str, PriorityQueue]] = {}

    for job in eligible_jobs(ssn):
        queue_map = jobs_map.get(job.namespace)
        if queue_map is None:
            namespaces.push(job.namespace)
            queue_map = {}
            jobs_map[job.namespace] = queue_map
        queue_map.setdefault(job.queue, PriorityQueue(ssn.job_order_fn)).push(job)

    pending_tasks: Dict[str, PriorityQueue] = {}

    while not namespaces.empty():
        namespace = namespaces.pop()
        queue_in_namespace = jobs_map[namespace]

        # Least-share non-overused queue, linear scan because shares move
        # as allocations land (allocate.go:122-145).
        queue = None
        for queue_id in list(queue_in_namespace):
            current_queue = ssn.queues[queue_id]
            if ssn.overused(current_queue):
                del queue_in_namespace[queue_id]
                continue
            if queue is None or ssn.queue_order_fn(current_queue, queue):
                queue = current_queue
        if queue is None:
            continue

        jobs = queue_in_namespace.get(queue.uid)
        if jobs is None or jobs.empty():
            continue

        job = jobs.pop()
        if job.uid not in pending_tasks:
            pending_tasks[job.uid] = build_pending_task_queue(ssn, job)
        tasks = pending_tasks[job.uid]

        # the loop body may write fit errors/deltas onto the job clone
        # even when nothing places — conservatively touched
        ssn.touched_jobs.add(job.uid)
        ctx = begin_job(job)

        while not tasks.empty():
            task = tasks.pop()
            if not place_task(ctx, task, job):
                break
            if ssn.job_ready(job):
                jobs.push(job)
                break

        end_job(ctx, job)
        namespaces.push(namespace)


def make_predicate_fn(ssn: Session):
    """Resource-fit check prepended to plugin predicates
    (allocate.go:100-107)."""

    def predicate_fn(task: TaskInfo, node: NodeInfo) -> None:
        if not task.init_resreq.less_equal(node.future_idle()):
            raise FitError(task, node, reasons.NODE_RESOURCE_FIT_FAILED)
        ssn.predicate_fn(task, node)

    return predicate_fn


def host_node_chooser(ssn: Session):
    """The reference per-task path: PredicateNodes → PrioritizeNodes →
    SelectBestNode (allocate.go:191-199)."""
    all_nodes = sched_util.get_node_list(ssn.nodes)
    predicate_fn = make_predicate_fn(ssn)

    def choose(task: TaskInfo, job: JobInfo) -> Optional[NodeInfo]:
        predicate_nodes, fit_errors = sched_util.predicate_nodes(
            task, all_nodes, predicate_fn
        )
        if not predicate_nodes:
            job.nodes_fit_errors[task.uid] = fit_errors
            for reason in fit_errors.histogram():
                metrics.register_unschedulable_reason(reason)
            return None
        node_scores = sched_util.prioritize_nodes(
            task,
            predicate_nodes,
            ssn.batch_node_order_fn,
            ssn.node_order_map_fn,
            ssn.node_order_reduce_fn,
        )
        return sched_util.select_best_node(node_scores)

    return choose


def make_place_task(ssn: Session, chooser):
    """Per-task body shared by allocate and jax-allocate
    (allocate.go:177-230): reset fit-delta, choose node, allocate into
    idle or pipeline onto future idle."""

    def place_task(stmt, task: TaskInfo, job: JobInfo) -> bool:
        if job.nodes_fit_delta:
            job.nodes_fit_delta = {}

        node = chooser(task, job)
        if node is None:
            return False

        if task.init_resreq.less_equal(node.idle):
            stmt.allocate(task, node.name)
        else:
            delta = node.idle.clone()
            delta.fit_delta(task.init_resreq)
            job.nodes_fit_delta[node.name] = delta
            if task.init_resreq.less_equal(node.future_idle()):
                stmt.pipeline(task, node.name)
        return True

    return place_task


def gang_end_job(ssn: Session):
    """Commit when the gang is ready, discard otherwise
    (allocate.go:232-236)."""

    def end_job(stmt, job: JobInfo) -> None:
        if ssn.job_ready(job):
            stmt.commit()
        else:
            stmt.discard()

    return end_job


class AllocateAction(Action):
    def name(self) -> str:
        return "allocate"

    def execute(self, ssn: Session) -> None:
        if ssn._trace.enabled:
            ssn._trace.event(
                "allocate:start", "action",
                jobs=len(ssn.jobs), nodes=len(ssn.nodes),
            )
        drive_allocate_loop(
            ssn,
            begin_job=lambda job: ssn.statement(),
            place_task=make_place_task(ssn, host_node_chooser(ssn)),
            end_job=gang_end_job(ssn),
        )


def new() -> AllocateAction:
    return AllocateAction()
