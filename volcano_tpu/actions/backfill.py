"""Backfill action — place best-effort tasks on any feasible node.

Reference: pkg/scheduler/actions/backfill/backfill.go.
"""

from __future__ import annotations

from volcano_tpu.api import FitError, TaskStatus
from volcano_tpu.api.unschedule_info import FitErrors
from volcano_tpu.apis import scheduling
from volcano_tpu.framework.interface import Action
from volcano_tpu.framework.session import Session
from volcano_tpu.scheduler import util as sched_util


class BackfillAction(Action):
    def name(self) -> str:
        return "backfill"

    def execute(self, ssn: Session) -> None:
        """backfill.go:41-91."""
        for job in sorted(ssn.jobs.values(), key=lambda j: j.uid):
            if (
                job.pod_group is not None
                and job.pod_group.status.phase == scheduling.POD_GROUP_PENDING
            ):
                continue
            vr = ssn.job_valid(job)
            if vr is not None and not vr.pass_:
                continue

            for task in sorted(
                job.task_status_index.get(TaskStatus.Pending, {}).values(),
                key=lambda t: t.uid,
            ):
                if not task.init_resreq.is_empty():
                    continue
                allocated = False
                fe = FitErrors()
                for node in sched_util.get_node_list(ssn.nodes):
                    try:
                        ssn.predicate_fn(task, node)
                    except FitError as err:
                        fe.set_node_error(node.name, err)
                        continue
                    try:
                        ssn.allocate(task, node.name)
                    except FitError as err:
                        # propagate the bare reasons — re-wrapping str(err)
                        # would stuff the whole "task X on node Y: reason"
                        # line into the list and corrupt the
                        # FitErrors.error() reason histogram
                        fe.set_node_error(
                            node.name, FitError(task, node, *err.reasons)
                        )
                        continue
                    except Exception as err:  # noqa: BLE001 — try next node
                        fe.set_node_error(node.name, FitError(task, node, str(err)))
                        continue
                    allocated = True
                    break
                if not allocated:
                    job.nodes_fit_errors[task.uid] = fe
                    ssn.touched_jobs.add(job.uid)


def new() -> BackfillAction:
    return BackfillAction()
