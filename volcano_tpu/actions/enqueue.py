"""Enqueue action — gate PodGroupPending → Inqueue by cluster headroom.

Reference: pkg/scheduler/actions/enqueue/enqueue.go.
"""

from __future__ import annotations

from typing import Dict

from volcano_tpu.api import Resource
from volcano_tpu.api.resource import empty_resource
from volcano_tpu.apis import scheduling
from volcano_tpu.conf import get_action_arguments
from volcano_tpu.framework.interface import Action
from volcano_tpu.framework.session import Session
from volcano_tpu.utils.priority_queue import PriorityQueue

#: enqueue.go:36-37
OVERCOMMIT_FACTOR = "overcommit-factor"
DEFAULT_OVERCOMMIT_FACTOR = 1.2


class EnqueueAction(Action):
    def name(self) -> str:
        return "enqueue"

    def _overcommit_factor(self, ssn: Session) -> float:
        args = get_action_arguments(ssn.configurations, self.name())
        if args is not None:
            return args.get_float(OVERCOMMIT_FACTOR, DEFAULT_OVERCOMMIT_FACTOR)
        return DEFAULT_OVERCOMMIT_FACTOR

    def execute(self, ssn: Session) -> None:
        """enqueue.go:54-134."""
        queues = PriorityQueue(ssn.queue_order_fn)
        queue_map: Dict[str, object] = {}
        jobs_map: Dict[str, PriorityQueue] = {}

        for job in sorted(ssn.jobs.values(), key=lambda j: j.uid):
            queue = ssn.queues.get(job.queue)
            if queue is None:
                continue
            if queue.uid not in queue_map:
                queue_map[queue.uid] = queue
                queues.push(queue)
            if (
                job.pod_group is not None
                and job.pod_group.status.phase == scheduling.POD_GROUP_PENDING
            ):
                jobs_map.setdefault(job.queue, PriorityQueue(ssn.job_order_fn)).push(job)

        empty = empty_resource()
        nodes_idle = empty_resource()
        factor = self._overcommit_factor(ssn)
        for node in ssn.nodes.values():
            nodes_idle.add(
                node.allocatable.clone().multi(factor).sub_unchecked(node.used)
            )

        while not queues.empty():
            if nodes_idle.less(empty):
                break
            queue = queues.pop()
            jobs = jobs_map.get(queue.uid)
            if jobs is None or jobs.empty():
                continue
            job = jobs.pop()

            inqueue = False
            min_resources = job.pod_group.spec.min_resources if job.pod_group else None
            if not min_resources:
                inqueue = True
            else:
                pg_resource = Resource.from_resource_list(min_resources)
                if ssn.job_enqueueable(job) and pg_resource.less_equal(nodes_idle):
                    nodes_idle.sub_unchecked(pg_resource)
                    inqueue = True

            if inqueue and job.pod_group is not None:
                job.pod_group.status.phase = scheduling.POD_GROUP_INQUEUE
                ssn.jobs[job.uid] = job

            queues.push(queue)


def new() -> EnqueueAction:
    return EnqueueAction()
