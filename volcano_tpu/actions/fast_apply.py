"""Bulk APPLY phase for jax-allocate: vectorized commit of a fully-placed
kernel assignment, bypassing the per-task statement/heap/event machinery.

The slow path (drive_allocate_loop + Statement) costs ~90µs/task of pure
Python at the 50k headline shape — 20x the whole device-kernel budget —
yet when the packer encoded every predicate exactly and the kernel
committed every task, the loop is mechanical: each ordered task lands on
its proposed node, every job turns gang-ready, every statement commits.
This module reproduces that exact final state with one pass over the
ordered tasks plus per-object bulk writebacks:

  * float accounting (job.allocated/total_request, node.idle/used, drf /
    proportion / namespace shares) applies the same per-lane operation
    sequences the slow path would (grouped by owning object, which
    preserves IEEE bit-identity — lanes of different objects never mix)
  * dict state (job.tasks order, task_status_index buckets, node.tasks
    clones, the two PodLister views) is rebuilt with the same insertion
    orders
  * cache side effects flow through SchedulerCache.bind_batch — the same
    internal mutations as per-task bind() under one mutex hold, with the
    binder/event effects run in task order

The commit is PARTIAL at job granularity: jobs whose every pending task
carries a clean validated-exact proposal bulk-commit; jobs with a
preference task, a PVC-backed pod, or a missing proposal stay on the
slow Statement loop, which runs only over that residual (committed jobs
drain to empty pending queues).  ``try_fast_apply`` returns True only
when nothing was left for the slow loop; session-level envelope
violations (unknown plugins, inexact packing, host-validation needs)
still refuse wholesale with nothing committed.

Equivalence scope: for fully-applied sessions, tests/test_fast_apply.py
pins the resulting session + cache state equal to the slow path's,
field by field.  For PARTIAL sessions the bulk subset commits before
the residual loop runs, so when a residual job sorts BEFORE a clean job
in the drive order AND the two contend for capacity, placements can
differ from the pure slow path's interleaving — the same
capacity-race envelope the kernel-proposal fallback already documents
(jax_allocate.py): every placement is still individually valid, kernel
resource accounting is conservative (it reserved for the residual tasks
too), and the partial-path tests pin exact state equality for the
residual-sorts-last case.
"""

from __future__ import annotations

from typing import Dict, List

from volcano_tpu.api import TaskInfo, TaskStatus
from volcano_tpu.api.job_info import _READY_STATUSES
from volcano_tpu.framework.session import Session

#: plugins whose event handlers / state this bulk path models exactly
_KNOWN_PLUGINS = frozenset(
    (
        "priority",
        "gang",
        "conformance",
        "drf",
        "proportion",
        "predicates",
        "nodeorder",
        "binpack",
    )
)

#: plugins that register an allocate/deallocate EventHandler
_HANDLER_PLUGINS = ("drf", "proportion", "predicates", "nodeorder")


class _LaneAcc:
    """Float lanes (cpu, memory, scalars) mutated with the exact op
    sequence the slow path would apply to the owning Resource object."""

    __slots__ = ("cpu", "mem", "scalars")

    def __init__(self, res):
        self.cpu = res.milli_cpu
        self.mem = res.memory
        self.scalars = dict(res.scalars) if res.scalars else {}

    def store(self, res) -> None:
        res.milli_cpu = self.cpu
        res.memory = self.mem
        if self.scalars or res.scalars:
            res.scalars = self.scalars


def _acc_alloc(a0: _LaneAcc, a1: _LaneAcc, rr) -> None:
    """Statement.allocate's job-lane ops: allocated +r; total -r,+r
    (update_task_status Pending→Allocated = delete_task_info +
    add_task_info)."""
    a0.cpu += rr.milli_cpu
    a0.mem += rr.memory
    a1.cpu = (a1.cpu - rr.milli_cpu) + rr.milli_cpu
    a1.mem = (a1.mem - rr.memory) + rr.memory
    if rr.scalars:
        _seq_add_scalars(a0, rr.scalars, (1,))
        _seq_add_scalars(a1, rr.scalars, (-1, 1))


def _acc_commit(a0: _LaneAcc, a1: _LaneAcc, rr) -> None:
    """Commit's job-lane ops: allocated -r,+r; total -r,+r
    (update_task_status Allocated→Binding — both allocated statuses)."""
    a0.cpu = (a0.cpu - rr.milli_cpu) + rr.milli_cpu
    a0.mem = (a0.mem - rr.memory) + rr.memory
    a1.cpu = (a1.cpu - rr.milli_cpu) + rr.milli_cpu
    a1.mem = (a1.mem - rr.memory) + rr.memory
    if rr.scalars:
        _seq_add_scalars(a0, rr.scalars, (-1, 1))
        _seq_add_scalars(a1, rr.scalars, (-1, 1))


def _seq_add_scalars(acc: _LaneAcc, scalars, pattern) -> None:
    """Apply +v/-v in ``pattern`` order per scalar lane (float
    non-associativity means x+v-v+v != x+v in general — the sequence must
    match the slow path's)."""
    sc = acc.scalars
    for name, v in scalars.items():
        x = sc.get(name, 0.0)
        for sign in pattern:
            x = x + v if sign > 0 else x - v
        sc[name] = x


def try_fast_apply(
    ssn: Session,
    ordered: List[TaskInfo],
    proposals: Dict[str, str],
    snap,
) -> bool:
    """Bulk-commit the provably-clean subset of ``proposals``.

    Returns True when EVERY ordered task committed (the caller can skip
    the Statement loop entirely).  Returns False either because the
    session is outside the bulk envelope (nothing committed) or because
    only a subset of jobs was bulk-committed — the caller then runs the
    slow drive loop, which naturally skips the committed jobs (their
    pending queues are empty) and handles only the residual tasks
    (preference terms, PVC flows, missing proposals).  One odd task no
    longer costs a full-session Python loop.

    Bulk granularity is the JOB: gang commit/discard is all-or-nothing
    per job, and the kernel's gang fixpoint only emits proposals for
    jobs it could fully place, so a job whose every pending task has a
    clean validated-exact proposal commits exactly as the slow path
    would."""
    if snap.needs_host_validation or not snap.memory_exact:
        return False
    if not set(ssn.plugins) <= _KNOWN_PLUGINS:
        return False
    expected_handlers = sum(1 for p in _HANDLER_PLUGINS if p in ssn.plugins)
    if len(ssn.event_handlers) != expected_handlers:
        return False
    ready_chain = [
        p.name
        for tier in ssn.tiers
        for p in tier.plugins
        if p.enabled_job_ready and p.name in ssn.job_ready_fns
    ]
    if not set(ready_chain) <= {"gang"}:
        return False
    cache = ssn.cache
    if not hasattr(cache, "bind_batch"):
        return False

    drf = ssn.plugins.get("drf")
    proportion = ssn.plugins.get("proportion")
    # weighted-namespace DRF mirrors the plugin's own enablement check
    ns_enabled = drf is not None and any(
        p.enabled_namespace_order
        for tier in ssn.tiers
        for p in tier.plugins
        if p.name == "drf"
    )
    # the PodLister views live only in handler closures; locate them so
    # the bulk path can update them without firing per-task events
    listers = _find_pod_listers(ssn)
    if listers is None:
        return False
    # needs_host_validation only covers the packed (pending) tasks' own
    # affinity specs — a PRE-ASSIGNED pod with required anti-affinity
    # makes the host predicate's symmetry check load-bearing for every
    # placement, which the kernel cannot see.  Refuse.
    if any(pl.any_required_anti_affinity() for pl in listers):
        return False

    nodes_by_name = ssn.nodes
    gang_ready = bool(ready_chain)

    # ---- classify jobs: bulk-eligible vs residual ----
    groups: Dict[str, List[TaskInfo]] = {}
    has_pref = snap.task_has_preferences
    pref_by_uid = {}
    for i, t in enumerate(ordered):
        groups.setdefault(t.job, []).append(t)
        pref_by_uid[t.uid] = bool(has_pref[i]) if i < len(has_pref) else False
    eligible: set = set()
    for uid, tasks in groups.items():
        job = ssn.jobs.get(uid)
        if job is None:
            continue
        ok = True
        for t in tasks:
            host = proposals.get(t.uid)
            if host is None or pref_by_uid[t.uid]:
                ok = False
                break
            node = nodes_by_name.get(host)
            if node is None or node.node is None:
                ok = False
                break
            if t.pod is not None and cache.task_claim_names(t):
                ok = False  # PVC flows keep the slow path's volume logic
                break
        # the slow path would gang-discard a job that cannot reach
        # min_available — such jobs (the kernel never proposes them
        # fully) stay on the slow path
        if ok and gang_ready and job.ready_task_num() + len(tasks) < job.min_available:
            ok = False
        if ok and drf is not None and uid not in drf.job_attrs:
            ok = False
        if ok and ns_enabled and any(
            t.namespace not in drf.namespace_opts for t in tasks
        ):
            ok = False
        if ok:
            eligible.add(uid)
    if not eligible:
        return False
    bulk = [t for t in ordered if t.job in eligible]

    # ---- single pass over the bulk tasks ----
    job_accs: Dict[str, tuple] = {}
    job_ready0: Dict[str, int] = {}
    node_rows: Dict[str, list] = {}
    drf_accs: Dict[str, _LaneAcc] = {}
    ns_accs: Dict[str, _LaneAcc] = {}
    q_accs: Dict[str, _LaneAcc] = {}

    for t in bulk:
        host = proposals[t.uid]
        rr = t.resreq
        rc, rm = rr.milli_cpu, rr.memory
        scal = rr.scalars

        job = ssn.jobs[t.job]
        acc = job_accs.get(job.uid)
        if acc is None:
            acc = (_LaneAcc(job.allocated), _LaneAcc(job.total_request), job, [])
            job_accs[job.uid] = acc
            job_ready0[job.uid] = job.ready_task_num()
        acc[3].append(t)

        rows = node_rows.get(host)
        if rows is None:
            rows = []
            node_rows[host] = rows
        rows.append(t)

        if drf is not None:
            jacc = drf_accs.get(t.job)
            if jacc is None:
                jacc = _LaneAcc(drf.job_attrs[t.job].allocated)
                drf_accs[t.job] = jacc
            jacc.cpu += rc
            jacc.mem += rm
            if scal:
                _seq_add_scalars(jacc, scal, (1,))
            if ns_enabled:
                nacc = ns_accs.get(t.namespace)
                if nacc is None:
                    nacc = _LaneAcc(drf.namespace_opts[t.namespace].allocated)
                    ns_accs[t.namespace] = nacc
                nacc.cpu += rc
                nacc.mem += rm
                if scal:
                    _seq_add_scalars(nacc, scal, (1,))
        if proportion is not None:
            qacc = q_accs.get(job.queue)
            if qacc is None:
                attr = proportion.queue_opts.get(job.queue)
                if attr is None:
                    continue
                qacc = _LaneAcc(attr.allocated)
                q_accs[job.queue] = qacc
            qacc.cpu += rc
            qacc.mem += rm
            if scal:
                _seq_add_scalars(qacc, scal, (1,))

    # ---- mutate: everything above validated, nothing mutated yet ----
    binding = TaskStatus.Binding
    for host, rows in node_rows.items():
        node = nodes_by_name[host]
        idle, used = _LaneAcc(node.idle), _LaneAcc(node.used)
        ntasks = node.tasks
        for t in rows:
            rr = t.resreq
            idle.cpu -= rr.milli_cpu
            idle.mem -= rr.memory
            used.cpu += rr.milli_cpu
            used.mem += rr.memory
            if rr.scalars:
                _seq_add_scalars(idle, rr.scalars, (-1,))
                _seq_add_scalars(used, rr.scalars, (1,))
            t.volume_ready = True
            t.node_name = host
            ti = t.clone()
            ti.status = TaskStatus.Allocated
            ntasks[t.uid] = ti
        idle.store(node.idle)
        used.store(node.used)

    for alloc_acc, total_acc, job, tasks in job_accs.values():
        # job.allocated/total_request follow the slow path's EPISODE
        # structure: the first episode feeds until gang-ready (all its
        # Statement.allocate ops, then all its commit ops), later episodes
        # are one task each.  Per-lane op order must match for IEEE
        # bit-identity — per-task interleave rounds differently on lanes
        # with non-exact values.
        ready0 = job_ready0[job.uid]
        k1 = 1
        if gang_ready and ready0 < job.min_available:
            k1 = min(max(job.min_available - ready0, 1), len(tasks))
        first, rest = tasks[:k1], tasks[k1:]
        for t in first:  # episode-1 allocates
            _acc_alloc(alloc_acc, total_acc, t.resreq)
        for t in first:  # episode-1 commits
            _acc_commit(alloc_acc, total_acc, t.resreq)
        for t in rest:  # single-task episodes
            _acc_alloc(alloc_acc, total_acc, t.resreq)
            _acc_commit(alloc_acc, total_acc, t.resreq)
        alloc_acc.store(job.allocated)
        total_acc.store(job.total_request)
        jtasks = job.tasks
        pending = job.task_status_index.get(TaskStatus.Pending)
        bbucket = job.task_status_index.setdefault(binding, {})
        ready_gain = 0
        for t in tasks:
            jtasks.pop(t.uid, None)
            jtasks[t.uid] = t
            if pending is not None:
                pending.pop(t.uid, None)
            if t.status not in _READY_STATUSES:
                ready_gain += 1  # Pending → Binding enters the ready set
            t.status = binding
            bbucket[t.uid] = t
        job.ready_num += ready_gain
        if pending is not None and not pending:
            del job.task_status_index[TaskStatus.Pending]

    if drf is not None:
        for uid, jacc in drf_accs.items():
            attr = drf.job_attrs[uid]
            jacc.store(attr.allocated)
            drf._update_share(attr)
        for ns, nacc in ns_accs.items():
            opt = drf.namespace_opts[ns]
            nacc.store(opt.allocated)
            drf._update_share(opt)
    if proportion is not None:
        for q, qacc in q_accs.items():
            attr = proportion.queue_opts[q]
            qacc.store(attr.allocated)
            proportion._update_share(attr)

    for pl in listers:
        tn = pl._task_nodes
        for t in bulk:
            tn[t.uid] = t.node_name
        # anti-affinity sets: gate guarantees no pod (anti-)affinity terms
        # (needs_host_validation would be set), so nothing to maintain.

    import time

    t0 = time.perf_counter()
    cache.bind_batch([(t, t.node_name) for t in bulk])
    # what the scheduling thread actually paid for the commit: with the
    # pipelined plane this is the mutex-held state mutation plus the
    # queue handoff — the binder/bus round trips land on the bind
    # workers, overlapped with the next cycle
    from volcano_tpu.actions import jax_allocate as _ja

    _ja.last_phase_stats["commit_handoff_ms"] = (
        time.perf_counter() - t0
    ) * 1e3
    # journal only after the batch landed — "bind" means an actual
    # cache bind, and bind_batch mutates nothing when it raises
    if ssn._trace.enabled:
        for t in bulk:
            ssn._trace.decision("bind", t.uid, t.node_name)
    # the session-side touched sets feed the cache's snapshot clone pool
    if hasattr(ssn, "touched_jobs"):
        ssn.touched_jobs.update(job_accs)
        ssn.touched_nodes.update(node_rows)
        ssn.node_state_epoch += 1
    return len(bulk) == len(ordered)


def _find_pod_listers(ssn: Session):
    """The predicates/nodeorder PodListers live in handler closures; pull
    them out so the bulk path can update them without firing per-task
    events.  None when a closure doesn't look like a PodLister-backed
    handler (unknown handler shape — refuse)."""
    from volcano_tpu.plugins.util import PodLister

    listers = []
    for eh in ssn.event_handlers:
        fn = eh.allocate_func
        if fn is None:
            continue
        found = None
        closure = getattr(fn, "__closure__", None) or ()
        for cell in closure:
            try:
                if isinstance(cell.cell_contents, PodLister):
                    found = cell.cell_contents
                    break
            except ValueError:  # pragma: no cover - empty cell
                continue
        if found is not None:
            listers.append(found)
    expected = sum(1 for p in ("predicates", "nodeorder") if p in ssn.plugins)
    if len(listers) != expected:
        return None
    return listers
