"""Fast ORDER phase for jax-allocate: episode-level simulation of
drive_allocate_loop's pop order without touching session state.

The exact replay in actions/jax_allocate.py walks the real priority
queues and fires the real plugin event handlers per task (~40-50µs/task
— ~2s at the 50k headline shape, 10x the whole device-kernel budget).
But every order-determining quantity is a small scalar:

  * task order within a job — static (priority desc, ts, uid)
  * job order — (priority desc?, gang not-ready-first?, drf share?) per
    the session's comparator chain, then (ts, uid)
  * queue choice — proportion share (allocated/deserved) + overused
  * namespace order — lexicographic (weighted-namespace DRF bails)

and the dynamic ones (drf job share, proportion queue share, gang
readiness) change ONLY for the job being fed — never for a job sitting
in a heap.  So the loop decomposes into *episodes* (one job pop each):
feed the job's tasks until gang-ready (statically many), update its
share once, push it back.  Episode count is O(jobs + post-ready tasks),
so the simulation runs at Python-scalar speed instead of
comparator-replay speed, while producing the bit-identical order:
float updates are applied per task in the same sequence as the drf /
proportion event handlers (drf.go:255-272), so accumulated shares are
IEEE-identical to the replay's.

``try_compute_task_order`` returns None unless the session's comparator
chains and overused/job-ready registrations match the semantics modeled
here (the same refuse-loudly discipline as ops/preempt_pack); callers
fall back to the exact replay.  Equivalence is enforced by
tests/test_fast_order.py, which diffs this order against the replay's
across multi-queue / multi-namespace / priority / preallocated /
best-effort sessions.
"""

from __future__ import annotations

import heapq
from typing import Dict, List, Optional

from volcano_tpu.api import TaskInfo, TaskStatus
from volcano_tpu.api.resource import (
    MIN_MEMORY,
    MIN_MILLI_CPU,
    MIN_MILLI_SCALAR,
    Resource,
)
from volcano_tpu.framework.session import Session

#: job-order comparators this simulator can express as scalar keys
_KNOWN_JOB_ORDER = {"priority", "gang", "drf"}
_KNOWN_TASK_ORDER = {"priority"}
_KNOWN_QUEUE_ORDER = {"proportion"}


def _chain(ssn: Session, fns: Dict, flag: str) -> List[str]:
    """Plugin names the session's _ordered dispatch would walk, in order."""
    return [
        p.name
        for tier in ssn.tiers
        for p in tier.plugins
        if getattr(p, flag) and p.name in fns
    ]


class _Share:
    """Mirror of a drf job attr / proportion queue attr: allocated lanes
    accumulated per task in event-handler order, share recomputed at
    episode boundaries with the plugins' exact formula."""

    __slots__ = ("cpu", "mem", "scalars", "share", "denom_cpu", "denom_mem",
                 "denom_scalars")

    def __init__(self, allocated: Resource, share: float, denom: Resource):
        self.cpu = allocated.milli_cpu
        self.mem = allocated.memory
        self.scalars = dict(allocated.scalars)
        self.share = share
        self.denom_cpu = denom.milli_cpu
        self.denom_mem = denom.memory
        self.denom_scalars = dict(denom.scalars)

    def add_task(self, rr: Resource) -> None:
        self.cpu += rr.milli_cpu
        self.mem += rr.memory
        if rr.scalars:
            for name, v in rr.scalars.items():
                self.scalars[name] = self.scalars.get(name, 0.0) + v

    def update_share(self) -> None:
        """share_fn max over the denominator's resource names (drf
        _update_share / proportion _update_share)."""
        res = _share_of(self.cpu, self.denom_cpu)
        s = _share_of(self.mem, self.denom_mem)
        if s > res:
            res = s
        for name, denom in self.denom_scalars.items():
            s = _share_of(self.scalars.get(name, 0.0), denom)
            if s > res:
                res = s
        self.share = res

    def overused(self) -> bool:
        """not allocated.less_equal(deserved) — Resource.less_equal with
        the reference's tolerance conventions."""
        if not _le(self.cpu, self.denom_cpu, MIN_MILLI_CPU):
            return True
        if not _le(self.mem, self.denom_mem, MIN_MEMORY):
            return True
        for name, v in self.scalars.items():
            if v <= MIN_MILLI_SCALAR:
                continue
            if not _le(v, self.denom_scalars.get(name, 0.0), MIN_MILLI_SCALAR):
                return True
        return False


def _share_of(l: float, r: float) -> float:
    if r == 0:
        return 1.0 if l > 0 else 0.0
    return l / r


def _le(l: float, r: float, diff: float) -> bool:
    return l < r or abs(l - r) < diff


def try_compute_task_order(ssn: Session) -> Optional[List[TaskInfo]]:
    """Simulated pop order, or None when the session's ordering semantics
    fall outside the modeled shape."""
    job_chain = _chain(ssn, ssn.job_order_fns, "enabled_job_order")
    task_chain = _chain(ssn, ssn.task_order_fns, "enabled_task_order")
    queue_chain = _chain(ssn, ssn.queue_order_fns, "enabled_queue_order")
    ns_chain = _chain(ssn, ssn.namespace_order_fns, "enabled_namespace_order")
    ready_chain = _chain(ssn, ssn.job_ready_fns, "enabled_job_ready")
    overused_names = set(ssn.overused_fns)

    if (
        not set(job_chain) <= _KNOWN_JOB_ORDER
        or not set(task_chain) <= _KNOWN_TASK_ORDER
        or not set(queue_chain) <= _KNOWN_QUEUE_ORDER
        or not set(ns_chain) <= {"drf"}
        or not set(ready_chain) <= {"gang"}
        or not overused_names <= {"proportion"}
    ):
        return None

    use_drf = "drf" in job_chain
    use_ns_drf = bool(ns_chain)  # weighted-namespace DRF order
    use_proportion = bool(queue_chain) or overused_names
    drf = ssn.plugins.get("drf") if use_drf or use_ns_drf else None
    proportion = ssn.plugins.get("proportion") if use_proportion else None
    if (use_drf or use_ns_drf) and (
        drf is None
        or not hasattr(drf, "job_attrs")
        or not hasattr(drf, "namespace_opts")
    ):
        return None
    if use_proportion and (
        proportion is None or not hasattr(proportion, "queue_opts")
    ):
        return None

    # ---- eligible jobs, namespace/queue maps (drive_allocate_loop) ----
    from volcano_tpu.actions.allocate import eligible_jobs

    jobs = list(eligible_jobs(ssn))
    if not jobs:
        return []

    job_shares: Dict[str, _Share] = {}
    if use_drf:
        total = drf.total_resource
        for job in jobs:
            attr = drf.job_attrs.get(job.uid)
            if attr is None:
                return None
            job_shares[job.uid] = _Share(attr.allocated, attr.share, total)

    # queue uid -> _Share, or None when proportion has no attr for it
    # (the plugin then reports share 0.0 and never overused).
    queue_shares: Dict[str, Optional[_Share]] = {}
    if use_proportion:
        for job in jobs:
            if job.queue in queue_shares:
                continue
            attr = proportion.queue_opts.get(job.queue)
            queue_shares[job.queue] = (
                None
                if attr is None
                else _Share(attr.allocated, attr.share, attr.deserved)
            )

    # namespace shares for weighted-namespace DRF (drf.go:223-248): the
    # ns being fed is outside the heap during its episode, so the same
    # lazy-repush discipline applies.
    ns_shares: Dict[str, _Share] = {}
    ns_weights: Dict[str, float] = {}
    if use_ns_drf:
        total = drf.total_resource
        empty = Resource()
        for job in jobs:
            if job.namespace in ns_shares:
                continue
            opt = drf.namespace_opts.get(job.namespace)
            ns_shares[job.namespace] = (
                _Share(opt.allocated, opt.share, total)
                if opt is not None
                else _Share(empty, 0.0, total)
            )
            info = ssn.namespace_info.get(job.namespace)
            ns_weights[job.namespace] = float(
                info.get_weight() if info else 1
            )

    def ns_key(ns: str):
        if use_ns_drf:
            return (ns_shares[ns].share / ns_weights[ns], ns)
        return (ns,)

    gang_ready = bool(ready_chain)  # gang's JobReady registered

    # per-job mutable order state
    fed: Dict[str, int] = {j.uid: 0 for j in jobs}
    ready0: Dict[str, int] = {j.uid: j.ready_task_num() for j in jobs}

    def job_key(job):
        key = []
        for name in job_chain:
            if name == "priority":
                key.append(-job.priority)
            elif name == "gang":
                ready = ready0[job.uid] + fed[job.uid] >= job.min_available
                key.append(1 if ready else 0)
            else:  # drf
                key.append(job_shares[job.uid].share)
        key.append(job.creation_timestamp)
        key.append(job.uid)
        return tuple(key)

    # namespace -> {queue uid -> job heap}, insertion order preserved;
    # ns heap entries are ns_key tuples ending in the namespace string.
    ns_heap: List = []
    ns_map: Dict[str, Dict[str, List]] = {}
    for job in jobs:
        queue_map = ns_map.get(job.namespace)
        if queue_map is None:
            heapq.heappush(ns_heap, ns_key(job.namespace))
            queue_map = {}
            ns_map[job.namespace] = queue_map
        heapq.heappush(
            queue_map.setdefault(job.queue, []), (job_key(job), job)
        )

    # lazily-built static task order per job (build_pending_task_queue)
    pending: Dict[str, List[TaskInfo]] = {}
    use_task_priority = bool(task_chain)

    def build_pending(job) -> List[TaskInfo]:
        tasks = [
            t
            for t in job.task_status_index.get(TaskStatus.Pending, {}).values()
            if not t.resreq.is_empty()
        ]
        if use_task_priority:
            tasks.sort(key=lambda t: (-t.priority, t.creation_timestamp, t.uid))
        else:
            tasks.sort(key=lambda t: (t.creation_timestamp, t.uid))
        return tasks

    order: List[TaskInfo] = []

    while ns_heap:
        namespace = heapq.heappop(ns_heap)[-1]
        queue_in_namespace = ns_map[namespace]

        # least-share non-overused queue, same linear scan + tie-break as
        # drive_allocate_loop (queue_order_fn then ts/uid).  Shares only
        # participate when proportion's queue-order is in the chain.
        by_share = bool(queue_chain)
        queue = None
        queue_share = None
        for queue_id in list(queue_in_namespace):
            qinfo = ssn.queues[queue_id]
            qs = queue_shares.get(queue_id)
            if qs is not None and qs.overused():
                del queue_in_namespace[queue_id]
                continue
            if queue is None:
                queue, queue_share = qinfo, qs
                continue
            ls = qs.share if by_share and qs is not None else 0.0
            rs = queue_share.share if by_share and queue_share is not None else 0.0
            before = (
                ls < rs
                if ls != rs
                else (
                    qinfo.uid < queue.uid
                    if qinfo.creation_timestamp == queue.creation_timestamp
                    else qinfo.creation_timestamp < queue.creation_timestamp
                )
            )
            if before:
                queue, queue_share = qinfo, qs
        if queue is None:
            continue

        heap = queue_in_namespace.get(queue.uid)
        if not heap:
            continue

        _, job = heapq.heappop(heap)
        tasks = pending.get(job.uid)
        if tasks is None:
            tasks = build_pending(job)
            pending[job.uid] = tasks

        # feed tasks until gang-ready (or exhaustion); without a JobReady
        # registration every placement reports ready immediately.
        n_fed = fed[job.uid]
        consumed = 0
        became_ready = False
        jshare = job_shares.get(job.uid)
        qshare = queue_shares.get(job.queue) if use_proportion else None
        nshare = ns_shares.get(namespace) if use_ns_drf else None
        while consumed < len(tasks):
            task = tasks[consumed]
            consumed += 1
            order.append(task)
            if jshare is not None:
                jshare.add_task(task.resreq)
            if qshare is not None:
                qshare.add_task(task.resreq)
            if nshare is not None:
                nshare.add_task(task.resreq)
            if (
                not gang_ready
                or ready0[job.uid] + n_fed + consumed >= job.min_available
            ):
                became_ready = True
                break
        fed[job.uid] = n_fed + consumed
        del tasks[:consumed]
        if consumed:
            if jshare is not None:
                jshare.update_share()
            if qshare is not None:
                qshare.update_share()
            if nshare is not None:
                nshare.update_share()

        if became_ready:
            heapq.heappush(heap, (job_key(job), job))
        heapq.heappush(ns_heap, ns_key(namespace))

    return order
