"""jax-allocate — the allocate action with the O(tasks×nodes) hot loop on
TPU.

Reference behavior: pkg/scheduler/actions/allocate/allocate.go.  Design
(SURVEY.md §7): the reference's per-task PredicateNodes/PrioritizeNodes/
SelectBestNode (scheduler_helper.go:64-211) is replaced by one fused device
kernel over the whole session; results apply through the same Statement so
gang commit/discard and plugin event handlers stay intact.

Three phases, all built on the single control-flow skeleton in
actions/allocate.py (drive_allocate_loop):

1. ORDER — replay the control flow *without placements* to obtain the task
   processing order.  Exact because every order-determining quantity (DRF
   share, proportion queue share/overused, gang readiness, priorities)
   updates from task resreqs only, never from which node a task landed on.
   The replay mutates session accounting through the real event handlers
   and then unwinds itself, Statement-style.
2. KERNEL — pack the snapshot (ops/packing.py) and run the fused
   predicate+score+assign scan (ops/kernels.py) over the ordered tasks.
3. APPLY — run the real control flow, placing each task on its kernel-
   proposed node after an O(1) host validation (plugin predicates + fit on
   that node only); tasks whose proposal fails validation — and tasks the
   kernel cannot score faithfully (preferred-affinity terms) — fall back
   to the host scoring path for that task alone.

Bindings equivalence: with deterministic tie-break, phase 2's argmax equals
the host path's SelectBestNode per task, so bindings are identical whenever
every ordered task is placeable (tests/test_jax_allocate.py).  When a
placement fails (capacity race against the static proposal), the fallback
keeps the result valid — semantics never degrade below the host action.
"""

from __future__ import annotations

import os
import time
from typing import Dict, List, Optional, Tuple

from volcano_tpu.actions.allocate import (
    drive_allocate_loop,
    gang_end_job,
    host_node_chooser,
    make_place_task,
    make_predicate_fn,
)
from volcano_tpu.api import FitError, TaskInfo, TaskStatus
from volcano_tpu.framework.interface import Action
from volcano_tpu.framework.session import Session
from volcano_tpu.metrics import metrics
from volcano_tpu.utils.logging import get_logger

log = get_logger(__name__)


class _ExplainContext:
    """Device-derived unschedulability explanations for one session.

    Built from the reason-count matrix the executor returned alongside
    the assignment (ops/explain).  ``try_explain`` replaces the host
    fallback's O(N) predicate sweep for a task the device PROVED fits no
    node — synthesizing the reference-identical FitErrors from the
    counts — under gates that keep the messages byte-faithful to what
    the host path would have recorded on the same snapshot:

      * the predicates plugin is in the session, with no opt-in
        pressure predicates (ops.explain.session_explain_compatible —
        the host chain's pressure checks have no device plane);
      * the task row is bitset-exact and memory-exact
        (ops.explain.task_exactly_encoded);
      * no placement has mutated node state since the pack — after a
        placement the host's pop-time first-failure can shift to a
        resource-fit failure the snapshot-time counts predate, so those
        tasks take the host sweep instead.
    """

    def __init__(self, ssn: Session, snap, counts, ordered, nodes,
                 planes=None):
        from volcano_tpu.ops.explain import (
            ExplainResult,
            session_explain_compatible,
        )

        self.ssn = ssn
        self.snap = snap
        self.result = ExplainResult(counts, snap.n_nodes, planes)
        self.index = {t.uid: i for i, t in enumerate(ordered)}
        self.node_names = [n.name for n in nodes]
        self.enabled = session_explain_compatible(ssn)
        #: node-state epoch at pack time — any later mutation (even of a
        #: node some earlier action already touched) advances it, so the
        #: gate below cannot be fooled by repeat mutations the
        #: touched-SET would deduplicate away
        self._epoch0 = ssn.node_state_epoch
        #: task uid → reason histogram, for the cycle summary
        self.explained: Dict[str, Dict[str, int]] = {}

    def try_explain(self, task: TaskInfo):
        """FitErrors for ``task`` when the device counts prove it
        unschedulable everywhere — None sends the caller to the host
        sweep."""
        from volcano_tpu.ops.explain import task_exactly_encoded

        if not self.enabled or self.ssn.node_state_epoch != self._epoch0:
            return None
        i = self.index.get(task.uid)
        if i is None or i >= len(self.result.counts):
            return None
        if not task_exactly_encoded(self.snap, i):
            return None
        if not self.result.all_infeasible(i):
            return None
        hist = self.result.histogram(i)
        self.explained[task.uid] = hist
        for reason in hist:
            metrics.register_unschedulable_reason(reason)
        return self.result.fit_errors(i)

    def summary(self) -> Dict[str, int]:
        """Aggregate reason → node-count histogram over the explained
        tasks (the per-cycle trace journal record)."""
        agg: Dict[str, int] = {}
        for hist in self.explained.values():
            for reason, count in hist.items():
                agg[reason] = agg.get(reason, 0) + count
        return agg


def compute_task_order(ssn: Session) -> List[TaskInfo]:
    """Phase 1: the task processing order.

    Sessions whose ordering semantics match the standard plugin shape
    take the episode-level simulation (actions/fast_order.py, ~10x
    cheaper than the replay at 50k tasks); anything else falls back to
    the exact replay below.  tests/test_fast_order.py pins the two
    orders equal."""
    from volcano_tpu.actions.fast_order import try_compute_task_order

    fast = try_compute_task_order(ssn)
    if fast is not None:
        return fast
    return compute_task_order_replay(ssn)


def compute_task_order_replay(ssn: Session) -> List[TaskInfo]:
    """Replay the loop assuming every task places, recording pop
    order; then unwind all accounting (reverse order, like
    Statement.Discard)."""
    order: List[TaskInfo] = []
    touched: List[Tuple[TaskInfo, TaskStatus]] = []

    def place_task(_ctx, task: TaskInfo, job) -> bool:
        order.append(task)
        touched.append((task, task.status))
        # the unwind restores statuses exactly but job.allocated float
        # lanes round-trip through add/sub — the clone is dirty for the
        # snapshot reuse pool
        ssn.touched_jobs.add(task.job)
        job.update_task_status(task, TaskStatus.Allocated)
        ssn._fire_allocate(task)
        return True

    drive_allocate_loop(
        ssn,
        begin_job=lambda job: None,
        place_task=place_task,
        end_job=lambda ctx, job: None,
    )

    for task, prior_status in reversed(touched):
        job = ssn.jobs[task.job]
        job.update_task_status(task, prior_status)
        ssn._fire_deallocate(task)

    return order


#: phase timings of the most recent execute() — read by bench.py right
#: after the call, same single-threaded discipline as dispatch state
last_phase_stats: Dict[str, float] = {}


class JaxAllocateAction(Action):
    def __init__(
        self,
        weights=None,
        gang_rounds: int = 3,
        explain: Optional[bool] = None,
        explain_planes: Optional[bool] = None,
    ):
        from volcano_tpu.ops.kernels import DEFAULT_WEIGHTS

        self.weights = weights or DEFAULT_WEIGHTS
        self.gang_rounds = gang_rounds
        from volcano_tpu.ops.explain import explain_enabled

        #: device-derived unschedulability explanations (ops/explain).
        #: On by default: the reason-count reduction only runs when a
        #: task went unplaced, so fully-placed warm cycles pay nothing.
        #: VTPU_NO_EXPLAIN=1 (or explain=False) turns it off.
        self.explain = explain_enabled() if explain is None else explain
        #: additionally retain the per-pair [T, N] reason plane for the
        #: /explain endpoint's node-level attribution.  Off by default —
        #: the retention transfer scales with T×N, the counts with T×5.
        self.explain_planes = (
            bool(os.environ.get("VTPU_EXPLAIN_PLANES"))
            if explain_planes is None
            else explain_planes
        )

    def name(self) -> str:
        return "jax-allocate"

    # ---- phase 2 ----

    def _kernel_proposals(
        self,
        ssn: Session,
        ordered_tasks: List[TaskInfo],
        nodes: Optional[List] = None,
        pack_cache=None,
    ) -> Tuple[Dict[str, str], Optional[object]]:
        """Pack + run the device kernel; ({task uid → node name}, snap).

        Tasks flagged ``task_has_preferences`` are excluded — the kernel
        has no lanes for preferred (anti-)affinity scores, so those route
        to the host chooser.  Relational predicates the packer could not
        encode (needs_host_validation) are safe regardless: phase 3
        validates every proposal against the full host predicate set."""
        from volcano_tpu.ops.executor import execute_allocate
        from volcano_tpu.ops.packing import pack_session

        jobs = {}
        for t in ordered_tasks:
            job = ssn.jobs.get(t.job)
            if job is not None and job.uid not in jobs:
                jobs[job.uid] = job
        if nodes is None:
            nodes = [ssn.nodes[name] for name in sorted(ssn.nodes)]
        if not nodes or not ordered_tasks:
            return {}, None

        enforce = "predicates" in ssn.predicate_fns
        t0 = time.perf_counter()
        if pack_cache is not None and ssn.pack_epoch is not None:
            # warm path: delta-assemble from the cycle-persistent cache
            snap = pack_cache.pack(
                ordered_tasks,
                list(jobs.values()),
                nodes,
                ssn.pack_epoch,
                enforce_pod_count=enforce,
            )
            last_phase_stats.update(pack_cache.last_stats)
            if getattr(ssn.cache, "in_micro_cycle", False):
                # a micro-triggered cycle that still had to cold-rebuild
                # (registry overflow, axis change, …) paid full-cycle
                # cost — attribute the cause so the SLO harness can see
                # why the incremental path was unsound
                cause = pack_cache.last_stats.get("cold_cause")
                if cause is not None:
                    metrics.register_full_cycle_fallback(cause)
        else:
            snap = pack_session(
                ordered_tasks,
                list(jobs.values()),
                nodes,
                enforce_pod_count=enforce,
            )
        pack_s = time.perf_counter() - t0
        last_phase_stats["pack_ms"] = pack_s * 1e3
        metrics.update_kernel_duration("pack", pack_s)

        if snap.cache_key is not None:
            # attach the device-resident mirror: only dirty rows travel
            try:
                from volcano_tpu.ops.device_stage import get_stager

                snap.device_planes = get_stager(snap.cache_key).stage(snap)
            except Exception as e:  # noqa: BLE001 — numpy path still valid
                log.error("device staging failed (%s); numpy planes", e)

        t0 = time.perf_counter()
        # executor indirection: in-process kernels, or the compute-plane
        # sidecar when VTPU_COMPUTE_PLANE is configured (with automatic
        # in-process fallback when the sidecar is down).  explain=True
        # makes the executor return the reason-count matrix alongside
        # the assignment when tasks went unplaced (lazy — a fully-placed
        # session computes nothing extra).
        from volcano_tpu.faults.watchdog import CycleDeadlineExceeded

        try:
            assignment = execute_allocate(
                snap, weights=self.weights, gang_rounds=self.gang_rounds,
                explain=self.explain,
            )
        except CycleDeadlineExceeded as e:
            # cycle watchdog: the device phase overran its budget and
            # was abandoned.  Nothing session-side has mutated (the
            # device phase is pure), so the cycle completes on the host
            # scoring path: no proposals → every task takes host_choose
            # in _apply.  The demotion is journaled and counted.
            log.error("device phase abandoned: %s", e)
            metrics.register_executor_fallback("device", "host", "deadline")
            rec = ssn._trace
            if rec.enabled:
                rec.event("watchdog:device-phase-abandoned", "fault",
                          error=str(e))
            return {}, snap
        metrics.update_kernel_duration("execute", time.perf_counter() - t0)

        rec = ssn._trace
        if rec.enabled and rec.should_capture():
            # sampled journal capture: the packed session + the kernel's
            # assignment + the kernel parameters, the replayable tuple
            # trace.replay.verify diffs.  The label is the executor that
            # actually produced the assignment (including mid-session
            # degradations; 'auto' when the compute-plane sidecar ran
            # it), translated to replay vocabulary.
            from volcano_tpu.ops.executor import last_allocate_executor
            from volcano_tpu.trace.replay import replay_executor_name

            rec.capture(
                snap,
                assignment,
                executor=replay_executor_name(last_allocate_executor()),
                weights=self.weights,
                gang_rounds=self.gang_rounds,
            )

        proposals = {}
        for i, task in enumerate(ordered_tasks):
            if assignment[i] >= 0 and not snap.task_has_preferences[i]:
                proposals[task.uid] = nodes[assignment[i]].name
        return proposals, snap

    # ---- phase 3 ----

    def execute(self, ssn: Session) -> None:
        last_phase_stats.clear()
        epoch = ssn.pack_epoch
        pc = getattr(ssn.cache, "pack_cache", None) if epoch is not None else None
        nodes = [ssn.nodes[name] for name in sorted(ssn.nodes)]

        # Warm cycles stage the dynamic node planes BEFORE the ORDER
        # phase: node rows don't depend on task order, so the host→device
        # transfer runs concurrently with the pure-host ORDER replay and
        # the remaining relay is only the (delta-sized) task planes.
        prestaged = False
        if pc is not None and nodes:
            t0 = time.perf_counter()
            pending = pc.begin_nodes(
                nodes, epoch, "predicates" in ssn.predicate_fns
            )
            if pending is not None:
                try:
                    from volcano_tpu.ops.device_stage import get_stager

                    get_stager(pc.key).prestage(
                        pending["planes"], pending["dirty_pos"], pc.rev + 1
                    )
                    prestaged = True
                except Exception as e:  # noqa: BLE001 — stage() recovers
                    log.error("node-plane prestage failed: %s", e)
            last_phase_stats["node_prepack_ms"] = (
                time.perf_counter() - t0
            ) * 1e3

        t0 = time.perf_counter()
        with ssn._trace.span("jax-allocate:order", "action"):
            ordered = compute_task_order(ssn)
        order_s = time.perf_counter() - t0
        last_phase_stats["order_ms"] = order_s * 1e3
        if prestaged:
            # the window the staged transfer had to overlap host work
            last_phase_stats["relay_overlap_ms"] = order_s * 1e3
        if not ordered:
            if self.explain:
                # nothing pending → nothing to explain; clear the
                # surface so /explain never serves a previous cycle
                self._publish_explain(ssn, None)
            return
        proposals, snap = self._kernel_proposals(ssn, ordered, nodes, pc)

        # Reason counts the executor produced for unplaced tasks — the
        # device-derived "why pending" source (ops/explain).
        explain_ctx = None
        if self.explain and snap is not None:
            from volcano_tpu.ops import executor as _executor
            from volcano_tpu.ops import explain as _explain

            counts = _executor.last_explain_counts()
            if counts is not None:
                planes = None
                if self.explain_planes:
                    # node-level attribution for the /explain surface;
                    # recomputed locally (the wire ships counts only)
                    # over the rows that recorded any infeasibility
                    import numpy as _np

                    planes = _explain.run_explain(
                        snap, retain_planes=True,
                        task_rows=_np.nonzero(counts.sum(axis=1) > 0)[0],
                    ).reasons
                explain_ctx = _ExplainContext(
                    ssn, snap, counts, ordered, nodes, planes=planes
                )
                # None when the sidecar reduced the counts — its own
                # metrics carry that cost; don't fabricate a local one
                explain_ms = _executor.last_explain_ms()
                if explain_ms is not None:
                    last_phase_stats["explain_ms"] = explain_ms

        try:
            self._apply(ssn, ordered, proposals, snap, explain_ctx)
        finally:
            if self.explain:
                # also clears: a cycle that explained nothing (all
                # placed, gate closed, or no packed session) must not
                # leave the /explain surface serving a previous cycle's
                # explanation as current
                self._publish_explain(ssn, explain_ctx)

    def _apply(self, ssn, ordered, proposals, snap, explain_ctx) -> None:
        # Fully-placed exact sessions commit in bulk (actions/fast_apply);
        # anything outside that envelope runs the loop below.
        if snap is not None:
            from volcano_tpu.actions.fast_apply import try_fast_apply

            if try_fast_apply(ssn, ordered, proposals, snap):
                return

        predicate_fn = make_predicate_fn(ssn)
        host_choose = host_node_chooser(ssn)

        def choose_node(task: TaskInfo, job):
            """Kernel proposal with O(1) validation; host path fallback."""
            name = proposals.get(task.uid)
            if name is not None:
                node = ssn.nodes.get(name)
                if node is not None:
                    try:
                        predicate_fn(task, node)
                        return node
                    except FitError:
                        pass  # capacity/relational race → host fallback
            if explain_ctx is not None:
                fe = explain_ctx.try_explain(task)
                if fe is not None:
                    # device-proven unschedulable: record the synthesized
                    # FitErrors (the same writeback the host sweep feeds)
                    # and skip the O(N) host predicate scan entirely
                    job.nodes_fit_errors[task.uid] = fe
                    return None
            return host_choose(task, job)

        drive_allocate_loop(
            ssn,
            begin_job=lambda job: ssn.statement(),
            place_task=make_place_task(ssn, choose_node),
            end_job=gang_end_job(ssn),
        )

    def _publish_explain(
        self, ssn: Session, ctx: Optional[_ExplainContext]
    ) -> None:
        """Per-cycle reason summary → trace journal + /explain surface.
        A ``None`` context or an empty explained set CLEARS the surface
        — it reflects the most recent cycle, never a stale one."""
        from volcano_tpu.ops.explain import set_last_explain

        if ctx is None or not ctx.explained:
            set_last_explain(None)
            return
        summary = ctx.summary()
        rec = ssn._trace
        if rec.enabled:
            rec.event(
                "explain-summary", "action",
                tasks=len(ctx.explained), reasons=summary,
            )
        from volcano_tpu import trace as _trace

        detail = {}
        if ctx.result.reasons is not None:
            detail = {
                uid: ctx.result.node_reasons(ctx.index[uid], ctx.node_names)
                for uid in ctx.explained
            }
        set_last_explain(
            {
                "cycle": _trace.current_cycle(),
                "n_nodes": ctx.result.n_nodes,
                "tasks": {
                    uid: {
                        "reasons": hist,
                        **({"nodes": detail[uid]} if uid in detail else {}),
                    }
                    for uid, hist in ctx.explained.items()
                },
                "summary": summary,
            }
        )


def new() -> JaxAllocateAction:
    return JaxAllocateAction()
