"""jax-preempt — the preempt action with the victim-selection replay on
device.

Reference behavior: pkg/scheduler/actions/preempt/preempt.go:45-276.
Design mirrors actions/jax_allocate.py: the host packs the session
(ops/preempt_pack.pack_preempt_session — order replay + victim sort
happen host-side), one device program replays the whole preemption pass
(ops/preempt_pallas.run_preempt_pallas; numpy ``preempt_dense`` off-TPU),
and the result applies through a real Statement so plugin event handlers
and cache eviction stay intact.

Because phase-1 discards are resolved ON DEVICE (shadow-buffer
rollback), the returned (evicted, pipelined) sets are the committed
outcome only — the host application is a single bulk statement:

  1. validate every pipelined placement (plugin predicates on the
     proposed node — the host preempt path's predicate set);
  2. evict the device-chosen victims (global eviction order);
  3. pipeline each preemptor after an O(R) fit check against the node's
     updated future_idle.

Any validation failure discards the bulk statement and falls back to
the pure host PreemptAction — semantics never degrade below the host
path (the same guarantee jax-allocate gives per-task, here per-pass
since preemption outcomes are interdependent).
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

from volcano_tpu.actions.preempt import PreemptAction
from volcano_tpu.api import FitError, TaskStatus
from volcano_tpu.framework.interface import Action
from volcano_tpu.framework.session import Session
from volcano_tpu.metrics import metrics
from volcano_tpu.utils.logging import get_logger

log = get_logger(__name__)


class JaxPreemptAction(Action):
    def __init__(self, weights=None):
        from volcano_tpu.ops.kernels import DEFAULT_WEIGHTS

        self.weights = weights or DEFAULT_WEIGHTS

    def name(self) -> str:
        return "jax-preempt"

    def _device_outcome(self, pk) -> Tuple[np.ndarray, np.ndarray]:
        """(evicted[V] bool, pipelined_node[P]) via the selected executor
        — the compute-plane sidecar when configured — degrading pallas →
        dense on runtime failure (the same native-path degradation
        discipline run_packed_auto uses)."""
        from volcano_tpu.ops.kernels import DEFAULT_WEIGHTS

        if self.weights == DEFAULT_WEIGHTS:
            # wire protocol carries no weights — only default-configured
            # sessions may route through the sidecar
            from volcano_tpu.ops.executor import execute_preempt

            return execute_preempt(pk)
        from volcano_tpu.ops.dispatch import run_preempt_auto

        return run_preempt_auto(pk, weights=self.weights)

    def execute(self, ssn: Session) -> None:
        from volcano_tpu.ops.preempt_pack import pack_preempt_session

        try:
            pk = pack_preempt_session(ssn)
        except ValueError as e:
            # unsupported preemptable tier configuration → host path
            log.info("preempt pack refused (%s); host fallback", e)
            PreemptAction().execute(ssn)
            return
        if pk.base.n_tasks == 0:
            return
        if pk.base.needs_host_validation:
            # relational predicates the packer could not encode: the bulk
            # apply below re-validates every placement, but victim
            # *selection* could still diverge — run the host action.
            PreemptAction().execute(ssn)
            return

        evicted, pipelined = self._device_outcome(pk)

        if not evicted.any() and not (pipelined >= 0).any():
            # nothing to evict — the preemptors stay Pending; explain
            # the ones the device proves fit no node at all, so the
            # Unschedulable event/condition writeback fires like on a
            # host-scheduled cycle (ops/explain)
            from volcano_tpu.ops.explain import (
                synthesize_no_victim_explanations,
            )

            synthesize_no_victim_explanations(ssn, pk)
            metrics.register_preemption_attempts()
            return

        stmt = ssn.statement()
        try:
            # victims in global (node-major) eviction order
            for i in np.nonzero(evicted)[0]:
                job = ssn.jobs.get(pk.job_uids[pk.vic_job[i]])
                task = job.tasks.get(pk.vic_uids[i]) if job else None
                if task is None or task.status != TaskStatus.Running:
                    raise FitError(task, None, "victim vanished")
                stmt.evict(task, "preempt")
            # pipelines in task order, validated against the live session
            # (ptasks are laid out job-contiguously: base.task_job[p] is
            # the owning job row — O(1) lookup, not a session scan)
            for p in np.nonzero(pipelined >= 0)[0]:
                node = ssn.nodes.get(pk.node_names[pipelined[p]])
                job = ssn.jobs.get(pk.job_uids[pk.base.task_job[p]])
                task = job.tasks.get(pk.ptask_uids[p]) if job else None
                if task is None or node is None:
                    raise FitError(task, node, "preemptor vanished")
                ssn.predicate_fn(task, node)  # raises FitError on veto
                if not task.init_resreq.less_equal(node.future_idle()):
                    raise FitError(task, node, "device fit diverged")
                stmt.pipeline(task, node.name)
        except FitError as e:
            # Fall back WITHOUT recording metrics here — the host action
            # records its own attempts/victims (no double count).
            log.error("device preempt apply diverged (%s); host fallback", e)
            stmt.discard()
            PreemptAction().execute(ssn)
            return
        # committed — record what actually happened
        metrics.update_preemption_victims_count(int(evicted.sum()))
        metrics.register_preemption_attempts()
        stmt.commit()


def new() -> JaxPreemptAction:
    return JaxPreemptAction()
