"""jax-reclaim — the reclaim action with the victim-selection replay on
the tensorized formulation.

Reference behavior: pkg/scheduler/actions/reclaim/reclaim.go:42-202.
Design mirrors actions/jax_preempt.py: the host packs the session
(ops/reclaim_pack.pack_reclaim_session), the dense replay decides the
whole pass (``reclaim_dense`` — vectorized victim eligibility/summing
per node attempt, proven ≡ the host ReclaimAction in
tests/test_reclaim_kernel.py), and the result applies through a real
Statement so plugin event handlers and cache eviction stay intact.

Any validation failure discards the bulk statement and falls back to
the pure host ReclaimAction — semantics never degrade below the host
path.  (Unlike preempt, reclaim never checks node resource fit — only
the predicate set — so apply validates predicates alone,
reclaim.go:123-126.)
"""

from __future__ import annotations

import numpy as np

from volcano_tpu.actions.reclaim import ReclaimAction
from volcano_tpu.api import FitError, TaskStatus
from volcano_tpu.framework.interface import Action
from volcano_tpu.framework.session import Session
from volcano_tpu.utils.logging import get_logger

log = get_logger(__name__)


class JaxReclaimAction(Action):
    def name(self) -> str:
        return "jax-reclaim"

    def execute(self, ssn: Session) -> None:
        from volcano_tpu.ops.reclaim_pack import pack_reclaim_session, reclaim_dense

        try:
            pk = pack_reclaim_session(ssn)
        except ValueError as e:
            log.info("reclaim pack refused (%s); host fallback", e)
            ReclaimAction().execute(ssn)
            return
        if pk.base.n_tasks == 0:
            return
        if pk.base.needs_host_validation:
            ReclaimAction().execute(ssn)
            return

        evicted, pipelined = reclaim_dense(pk)
        if not evicted.any() and not (pipelined >= 0).any():
            # no reclaimable victims — explain the provably-unplaceable
            # reclaimers (same no-victim discipline as jax-preempt)
            from volcano_tpu.ops.explain import (
                synthesize_no_victim_explanations,
            )

            synthesize_no_victim_explanations(ssn, pk)
            return

        stmt = ssn.statement()
        try:
            for i in np.nonzero(evicted)[0]:
                job = ssn.jobs.get(pk.job_uids[pk.vic_job[i]])
                task = job.tasks.get(pk.vic_uids[i]) if job else None
                if task is None or task.status != TaskStatus.Running:
                    raise FitError(task, None, "victim vanished")
                stmt.evict(task, "reclaim")
            for p in np.nonzero(pipelined >= 0)[0]:
                node = ssn.nodes.get(pk.node_names[pipelined[p]])
                job = ssn.jobs.get(pk.job_uids[pk.base.task_job[p]])
                task = job.tasks.get(pk.ptask_uids[p]) if job else None
                if task is None or node is None:
                    raise FitError(task, node, "reclaimer vanished")
                ssn.predicate_fn(task, node)  # raises FitError on veto
                stmt.pipeline(task, node.name)
        except FitError as e:
            log.error("dense reclaim apply diverged (%s); host fallback", e)
            stmt.discard()
            ReclaimAction().execute(ssn)
            return
        stmt.commit()


def new() -> JaxReclaimAction:
    return JaxReclaimAction()
