"""Preempt action — in-queue preemption for starving jobs.

Reference: pkg/scheduler/actions/preempt/preempt.go.
"""

from __future__ import annotations

from typing import Callable, Dict, List

from volcano_tpu.api import TaskInfo, TaskStatus
from volcano_tpu.api.resource import empty_resource
from volcano_tpu.apis import scheduling
from volcano_tpu.framework.interface import Action
from volcano_tpu.framework.session import Session
from volcano_tpu.framework.statement import Statement
from volcano_tpu.metrics import metrics
from volcano_tpu.scheduler import util as sched_util
from volcano_tpu.utils.priority_queue import PriorityQueue


class PreemptAction(Action):
    def name(self) -> str:
        return "preempt"

    def execute(self, ssn: Session) -> None:
        """preempt.go:45-177."""
        if ssn._trace.enabled:
            ssn._trace.event("preempt:start", "action", jobs=len(ssn.jobs))
        preemptors_map: Dict[str, PriorityQueue] = {}
        preemptor_tasks: Dict[str, PriorityQueue] = {}
        under_request: List = []
        queues: Dict[str, object] = {}

        for job in sorted(ssn.jobs.values(), key=lambda j: j.uid):
            if (
                job.pod_group is not None
                and job.pod_group.status.phase == scheduling.POD_GROUP_PENDING
            ):
                continue
            vr = ssn.job_valid(job)
            if vr is not None and not vr.pass_:
                continue
            queue = ssn.queues.get(job.queue)
            if queue is None:
                continue
            queues.setdefault(queue.uid, queue)

            # Starving: pending tasks and not pipelined (preempt.go:72-82).
            if job.task_status_index.get(TaskStatus.Pending) and not ssn.job_pipelined(job):
                preemptors_map.setdefault(job.queue, PriorityQueue(ssn.job_order_fn)).push(job)
                under_request.append(job)
                tasks = PriorityQueue(ssn.task_order_fn)
                for task in sorted(
                    job.task_status_index[TaskStatus.Pending].values(),
                    key=lambda t: t.uid,
                ):
                    tasks.push(task)
                preemptor_tasks[job.uid] = tasks

        for queue in queues.values():
            # Preemption between jobs within queue (preempt.go:86-143).
            while True:
                preemptors = preemptors_map.get(queue.uid)
                if preemptors is None or preemptors.empty():
                    break
                preemptor_job = preemptors.pop()

                stmt = ssn.statement()
                assigned = False
                while True:
                    if ssn.job_pipelined(preemptor_job):
                        break
                    if preemptor_tasks[preemptor_job.uid].empty():
                        break
                    preemptor = preemptor_tasks[preemptor_job.uid].pop()

                    def job_filter(task: TaskInfo) -> bool:
                        if task.status != TaskStatus.Running:
                            return False
                        job = ssn.jobs.get(task.job)
                        if job is None:
                            return False
                        return job.queue == preemptor_job.queue and preemptor.job != task.job

                    if _preempt(ssn, stmt, preemptor, job_filter):
                        assigned = True

                if ssn.job_pipelined(preemptor_job):
                    stmt.commit()
                else:
                    stmt.discard()
                    continue

                if assigned:
                    preemptors.push(preemptor_job)

            # Preemption between tasks within job (preempt.go:146-175).
            for job in under_request:
                while True:
                    tasks = preemptor_tasks.get(job.uid)
                    if tasks is None or tasks.empty():
                        break
                    preemptor = tasks.pop()
                    stmt = ssn.statement()
                    assigned = _preempt(
                        ssn,
                        stmt,
                        preemptor,
                        lambda task: task.status == TaskStatus.Running
                        and preemptor.job == task.job,
                    )
                    stmt.commit()
                    if not assigned:
                        break


def _preempt(
    ssn: Session,
    stmt: Statement,
    preemptor: TaskInfo,
    filter_fn: Callable[[TaskInfo], bool],
) -> bool:
    """preempt.go:181-259."""
    all_nodes = sched_util.get_node_list(ssn.nodes)
    predicate_nodes, _ = sched_util.predicate_nodes(preemptor, all_nodes, ssn.predicate_fn)
    node_scores = sched_util.prioritize_nodes(
        preemptor,
        predicate_nodes,
        ssn.batch_node_order_fn,
        ssn.node_order_map_fn,
        ssn.node_order_reduce_fn,
    )
    selected_nodes = sched_util.sort_nodes(node_scores)

    assigned = False
    for node in selected_nodes:
        preemptees = [
            task.clone()
            for task in sorted(node.tasks.values(), key=lambda t: t.uid)
            if filter_fn(task)
        ]
        victims = ssn.preemptable(preemptor, preemptees)
        metrics.update_preemption_victims_count(len(victims))

        if not _validate_victims(preemptor, node, victims):
            continue

        # Lowest-priority victims first (preempt.go:216-221).  The
        # reference inverts with `!TaskOrderFn`, which makes equal-order
        # pop sequence heap-structural (unspecified); swapping the
        # arguments instead gives the same inverted order with a
        # well-defined stable tie-break (insertion = uid order) — required
        # for bindings-equivalence with the device path.
        victims_queue = PriorityQueue(lambda l, r: ssn.task_order_fn(r, l))
        for victim in victims:
            victims_queue.push(victim)

        preempted = empty_resource()
        while not victims_queue.empty():
            if preemptor.init_resreq.less_equal(node.future_idle()):
                break
            preemptee = victims_queue.pop()
            stmt.evict(preemptee, "preempt")
            preempted.add(preemptee.resreq)

        metrics.register_preemption_attempts()

        if preemptor.init_resreq.less_equal(node.future_idle()):
            stmt.pipeline(preemptor, node.name)
            assigned = True
            break

    return assigned


def _validate_victims(preemptor: TaskInfo, node, victims: List[TaskInfo]) -> bool:
    """preempt.go:261-276."""
    if not victims:
        return False
    future_idle = node.future_idle()
    for victim in victims:
        future_idle.add(victim.resreq)
    return preemptor.init_resreq.less_equal(future_idle)


def new() -> PreemptAction:
    return PreemptAction()
