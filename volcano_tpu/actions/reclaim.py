"""Reclaim action — cross-queue reclaim for underserved queues.

Reference: pkg/scheduler/actions/reclaim/reclaim.go.
"""

from __future__ import annotations

from typing import Dict

from volcano_tpu.api import FitError, TaskStatus
from volcano_tpu.api.resource import empty_resource
from volcano_tpu.apis import scheduling
from volcano_tpu.framework.interface import Action
from volcano_tpu.framework.session import Session
from volcano_tpu.scheduler import util as sched_util
from volcano_tpu.utils.priority_queue import PriorityQueue


class ReclaimAction(Action):
    def name(self) -> str:
        return "reclaim"

    def execute(self, ssn: Session) -> None:
        """reclaim.go:42-202."""
        if ssn._trace.enabled:
            ssn._trace.event("reclaim:start", "action", jobs=len(ssn.jobs))
        queues = PriorityQueue(ssn.queue_order_fn)
        queue_map: Dict[str, object] = {}
        preemptors_map: Dict[str, PriorityQueue] = {}
        preemptor_tasks: Dict[str, PriorityQueue] = {}

        for job in sorted(ssn.jobs.values(), key=lambda j: j.uid):
            if (
                job.pod_group is not None
                and job.pod_group.status.phase == scheduling.POD_GROUP_PENDING
            ):
                continue
            vr = ssn.job_valid(job)
            if vr is not None and not vr.pass_:
                continue
            queue = ssn.queues.get(job.queue)
            if queue is None:
                continue
            if queue.uid not in queue_map:
                queue_map[queue.uid] = queue
                queues.push(queue)

            if job.task_status_index.get(TaskStatus.Pending):
                preemptors_map.setdefault(job.queue, PriorityQueue(ssn.job_order_fn)).push(job)
                tasks = PriorityQueue(ssn.task_order_fn)
                for task in sorted(
                    job.task_status_index[TaskStatus.Pending].values(),
                    key=lambda t: t.uid,
                ):
                    tasks.push(task)
                preemptor_tasks[job.uid] = tasks

        while not queues.empty():
            queue = queues.pop()
            if ssn.overused(queue):
                continue

            jobs = preemptors_map.get(queue.uid)
            if jobs is None or jobs.empty():
                continue
            job = jobs.pop()

            tasks = preemptor_tasks.get(job.uid)
            if tasks is None or tasks.empty():
                continue
            task = tasks.pop()

            assigned = False
            for node in sched_util.get_node_list(ssn.nodes):
                # If predicates failed, next node (reclaim.go:123-126).
                try:
                    ssn.predicate_fn(task, node)
                except FitError:
                    continue

                resreq = task.init_resreq.clone()
                reclaimed = empty_resource()

                reclaimees = [
                    t.clone()
                    for t in sorted(node.tasks.values(), key=lambda t: t.uid)
                    if t.status == TaskStatus.Running
                    and t.job in ssn.jobs
                    and ssn.jobs[t.job].queue != job.queue
                ]
                victims = ssn.reclaimable(task, reclaimees)
                if not victims:
                    continue

                # Enough victim resources in total? (reclaim.go:155-163)
                all_res = empty_resource()
                for v in victims:
                    all_res.add(v.resreq)
                if not resreq.less_equal(all_res):
                    continue

                # Evict until reclaimed enough (reclaim.go:165-180).
                for reclaimee in victims:
                    try:
                        ssn.evict(reclaimee, "reclaim")
                    except Exception:  # noqa: BLE001 — try next victim
                        continue
                    reclaimed.add(reclaimee.resreq)
                    if resreq.less_equal(reclaimed):
                        break

                if task.init_resreq.less_equal(reclaimed):
                    ssn.pipeline(task, node.name)
                    assigned = True
                    break

            # Only the queue returns for another round (reclaim.go:197-199).
            if assigned:
                queues.push(queue)


def new() -> ReclaimAction:
    return ReclaimAction()
