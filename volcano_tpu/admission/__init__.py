"""Admission webhooks: job validation/mutation + pod creation gate.

Reference: pkg/admission — jobs/validate/admit_job.go, jobs/mutate/
mutate_job.go, pods/admit_pod.go, wired through the router into the API
server (here: registered as in-process admission hooks, the standalone
equivalent of webhook configurations with CA bundles).
"""

from volcano_tpu.admission.jobs import DEFAULT_QUEUE, mutate_job, validate_job
from volcano_tpu.admission.pods import validate_pod
from volcano_tpu.admission.server import register_webhooks

__all__ = ["mutate_job", "validate_job", "validate_pod", "register_webhooks"]
