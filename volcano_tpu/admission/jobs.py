"""Job admission: defaulting mutation + deep validation.

Reference: pkg/admission/jobs/mutate/mutate_job.go:105-143 and
jobs/validate/admit_job.go:103-258.

Validation SUBSET note: this module checks job/task naming (DNS-1123),
replica/minAvailable arithmetic, duplicate task names, policy event/
action legality (incl. exclusiveness rules), container identity
(DNS-1123 names, non-empty image), resource quantity syntax and
requests≤limits, restart-policy allowed values, port legality, env-var
names, volume-mount/volume cross-references, and pod volume/hostname/
subdomain identity.  The reference runs the complete vendored k8s
PodTemplateSpec validators (admit_job.go:194+ → k8s
validation.ValidatePodTemplateSpec); fields outside this subset
(probes, security contexts, lifecycle hooks) fail at pod-creation time
rather than at admission.  Documented in README "Known gaps".
"""

from __future__ import annotations

import re
from typing import List, Optional

from volcano_tpu.apis import batch
from volcano_tpu.client.apiserver import AdmissionError, APIServer

DEFAULT_QUEUE = "default"

_DNS1123_RE = re.compile(r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?$")
_DNS1123_MAX = 63


def is_dns1123_label(name: str) -> bool:
    return len(name) <= _DNS1123_MAX and bool(_DNS1123_RE.match(name))


def mutate_job(job: batch.Job) -> batch.Job:
    """Defaulting patch: queue="default", task name=default<idx>
    (mutate_job.go:105-143)."""
    if not job.spec.queue:
        job.spec.queue = DEFAULT_QUEUE
    for index, task in enumerate(job.spec.tasks):
        if not task.name:
            task.name = f"{batch.DEFAULT_TASK_SPEC}{index}"
    return job


def _validate_policies(policies: List[batch.LifecyclePolicy], path: str) -> List[str]:
    """admit_job.go validatePolicies: event/action legality, event+events
    exclusivity, duplicates, exit code rules."""
    msgs: List[str] = []
    events_seen = set()
    exit_codes_seen = set()
    for policy in policies:
        if policy.event and policy.events:
            msgs.append(f"{path}: both event and events are specified")
        for event in [policy.event, *policy.events]:
            if event and event not in batch.VALID_EVENTS:
                msgs.append(f"{path}: invalid event {event}")
            if event:
                if event in events_seen:
                    msgs.append(f"{path}: duplicate event {event}")
                events_seen.add(event)
        if policy.action and policy.action not in batch.VALID_ACTIONS:
            msgs.append(f"{path}: invalid action {policy.action}")
        if policy.exit_code is not None:
            if policy.exit_code == 0:
                msgs.append(f"{path}: 0 is not a valid error code")
            if policy.exit_code in exit_codes_seen:
                msgs.append(f"{path}: duplicate exitCode {policy.exit_code}")
            exit_codes_seen.add(policy.exit_code)
        if not policy.event and not policy.events and policy.exit_code is None:
            msgs.append(f"{path}: either event(s) or exitCode must be specified")
    return msgs


_VALID_RESTART_POLICIES = {"", "Always", "OnFailure", "Never"}
_VALID_PROTOCOLS = {"TCP", "UDP", "SCTP"}
#: k8s validation.IsEnvVarName
_ENV_NAME_RE = re.compile(r"^[-._a-zA-Z][-._a-zA-Z0-9]*$")


def _validate_task_template(task: batch.TaskSpec, index: int) -> List[str]:
    """admit_job.go:194+ validateTaskTemplate — the used subset of the
    k8s pod-template validators: container identity, resource quantity
    parse + requests≤limits, restart policy, port legality."""
    from volcano_tpu.apis import quantity

    msgs: List[str] = []
    path = f"spec.tasks[{index}].template"
    spec = task.template.spec

    if spec.restart_policy not in _VALID_RESTART_POLICIES:
        msgs.append(
            f"{path}.spec.restartPolicy: unsupported value "
            f"{spec.restart_policy!r};"
        )

    # pod-level identity: volume names unique + DNS-1123; hostname /
    # subdomain DNS-1123 when set (k8s ValidatePodSpec)
    volume_names = set()
    for vi, vol in enumerate(spec.volumes or []):
        vpath = f"{path}.spec.volumes[{vi}]"
        # invalid names are flagged once and kept OUT of volume_names:
        # they can't satisfy a mount reference, and two unnamed volumes
        # are not "duplicates" of each other
        if not vol.name or not is_dns1123_label(vol.name):
            msgs.append(f"{vpath}.name: must be a valid DNS-1123 label;")
        elif vol.name in volume_names:
            msgs.append(f"{vpath}.name: duplicate volume name {vol.name!r};")
        else:
            volume_names.add(vol.name)
    if spec.hostname and not is_dns1123_label(spec.hostname):
        msgs.append(f"{path}.spec.hostname: must be a valid DNS-1123 label;")
    if spec.subdomain and not is_dns1123_label(spec.subdomain):
        msgs.append(f"{path}.spec.subdomain: must be a valid DNS-1123 label;")

    container_names = set()
    all_containers = [
        (f"{path}.spec.initContainers[{ci}]", c)
        for ci, c in enumerate(getattr(spec, "init_containers", []) or [])
    ] + [
        (f"{path}.spec.containers[{ci}]", c)
        for ci, c in enumerate(spec.containers)
    ]
    for cpath, container in all_containers:
        # port dedup is PER CONTAINER (k8s allows two containers to
        # declare the same containerPort; only hostPort conflicts matter
        # across containers, which scheduling handles)
        port_keys = set()
        port_names = set()
        if not container.name or not is_dns1123_label(container.name):
            msgs.append(f"{cpath}.name: must be a valid DNS-1123 label;")
        if container.name in container_names:
            msgs.append(f"{cpath}.name: duplicate container name {container.name!r};")
        container_names.add(container.name)

        # k8s validation.ValidateContainers: image is required — an
        # imageless template is undeployable and previously failed only
        # at pod-creation time, far from the submitter (admit_job.go:194+)
        if not container.image:
            msgs.append(f"{cpath}.image: required;")

        resources = container.resources or {}
        parsed = {}
        for field_name in ("requests", "limits"):
            for res, value in (resources.get(field_name) or {}).items():
                try:
                    parsed[(field_name, res)] = quantity.parse_quantity(value)
                except (ValueError, TypeError):
                    msgs.append(
                        f"{cpath}.resources.{field_name}[{res}]: "
                        f"invalid quantity {value!r};"
                    )
                    continue
                if parsed[(field_name, res)] < 0:
                    msgs.append(
                        f"{cpath}.resources.{field_name}[{res}]: "
                        "must be non-negative;"
                    )
        for res in resources.get("requests") or {}:
            req = parsed.get(("requests", res))
            lim = parsed.get(("limits", res))
            if req is not None and lim is not None and req > lim:
                msgs.append(
                    f"{cpath}.resources.requests[{res}]: "
                    "must be less than or equal to the limit;"
                )

        for ei, env in enumerate(container.env or []):
            epath = f"{cpath}.env[{ei}]"
            # duplicates are VALID in k8s (last entry wins) — only the
            # name syntax is checked, matching validation.ValidateEnv
            if not env.name or not _ENV_NAME_RE.match(env.name):
                msgs.append(f"{epath}.name: not a valid environment variable name;")

        mount_paths_seen = set()
        for mi, mount in enumerate(container.volume_mounts or []):
            mpath = f"{cpath}.volumeMounts[{mi}]"
            if not mount.mount_path:
                msgs.append(f"{mpath}.mountPath: required;")
            elif mount.mount_path in mount_paths_seen:
                msgs.append(
                    f"{mpath}.mountPath: duplicate mount path "
                    f"{mount.mount_path!r};"
                )
            mount_paths_seen.add(mount.mount_path)
            if mount.name not in volume_names:
                msgs.append(
                    f"{mpath}.name: volume {mount.name!r} not declared "
                    "in spec.volumes;"
                )

        for pi, port in enumerate(container.ports):
            ppath = f"{cpath}.ports[{pi}]"
            if not (0 < port.container_port < 65536):
                msgs.append(f"{ppath}.containerPort: must be between 1 and 65535;")
            if port.host_port and not (0 < port.host_port < 65536):
                msgs.append(f"{ppath}.hostPort: must be between 1 and 65535;")
            if port.protocol and port.protocol not in _VALID_PROTOCOLS:
                msgs.append(f"{ppath}.protocol: unsupported protocol {port.protocol!r};")
            if port.name:
                if port.name in port_names:
                    msgs.append(f"{ppath}.name: duplicate port name {port.name!r};")
                port_names.add(port.name)
            key = (port.container_port, port.protocol or "TCP")
            if key in port_keys:
                msgs.append(
                    f"{ppath}.containerPort: duplicate port "
                    f"{port.container_port}/{port.protocol or 'TCP'};"
                )
            port_keys.add(key)
    return msgs


def validate_job(job: batch.Job, api: Optional[APIServer] = None) -> None:
    """admit_job.go:103-192 — raises AdmissionError on the first deny."""
    if job.spec.min_available <= 0:
        raise AdmissionError("'minAvailable' must be greater than zero.")
    if job.spec.max_retry < 0:
        raise AdmissionError("'maxRetry' cannot be less than zero.")
    if (
        job.spec.ttl_seconds_after_finished is not None
        and job.spec.ttl_seconds_after_finished < 0
    ):
        raise AdmissionError("'ttlSecondsAfterFinished' cannot be less than zero.")
    if not job.spec.tasks:
        raise AdmissionError("No task specified in job spec")

    msgs: List[str] = []
    task_names = set()
    total_replicas = 0
    for index, task in enumerate(job.spec.tasks):
        if task.replicas <= 0:
            msgs.append(f"'replicas' is not set positive in task: {task.name};")
        total_replicas += max(task.replicas, 0)
        if not is_dns1123_label(task.name):
            msgs.append(f"task name {task.name!r} must be a valid DNS-1123 label;")
        if task.name in task_names:
            msgs.append(f"duplicated task name {task.name};")
            break
        task_names.add(task.name)
        msgs.extend(_validate_policies(task.policies, f"spec.tasks[{index}].policies"))
        if not task.template.spec.containers:
            msgs.append(f"task {task.name} has no containers in pod template;")
        else:
            msgs.extend(_validate_task_template(task, index))

    if total_replicas < job.spec.min_available:
        msgs.append("'minAvailable' should not be greater than total replicas in tasks;")

    msgs.extend(_validate_policies(job.spec.policies, "spec.policies"))

    # Plugin existence (admit_job.go:169-176).
    from volcano_tpu.controllers.job.plugins import get_plugin_builder

    for name in job.spec.plugins:
        if get_plugin_builder(name) is None:
            msgs.append(f"unable to find job plugin: {name}")

    # Duplicated volume mount paths (validateIO).
    mount_paths = set()
    for volume in job.spec.volumes:
        if not volume.mount_path:
            msgs.append("mountPath is required;")
        elif volume.mount_path in mount_paths:
            msgs.append(f"duplicated mountPath: {volume.mount_path};")
        mount_paths.add(volume.mount_path)

    # Queue existence (admit_job.go:179-185).
    if api is not None:
        if api.get("Queue", "", job.spec.queue) is None:
            msgs.append(f"unable to find job queue: {job.spec.queue}")

    if msgs:
        raise AdmissionError(" ".join(msgs))
