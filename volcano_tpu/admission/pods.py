"""Pod admission gate — delay pod creation until its PodGroup leaves
Pending.

Reference: pkg/admission/pods/admit_pod.go:96-134 (the delay-pod-creation
design, docs/design/delay-pod-creation.md).
"""

from __future__ import annotations

from typing import Optional

from volcano_tpu.apis import core, scheduling
from volcano_tpu.client.apiserver import AdmissionError, APIServer


def validate_pod(
    pod: core.Pod, api: APIServer, scheduler_name: str = "volcano-tpu"
) -> None:
    """Allow when (1) not our scheduler, (2) podgroup exists and is
    non-pending, (3) normal pod with no podgroup yet."""
    if pod.spec.scheduler_name != scheduler_name:
        return

    pg_name = pod.metadata.annotations.get(scheduling.GROUP_NAME_ANNOTATION_KEY, "")
    if pg_name:
        # vc-job pod: podgroup must exist and be past Pending.
        pg = api.get("PodGroup", pod.metadata.namespace, pg_name)
        if pg is None:
            raise AdmissionError(
                f"failed to create pod <{pod.key()}>: cannot find PodGroup {pg_name}"
            )
        if pg.status.phase == scheduling.POD_GROUP_PENDING:
            raise AdmissionError(
                f"failed to create pod <{pod.key()}>: PodGroup {pg_name} is Pending"
            )
        return

    # Normal pod: its auto-created podgroup (podgroup controller) may not
    # exist yet — allowed; once it exists it must be past Pending.
    from volcano_tpu.controllers.podgroup_controller import pod_group_name

    pg = api.get("PodGroup", pod.metadata.namespace, pod_group_name(pod))
    if pg is not None and pg.status.phase == scheduling.POD_GROUP_PENDING:
        raise AdmissionError(
            f"failed to create pod <{pod.key()}>: PodGroup {pod_group_name(pod)} is Pending"
        )
