"""Webhook registration — wires admission hooks into the API server.

Reference: cmd/admission/app/server.go:37-99 + pkg/admission/router
(path→handler registry; TLS/CA plumbing has no in-process equivalent).
"""

from __future__ import annotations

from volcano_tpu.client.apiserver import APIServer


def register_webhooks(
    api: APIServer,
    scheduler_name: str = "volcano-tpu",
    gate_pods: bool = False,
) -> None:
    """Register mutate-then-validate hooks for Jobs and (optionally) the
    pod-creation gate.  ``gate_pods`` mirrors deploying the pod webhook —
    off by default like the reference's optional configuration."""
    from volcano_tpu.admission.jobs import mutate_job, validate_job
    from volcano_tpu.admission.pods import validate_pod

    def job_hook(operation: str, job):
        job = mutate_job(job)
        validate_job(job, api)
        return job

    api.register_admission("Job", "CREATE", job_hook)
    api.register_admission("Job", "UPDATE", job_hook)

    if gate_pods:
        api.register_admission(
            "Pod",
            "CREATE",
            lambda op, pod: (validate_pod(pod, api, scheduler_name), pod)[1],
        )
