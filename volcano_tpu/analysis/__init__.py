"""Project-invariant static analysis — the staticcheck/`go vet` analogue
for this port.

The reference is ~45k LoC of Go kept honest by `go vet`, staticcheck and
`go test -race`; the invariants this port grew instead — "guarded by
``self._mutex``", "no wall-clock/RNG in replay-critical paths",
"jit-cache-stable kernel signatures", "version-gated VBUS ops" — lived
only in docstrings and reviewer memory.  This package makes them
machine-checked:

* :mod:`~volcano_tpu.analysis.lock_discipline` — attributes declared
  ``# guarded-by: <lock>`` may only be touched inside a ``with <lock>``
  scope (or a function annotated ``# requires-lock: <lock>``).
* :mod:`~volcano_tpu.analysis.determinism` — replay-critical modules
  (trace/, faults/, ops/, actions/, cache/) must not reach wall-clock,
  unseeded RNG, or order-escaping ``set`` iteration except through the
  explicit ``# det:`` allowlist.
* :mod:`~volcano_tpu.analysis.jit_safety` — jitted functions keep
  stable static signatures: no data-dependent Python branches on
  tracers, no ``.item()`` / ``float()`` concretization inside jit, no
  reuse of a donated buffer after the donating call.
* :mod:`~volcano_tpu.analysis.serde_drift` — every frame kind in
  ``bus/protocol.py`` has a serde round-trip exemplar, every bus op
  is version-registered (ops past ``MIN_VERSION`` must carry the
  old-peer fallback), and the README's VBUS version ladder declares
  the current version and names every registered op (SRD005).
* :mod:`~volcano_tpu.analysis.metric_hygiene` — every Counter/Histogram
  label with a non-literal value declares a statically bounded
  vocabulary (docstring ``label ∈ {...}`` or ``# label-vocab:``), and
  every catalog helper in ``metrics/metrics.py`` is observed by some
  product module (no dead dashboard entries).

Run ``python -m volcano_tpu.analysis`` (or ``vtctl lint``); CI fails on
any finding not suppressed in the checked-in ``baseline.json``.

The runtime half is three engines:

* :mod:`~volcano_tpu.analysis.lock_order` — the opt-in
  (``VTPU_LOCK_ORDER=1``) instrumented-lock wrapper that records the
  cross-thread lock-acquisition graph during the chaos / commit-plane
  suites and fails on cycles.
* :mod:`~volcano_tpu.analysis.race` — the opt-in (``VTPU_RACE=1``)
  happens-before race detector: vector clocks over the same lock
  proxies plus thread/queue/event sync edges, with every
  ``# guarded-by:``-declared attribute wrapped in a tracking
  descriptor, so aliased and cross-module accesses the lexical pass
  cannot see are checked at runtime (declaration layer: LCK;
  enforcement layer: this).
* :mod:`~volcano_tpu.analysis.explore` — the deterministic
  interleaving explorer (``vtctl explore``): the election / lease /
  gang-assembly protocols swept across hundreds of seed-replayable
  schedules with four invariants asserted after every step.
"""

from volcano_tpu.analysis.core import (  # noqa: F401 — public surface
    Baseline,
    Finding,
    SourceFile,
    iter_source_files,
    run_passes,
)

PASSES = ("lock", "det", "jit", "serde", "mtr")
