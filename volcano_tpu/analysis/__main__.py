"""``python -m volcano_tpu.analysis`` — run the lint suite.

Exit status: 0 when every finding is suppressed by the checked-in
baseline (or the tree is clean), 1 on any unsuppressed finding, 2 on
stale baseline entries with ``--strict-baseline`` (the default in CI:
a suppression whose finding no longer exists must be deleted, or the
baseline rots into a list nobody can audit).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from volcano_tpu.analysis import PASSES
from volcano_tpu.analysis.core import Baseline, run_passes

DEFAULT_BASELINE = "volcano_tpu/analysis/baseline.json"


def find_root(start: Optional[str] = None) -> str:
    """Walk up from ``start`` (default: this package) to the directory
    holding the ``volcano_tpu`` package — the analysis root."""
    d = os.path.abspath(start or os.path.join(os.path.dirname(__file__)))
    while True:
        if os.path.isdir(os.path.join(d, "volcano_tpu")):
            return d
        parent = os.path.dirname(d)
        if parent == d:
            raise SystemExit("cannot locate the volcano_tpu package root")
        d = parent


def main(argv: Optional[List[str]] = None, out=None) -> int:
    out = out or sys.stdout
    parser = argparse.ArgumentParser(
        prog="python -m volcano_tpu.analysis",
        description="project-invariant static analysis "
                    "(lock discipline / determinism / jit safety / "
                    "VBUS serde drift)",
    )
    parser.add_argument("--root", default=None,
                        help="repo root (default: auto-detected)")
    parser.add_argument("--baseline", default=None,
                        help=f"suppression file (default: {DEFAULT_BASELINE})")
    parser.add_argument("--pass", dest="passes", action="append",
                        choices=PASSES,
                        help="run only this pass (repeatable)")
    parser.add_argument("--report", default=None,
                        help="write a JSON findings report here")
    parser.add_argument("--write-baseline", action="store_true",
                        help="write every current finding to the baseline "
                             "(then edit the TODO reasons)")
    parser.add_argument("--no-strict-baseline", action="store_true",
                        help="tolerate stale baseline entries")
    args = parser.parse_args(argv)

    root = args.root or find_root()
    baseline_path = args.baseline or os.path.join(root, DEFAULT_BASELINE)

    findings = run_passes(root, passes=args.passes)

    if args.write_baseline:
        Baseline.write(baseline_path, findings)
        print(f"wrote {len(findings)} suppression(s) to {baseline_path}",
              file=out)
        return 0

    baseline = Baseline.load(baseline_path)
    unsuppressed, suppressed, stale = baseline.split(findings)
    # a partial run (--pass) must not judge the other passes' entries
    if args.passes:
        stale = [e for e in stale if e["pass"] in set(args.passes)]

    if args.report:
        with open(args.report, "w", encoding="utf-8") as f:
            json.dump({
                "findings": [f_.__dict__ for f_ in unsuppressed],
                "suppressed": [f_.__dict__ for f_ in suppressed],
                "stale_baseline_entries": stale,
            }, f, indent=2)
            f.write("\n")

    for f_ in unsuppressed:
        print(f_.render(), file=out)
    if stale and not args.no_strict_baseline:
        for e in stale:
            print(
                f"stale baseline entry (finding no longer exists): "
                f"{e['pass']}/{e['code']} {e['file']} {e['symbol']}",
                file=out,
            )
    print(
        f"analysis: {len(unsuppressed)} finding(s), "
        f"{len(suppressed)} suppressed, {len(stale)} stale baseline "
        f"entr{'y' if len(stale) == 1 else 'ies'}",
        file=out,
    )
    if unsuppressed:
        return 1
    if stale and not args.no_strict_baseline:
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
