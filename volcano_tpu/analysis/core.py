"""Shared machinery for the analysis passes: parsed source files with
comment markers, the Finding model, and the suppression baseline.

Findings are keyed by ``(pass, code, file, symbol)`` — deliberately NOT
by line number, so a baseline entry survives unrelated edits above it.
``symbol`` is the nearest stable anchor: ``Class.attr`` for a guarded
attribute, ``Class.method`` / ``function`` for code findings, the kind
or op name for serde findings.
"""

from __future__ import annotations

import ast
import io
import json
import os
import tokenize
from dataclasses import dataclass, field
from typing import Dict, Iterable, Iterator, List, Optional, Tuple

#: inline waiver markers, one per pass: a trailing comment
#: ``# <marker>: <reason>`` on the offending line waives the finding
#: (the reason is mandatory — a bare marker does not count).
MARKERS = {
    "lock": "unlocked-ok",
    "det": "det",
    "jit": "jit-ok",
    "serde": "serde-ok",
    "mtr": "mtr",
}


@dataclass(frozen=True)
class Finding:
    pass_id: str
    code: str
    file: str  # repo-relative posix path
    line: int
    symbol: str
    message: str

    @property
    def key(self) -> Tuple[str, str, str, str]:
        return (self.pass_id, self.code, self.file, self.symbol)

    def render(self) -> str:
        return (
            f"{self.file}:{self.line}: {self.code} [{self.pass_id}] "
            f"{self.symbol}: {self.message}"
        )


class SourceFile:
    """One parsed module: AST + per-line comments (via tokenize, so
    markers survive any formatting) + marker helpers."""

    def __init__(self, path: str, rel: str, text: str):
        self.path = path
        self.rel = rel
        self.text = text
        self.tree = ast.parse(text, filename=rel)
        self._lines = text.splitlines()
        #: line → comment text without the leading ``#``
        self.comments: Dict[int, str] = {}
        try:
            for tok in tokenize.generate_tokens(io.StringIO(text).readline):
                if tok.type == tokenize.COMMENT:
                    self.comments[tok.start[0]] = tok.string.lstrip("#").strip()
        except tokenize.TokenError:
            pass

    def marker(self, line: int, name: str) -> Optional[str]:
        """Return the reason of an inline ``# <name>: reason`` marker on
        ``line`` (or the directly preceding ``#:`` doc-comment block for
        declaration markers), else None."""
        for ln in (line, line - 1):
            comment = self.comments.get(ln)
            if comment is None:
                continue
            if ln != line and not self._comment_only(ln):
                # a TRAILING comment on the previous line annotates that
                # line, not this one — only a standalone comment line
                # above counts as a declaration marker
                continue
            body = comment.lstrip(":").strip()
            if body.startswith(name + ":"):
                reason = body[len(name) + 1 :].strip()
                if reason:
                    return reason
        return None

    def _comment_only(self, line: int) -> bool:
        if 1 <= line <= len(self._lines):
            return self._lines[line - 1].lstrip().startswith("#")
        return False

    def func_marker(self, node: ast.AST, name: str) -> Optional[str]:
        """Return the value of a ``# <name>: value`` comment anywhere
        inside a function's line span (function-scoped annotations like
        ``requires-lock``)."""
        end = getattr(node, "end_lineno", node.lineno)
        for ln in range(node.lineno, end + 1):
            comment = self.comments.get(ln)
            if comment is None:
                continue
            body = comment.lstrip(":").strip()
            if body.startswith(name + ":"):
                value = body[len(name) + 1 :].strip()
                if value:
                    return value
        return None


def iter_source_files(
    root: str, subdirs: Optional[Iterable[str]] = None
) -> Iterator[SourceFile]:
    """Yield parsed SourceFiles under ``root`` (repo root).  With
    ``subdirs``, only files whose repo-relative path starts with one of
    them."""
    prefixes = tuple(subdirs) if subdirs else None
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(
            d for d in dirnames
            if d not in ("__pycache__", ".git", ".ruff_cache")
        )
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            if prefixes and not rel.startswith(prefixes):
                continue
            try:
                with open(path, encoding="utf-8") as f:
                    text = f.read()
                yield SourceFile(path, rel, text)
            except (OSError, SyntaxError):
                continue


@dataclass
class Baseline:
    """Checked-in suppression list.  Every entry needs a reason — the
    baseline records findings we chose to live with, not findings we
    forgot."""

    path: Optional[str] = None
    entries: List[dict] = field(default_factory=list)

    @classmethod
    def load(cls, path: str) -> "Baseline":
        if not os.path.exists(path):
            return cls(path=path)
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
        entries = data.get("suppressions", [])
        for e in entries:
            for k in ("pass", "code", "file", "symbol", "reason"):
                if not e.get(k):
                    raise ValueError(
                        f"baseline entry missing {k!r}: {e!r} "
                        f"(every suppression needs a reason)"
                    )
        return cls(path=path, entries=entries)

    def _keys(self) -> set:
        return {
            (e["pass"], e["code"], e["file"], e["symbol"])
            for e in self.entries
        }

    def split(
        self, findings: List[Finding]
    ) -> Tuple[List[Finding], List[Finding], List[dict]]:
        """→ (unsuppressed, suppressed, stale-baseline-entries)."""
        keys = self._keys()
        found_keys = {f.key for f in findings}
        unsuppressed = [f for f in findings if f.key not in keys]
        suppressed = [f for f in findings if f.key in keys]
        stale = [
            e for e in self.entries
            if (e["pass"], e["code"], e["file"], e["symbol"]) not in found_keys
        ]
        return unsuppressed, suppressed, stale

    @staticmethod
    def write(path: str, findings: List[Finding]) -> None:
        entries = [
            {
                "pass": f.pass_id,
                "code": f.code,
                "file": f.file,
                "symbol": f.symbol,
                "reason": "TODO: justify or fix",
            }
            for f in sorted(findings, key=lambda f: f.key)
        ]
        with open(path, "w", encoding="utf-8") as f:
            json.dump({"suppressions": entries}, f, indent=2, sort_keys=False)
            f.write("\n")


def run_passes(
    root: str, passes: Optional[Iterable[str]] = None
) -> List[Finding]:
    """Run the selected passes (default: all) over the tree at ``root``
    and return the raw findings, stably sorted."""
    from volcano_tpu.analysis import determinism, jit_safety, lock_discipline
    from volcano_tpu.analysis import metric_hygiene, serde_drift

    selected = set(passes) if passes else {"lock", "det", "jit", "serde",
                                           "mtr"}
    findings: List[Finding] = []
    if "lock" in selected:
        findings.extend(lock_discipline.run(root))
    if "det" in selected:
        findings.extend(determinism.run(root))
    if "jit" in selected:
        findings.extend(jit_safety.run(root))
    if "serde" in selected:
        findings.extend(serde_drift.run(root))
    if "mtr" in selected:
        findings.extend(metric_hygiene.run(root))
    findings.sort(key=lambda f: (f.file, f.line, f.code, f.symbol))
    return findings
