"""Determinism pass — replay-critical modules must not reach wall-clock
time, unseeded RNG, or order-escaping ``set`` iteration.

This is the contract that makes seeded chaos schedules
(``faults/plane.py``) and ``trace.replay.verify()`` bit-identity
trustworthy: a fault decision or a binding order that consults
``time.time()`` / global ``random`` / ``set`` iteration order cannot be
reproduced from a journal.

Scope: ``volcano_tpu/{trace,faults,ops,actions,cache}/``.  Flagged:

* ``time.time()`` / ``time.time_ns()`` / ``datetime.now()`` /
  ``datetime.utcnow()`` — wall clock.  (``perf_counter`` / ``monotonic``
  are allowed: they time and back off, they never *decide*.)
* module-level ``random.<fn>()`` and ``np.random.<fn>()`` — global,
  unseeded RNG state.  Seeded constructors (``random.Random(seed)``,
  ``np.random.RandomState(seed)``, ``np.random.default_rng(seed)``)
  are allowed — the seed is the determinism.
* ``uuid.uuid1()`` / ``uuid.uuid4()`` — entropy.
* iterating a ``set`` where order escapes: ``for x in {…}`` /
  ``set(...)`` / a set comprehension, and ``list()`` / ``tuple()`` /
  ``enumerate()`` over the same.  (``sorted(set(...))`` is the fix and
  is not flagged.)

Allowlist: a trailing ``# det: <reason>`` comment on the line (journal
timestamps and cache-identity uuids are the two legitimate uses today),
or a baseline entry.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from volcano_tpu.analysis.core import Finding, iter_source_files, SourceFile

PASS = "det"
CODE_WALLCLOCK = "DET001"
CODE_RNG = "DET002"
CODE_SET_ORDER = "DET003"
CODE_ENTROPY = "DET004"

#: replay-critical subtrees (ISSUE 7 / trace.replay contract)
REPLAY_CRITICAL = (
    "volcano_tpu/trace/",
    "volcano_tpu/faults/",
    "volcano_tpu/ops/",
    "volcano_tpu/actions/",
    "volcano_tpu/cache/",
)

_WALLCLOCK = {
    ("time", "time"), ("time", "time_ns"),
    ("datetime", "now"), ("datetime", "utcnow"), ("datetime", "today"),
    ("date", "today"),
}
_SEEDED_CTORS = {"Random", "RandomState", "Generator", "default_rng",
                 "SystemRandom", "PRNGKey", "key"}
_RANDOM_MODULES = {"random"}
_ENTROPY = {("uuid", "uuid1"), ("uuid", "uuid4")}
_ORDER_ESCAPES = {"list", "tuple", "enumerate", "iter", "next"}


def _dotted(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _dotted(node.value)
        return f"{base}.{node.attr}" if base else None
    return None


def _is_set_expr(node: ast.AST) -> bool:
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Name)
        and node.func.id == "set"
    ):
        return True
    return False


class _Checker(ast.NodeVisitor):
    def __init__(self, src: SourceFile):
        self.src = src
        self.findings: List[Finding] = []
        self._func_stack: List[str] = []

    def _owner(self) -> str:
        return ".".join(self._func_stack) or "<module>"

    def _emit(self, code: str, node: ast.AST, message: str, what: str) -> None:
        if self.src.marker(node.lineno, "det"):
            return
        self.findings.append(Finding(
            PASS, code, self.src.rel, node.lineno,
            f"{self._owner()}:{what}", message,
        ))

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._func_stack.append(node.name)
        self.generic_visit(node)
        self._func_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._func_stack.append(node.name)
        self.generic_visit(node)
        self._func_stack.pop()

    def visit_Call(self, node: ast.Call) -> None:
        dotted = _dotted(node.func)
        if dotted:
            parts = tuple(dotted.split("."))
            tail2 = parts[-2:] if len(parts) >= 2 else None
            if tail2 in _WALLCLOCK:
                self._emit(
                    CODE_WALLCLOCK, node,
                    f"wall-clock `{dotted}()` in a replay-critical module "
                    f"(use perf_counter/monotonic, or `# det:` if this is "
                    f"a journal timestamp)", dotted,
                )
            elif tail2 in _ENTROPY:
                self._emit(
                    CODE_ENTROPY, node,
                    f"`{dotted}()` draws entropy in a replay-critical "
                    f"module", dotted,
                )
            elif (
                len(parts) >= 2
                and (parts[0] in _RANDOM_MODULES
                     or parts[-2] == "random")
                and parts[-1] not in _SEEDED_CTORS
            ):
                # module-level random.* / np.random.* — global RNG state
                self._emit(
                    CODE_RNG, node,
                    f"unseeded global RNG `{dotted}()` in a "
                    f"replay-critical module (seed an explicit "
                    f"Random/Generator instead)", dotted,
                )
            elif (
                isinstance(node.func, ast.Name)
                and node.func.id in _ORDER_ESCAPES
                and node.args
                and _is_set_expr(node.args[0])
            ):
                self._emit(
                    CODE_SET_ORDER, node,
                    f"`{node.func.id}()` over a set leaks iteration order "
                    f"(wrap in sorted())", f"{node.func.id}(set)",
                )
        self.generic_visit(node)

    def visit_For(self, node: ast.For) -> None:
        if _is_set_expr(node.iter):
            self._emit(
                CODE_SET_ORDER, node,
                "iterating a set leaks its order into a replay-critical "
                "path (wrap in sorted())", "for-in-set",
            )
        self.generic_visit(node)


def check_file(src: SourceFile) -> List[Finding]:
    checker = _Checker(src)
    checker.visit(src.tree)
    return checker.findings


def run(root: str) -> List[Finding]:
    findings: List[Finding] = []
    for src in iter_source_files(root, subdirs=REPLAY_CRITICAL):
        findings.extend(check_file(src))
    return findings
