"""Deterministic interleaving explorer for the bus/federation protocols.

The chaos smokes explore ONE interleaving per run — whatever the OS
scheduler happened to produce under that seed.  This module is the
CHESS-style complement: the election, lease absorb/shed and gang
assembly state machines run **in-process under a controlled
scheduler**, and every schedule — which message is delivered next,
which fault point fires, who crashes when — is a deterministic function
of one integer seed.  Hundreds of distinct schedules per run, each
replayable from its seed alone.

Three machines, four pinned invariants:

* ``election`` — a model of ``bus/replication.py``'s leader protocol at
  action granularity (probe+decide+promote is one atomic action, the
  window the real stagger/re-probe protects): writes, shipments,
  quorum acks, crash/restart with durable logs, elections.  Invariants:
  **at most one leader per term**, and **no acked-then-lost write**
  (every client-acked write is in the live leader's log, across any
  crash/election sequence).
* ``lease`` — the REAL :class:`~volcano_tpu.federation.leases.
  ShardLeaseManager` ticking against a real in-process ``APIServer``
  under a fake clock: the explorer permutes tick order, clock advances
  and member crashes.  Invariant: **no doubly-owned shard slice** (two
  live members never both hold a slice within their renewal validity).
* ``gang`` — the REAL :meth:`~volcano_tpu.client.apiserver.APIServer.
  txn_commit` driven by two racing assembly planners with stale-claim
  injection and mid-assembly crashes.  Invariant: **no partial gang
  below minMember** (bound members ∈ {0} ∪ [minMember, size] at every
  observable state).

Fault-point firing reuses the ``faults/`` plane grammar: each schedule
builds a :class:`~volcano_tpu.faults.plane.FaultPlane` seeded by the
schedule id, so ``repl.drop`` / ``bus.leader_kill`` /
``lease.cas_fail`` / ``gang.kill_mid_assembly`` fire deterministically
per schedule and replay identically.

Schedules: low seeds walk the decision tree systematically (the seed is
consumed as a mixed-radix numeral, one digit per choice, so every
distinct decision prefix below the systematic budget is visited
exactly once); seeds past the budget drive seeded-random choices.
``vtctl explore --replay <machine>:<seed>`` re-runs one schedule and
prints its full action trace.

Planted bugs (``--plant``) prove the engine catches what it claims to:
``stale-election`` splits probe from promote so two candidates promote
on stale views (dual leader, same term); ``partial-commit`` replays a
gang as per-member ``cas_bind``s that ignore conflicts (the exact
replay the VBUS old-peer fallback forbids); ``lease-steal`` treats
every lease as expired at claim time.  Each is caught, named, and
replayable — and none of them is reachable through the unplanted
protocols across the whole schedule budget, which is the regression
net ROADMAP items 4–5 rewrite under.
"""

from __future__ import annotations

import argparse
import itertools
import json
import random as _random
import sys
from typing import Callable, Dict, List, Optional, Tuple

from volcano_tpu.faults.plane import FaultPlane, parse_faults

#: schedule seeds below this walk the choice tree systematically
SYSTEMATIC_BUDGET = 64

#: default per-schedule step budget; a violation found under a
#: non-default budget carries it in its replay command (the budget is
#: part of the schedule's identity, like --plant/--faults)
_MAX_STEPS = 60

PLANTS = ("stale-election", "partial-commit", "lease-steal")


class Schedule:
    """One replayable interleaving, fully determined by ``sid``."""

    def __init__(self, sid: int, systematic_below: int = SYSTEMATIC_BUDGET):
        self.sid = sid
        self._rng = _random.Random(0x9E3779B9 ^ sid)
        #: mixed-radix systematic prefix: digits of ``sid`` in the radix
        #: sequence of choice arities, most-significant last — every
        #: distinct prefix below the budget is visited exactly once
        self._forced: Optional[int] = (
            sid if 0 <= sid < systematic_below else None
        )
        self.choices: List[int] = []

    def choose(self, n: int) -> int:
        """Pick one of ``n`` alternatives."""
        if n <= 1:
            self.choices.append(0)
            return 0
        if self._forced is not None:
            c = self._forced % n
            self._forced //= n
            if self._forced == 0:
                self._forced = None
            self.choices.append(c)
            return c
        c = self._rng.randrange(n)
        self.choices.append(c)
        return c


class Violation:
    """One invariant violation, replayable from ``(machine, sid)`` —
    plus the ``--plant``/``--faults`` flags it was found under, which
    are part of the schedule's identity: the printed replay command
    must reproduce the trace bit-for-bit, and a plant-found violation
    replayed without the plant is (by design) clean."""

    def __init__(self, machine: str, sid: int, step: int, invariant: str,
                 trace: List[str], plant: Optional[str] = None,
                 faults: Optional[str] = None,
                 max_steps: Optional[int] = None):
        self.machine = machine
        self.sid = sid
        self.step = step
        self.invariant = invariant
        self.trace = trace
        self.plant = plant
        self.faults = faults
        self.max_steps = max_steps

    def render(self) -> str:
        tail = self.trace[-12:]
        pre = "... " if len(self.trace) > 12 else ""
        replay = f"vtctl explore --replay {self.machine}:{self.sid}"
        if self.plant:
            replay += f" --plant {self.plant}"
        if self.faults:
            replay += f" --faults '{self.faults}'"
        if self.max_steps is not None and self.max_steps != _MAX_STEPS:
            # a violation past the default step budget replays clean
            # without the budget that reached it
            replay += f" --max-steps {self.max_steps}"
        return (
            f"[{self.machine}] schedule {self.sid} step {self.step}: "
            f"{self.invariant}\n"
            f"  trace: {pre}{' -> '.join(tail)}\n"
            f"  replay: {replay}"
        )

    def to_dict(self) -> dict:
        out = {
            "machine": self.machine, "sid": self.sid, "step": self.step,
            "invariant": self.invariant, "trace": self.trace,
        }
        if self.plant:
            out["plant"] = self.plant
        if self.faults:
            out["faults"] = self.faults
        if self.max_steps is not None and self.max_steps != _MAX_STEPS:
            out["max_steps"] = self.max_steps
        return out

    @classmethod
    def from_dict(cls, d: dict) -> "Violation":
        """Round-trip of :meth:`to_dict` — the absent default flags
        come back as None, which renders identically (omitted from the
        replay command)."""
        return cls(d["machine"], d["sid"], d["step"], d["invariant"],
                   d["trace"], plant=d.get("plant"),
                   faults=d.get("faults"), max_steps=d.get("max_steps"))


# ---------------------------------------------------------------------------
# election machine (model of bus/replication.py)
# ---------------------------------------------------------------------------

class _Replica:
    __slots__ = ("index", "alive", "role", "term", "log", "coord")

    def __init__(self, index: int):
        self.index = index
        self.alive = True
        self.role = "follower"
        self.term = 0  # persisted (set_term writes the WAL meta)
        #: durable ordered log of write ids — the WAL survives crashes
        self.log: List[int] = []
        #: leader-side coordinator: follower index → acked log length;
        #: reset on every promotion (the real coordinator is rebuilt)
        self.coord: Dict[int, int] = {}


class ElectionMachine:
    """Model of the replication leader protocol: most-advanced-survivor
    election with a reachable-majority floor, quorum-acked writes,
    crash-stop faults with durable logs.  Mirrors ``_elect`` /
    ``_lead_tick`` / the commit rule in ``bus/replication.py`` — the
    ordering comparators and the quorum rule are the same expressions.
    """

    name = "election"
    default_faults = "repl.drop=0.15;bus.leader_kill=0.15:count=2"

    def __init__(self, replicas: int = 3, max_writes: int = 6,
                 crash_budget: int = 3):
        self.n = replicas
        self.max_writes = max_writes
        self.crash_budget_total = crash_budget

    def reset(self, sched: Schedule, plane: FaultPlane,
              plant: Optional[str]) -> None:
        # the PRODUCTION quorum rule and ordering comparators — the
        # model cannot drift from bus/replication.py
        from volcano_tpu.bus.replication import (
            candidate_rank, leader_rank, quorum_of,
        )

        self.sched = sched
        self.plane = plane
        self.plant = plant
        self.quorum = quorum_of(self.n)
        self._candidate_rank = candidate_rank
        self._leader_rank = leader_rank
        self.replicas = [_Replica(i) for i in range(self.n)]
        self.replicas[0].role = "leader"
        self.replicas[0].term = 1
        self.acked: set = set()        # write ids acked to clients
        self.writes = itertools.count(1)
        self.n_writes = 0
        self.crash_budget = self.crash_budget_total
        #: plant state: candidate index → stale probe snapshot
        self.stale_probe: Dict[int, List[Tuple[int, int, int]]] = {}

    def teardown(self) -> None:
        pass

    # ---- helpers ----

    def _leaders(self) -> List[_Replica]:
        return [r for r in self.replicas if r.alive and r.role == "leader"]

    def _commit_len(self, leader: _Replica) -> int:
        # quorum-th highest held position across the WHOLE group — a
        # follower that never acked holds position 0 (the real
        # coordinator's rule; counting only acked followers would let a
        # lone leader self-quorum)
        held = sorted(
            [len(leader.log)]
            + [leader.coord.get(i, 0) for i in range(self.n)
               if i != leader.index],
            reverse=True,
        )
        return held[self.quorum - 1]

    def _recompute_acks(self, leader: _Replica) -> None:
        self.acked.update(leader.log[: self._commit_len(leader)])

    def _promote(self, r: _Replica, term: int) -> None:
        r.term = term
        r.role = "leader"
        r.coord = {}

    # ---- actions ----

    def actions(self) -> List[Tuple[str, Callable[[], None]]]:
        acts: List[Tuple[str, Callable[[], None]]] = []
        leaders = self._leaders()
        leader = leaders[0] if leaders else None

        if leader is not None and self.n_writes < self.max_writes:
            acts.append(("write", self._act_write))
        for f in self.replicas:
            if f.alive and f.role == "follower":
                for ld in leaders:
                    acts.append((
                        f"ship r{f.index}<-r{ld.index}",
                        lambda f=f, ld=ld: self._act_ship(f, ld),
                    ))
        if self.crash_budget > 0:
            for r in self.replicas:
                if r.alive:
                    acts.append((
                        f"crash r{r.index}",
                        lambda r=r: self._act_crash(r),
                    ))
        for r in self.replicas:
            if not r.alive:
                acts.append((
                    f"restart r{r.index}", lambda r=r: self._act_restart(r)
                ))
        if not leaders:
            for r in self.replicas:
                if r.alive and r.index not in self.stale_probe:
                    acts.append((
                        f"elect r{r.index}", lambda r=r: self._act_elect(r)
                    ))
        if self.plant == "stale-election":
            for idx in list(self.stale_probe):
                r = self.replicas[idx]
                if r.alive and r.role == "follower":
                    acts.append((
                        f"promote-stale r{idx}",
                        lambda r=r: self._act_promote_stale(r),
                    ))
                else:
                    del self.stale_probe[idx]
        if len(leaders) > 1:
            for r in leaders:
                acts.append((
                    f"lead-tick r{r.index}",
                    lambda r=r: self._act_lead_tick(r),
                ))
        return acts

    def _act_write(self) -> None:
        leader = self._leaders()[0]
        leader.log.append(next(self.writes))
        self.n_writes += 1
        self._recompute_acks(leader)

    def _act_ship(self, f: _Replica, leader: _Replica) -> None:
        if self.plane.should("repl.drop"):
            return  # the shipment batch is dropped; the follower re-pulls
        if f.log == leader.log[: len(f.log)]:
            f.log.extend(leader.log[len(f.log):])
        else:
            # diverged history (a deposed leader's un-acked suffix):
            # chain mismatch → snapshot resync, exactly the repl_append
            # `snapshot_needed` path
            f.log = list(leader.log)
        if leader.term > f.term:
            f.term = leader.term
        leader.coord[f.index] = len(f.log)
        self._recompute_acks(leader)

    def _act_crash(self, r: _Replica) -> None:
        r.alive = False
        self.crash_budget -= 1
        # term/log survive: the WAL is durable.  Leadership does not.
        if r.role == "leader":
            r.role = "follower"
            r.coord = {}

    def _act_restart(self, r: _Replica) -> None:
        r.alive = True
        r.role = "follower"

    def _probe(self, r: _Replica) -> List[Tuple[int, int, int]]:
        """``candidate_rank`` of every reachable live peer."""
        return [
            self._candidate_rank(p.term, len(p.log), p.index)
            for p in self.replicas
            if p.alive and p.index != r.index
        ]

    def _act_elect(self, r: _Replica) -> None:
        """One atomic election attempt: probe + decide + promote.  The
        real protocol's probe window is protected by the index stagger
        and re-probe; the model collapses it to one action (the planted
        ``stale-election`` variant splits it back open)."""
        statuses = self._probe(r)
        if self.plant == "stale-election":
            self.stale_probe[r.index] = statuses
            return
        if self._leaders():
            return  # an existing leader wins immediately: follow it
        if len(statuses) + 1 < self.quorum:
            return  # below the reachable-majority floor: refuse
        mine = self._candidate_rank(r.term, len(r.log), r.index)
        if any(peer > mine for peer in statuses):
            return  # a more advanced peer exists; let it promote
        max_term = max([r.term] + [t for t, _s, _i in statuses])
        self._promote(r, max_term + 1)

    def _act_promote_stale(self, r: _Replica) -> None:
        """PLANTED BUG: decide on the snapshot taken at probe time.  A
        peer that promoted since is invisible, so two candidates can
        claim the same term."""
        statuses = self.stale_probe.pop(r.index)
        if len(statuses) + 1 < self.quorum:
            return
        mine = self._candidate_rank(r.term, len(r.log), r.index)
        if any(peer > mine for peer in statuses):
            return
        max_term = max([r.term] + [t for t, _s, _i in statuses])
        self._promote(r, max_term + 1)

    def _act_lead_tick(self, r: _Replica) -> None:
        """Same-term dual-leader resolution: the higher ``leader_rank``
        stays, the other steps down — ``_lead_tick``'s rule."""
        mine = self._leader_rank(r.term, self._commit_len(r), r.index)
        for p in self._leaders():
            if p is r:
                continue
            peer = self._leader_rank(p.term, self._commit_len(p), p.index)
            if peer > mine:
                r.role = "follower"
                r.coord = {}
                return

    # ---- faults + invariants ----

    def fire_faults(self) -> Optional[str]:
        leaders = self._leaders()
        if leaders and self.crash_budget > 0 and self.plane.should(
            "bus.leader_kill"
        ):
            leader = leaders[0]
            self._act_crash(leader)
            return f"fault:bus.leader_kill r{leader.index}"
        return None

    def check(self) -> List[str]:
        errs: List[str] = []
        by_term: Dict[int, int] = {}
        for r in self._leaders():
            if r.term in by_term:
                errs.append(
                    f"two live leaders in term {r.term}: replicas "
                    f"r{by_term[r.term]} and r{r.index}"
                )
            else:
                by_term[r.term] = r.index
        for leader in self._leaders():
            lost = self.acked - set(leader.log)
            if lost:
                errs.append(
                    f"acked-then-lost: writes {sorted(lost)} were acked "
                    f"to clients but are missing from live leader "
                    f"r{leader.index}'s log"
                )
        return errs


# ---------------------------------------------------------------------------
# lease machine (drives the REAL ShardLeaseManager._tick)
# ---------------------------------------------------------------------------

class _FakeClock:
    """Stand-in for the ``time`` module inside ``federation.leases`` —
    wall and monotonic advance in lockstep under schedule control."""

    def __init__(self, start: float = 1000.0):
        self.now = start

    def time(self) -> float:
        return self.now

    def monotonic(self) -> float:
        return self.now

    def advance(self, dt: float) -> None:
        self.now += dt


class LeaseMachine:
    """The real CAS-lease protocol under permuted tick order, clock
    advances, CAS failures and member crashes."""

    name = "lease"
    default_faults = "lease.cas_fail=0.1"

    def __init__(self, members: int = 3, n_shards: int = 4,
                 crash_budget: int = 2):
        self.n_members = members
        self.n_shards = n_shards
        self.crash_budget_total = crash_budget

    def reset(self, sched: Schedule, plane: FaultPlane,
              plant: Optional[str]) -> None:
        from volcano_tpu.client.apiserver import APIServer, ConflictError
        from volcano_tpu.federation import leases as leases_mod

        self.sched = sched
        self.plane = plane
        self.plant = plant
        self._leases_mod = leases_mod
        self._orig_time = leases_mod.time
        # __dict__ access keeps the staticmethod wrapper — plain
        # attribute access unwraps it and the restore would re-bind self
        self._orig_expired = leases_mod.ShardLeaseManager.__dict__["_expired"]
        self.clock = _FakeClock()
        leases_mod.time = self.clock  # type: ignore[assignment]
        if plant == "lease-steal":
            # PLANTED BUG: every lease reads as expired at claim time —
            # a member steals slices its peers still validly hold
            leases_mod.ShardLeaseManager._expired = staticmethod(
                lambda entry, now: True
            )
        self.api = APIServer()
        orig_cas = self.api.compare_and_update

        def cas_with_fault(obj, expected_rv):
            if plane.should("lease.cas_fail"):
                raise ConflictError(
                    "injected lease.cas_fail: CAS lost this tick"
                )
            return orig_cas(obj, expected_rv)

        self.api.compare_and_update = cas_with_fault
        self.lease_duration = 2.0
        self.mgrs: Dict[str, leases_mod.ShardLeaseManager] = {}
        self.live: set = set()
        for i in range(self.n_members):
            self._spawn(f"m{i}")
        self.crash_budget = self.crash_budget_total

    def _spawn(self, ident: str) -> None:
        self.mgrs[ident] = self._leases_mod.ShardLeaseManager(
            self.api, ident, n_shards=self.n_shards,
            lease_duration=self.lease_duration, retry_period=0.2,
        )
        # a fresh manager has never renewed; seed validity bookkeeping
        self.mgrs[ident]._last_renew = -self.lease_duration * 10
        self.live.add(ident)

    def teardown(self) -> None:
        if not hasattr(self, "_leases_mod"):
            return  # reset failed before saving/patching anything
        self._leases_mod.time = self._orig_time
        setattr(self._leases_mod.ShardLeaseManager, "_expired",
                self._orig_expired)

    # ---- actions ----

    def actions(self) -> List[Tuple[str, Callable[[], None]]]:
        from volcano_tpu.client.apiserver import ApiError

        acts: List[Tuple[str, Callable[[], None]]] = []

        def tick(ident: str) -> None:
            mgr = self.mgrs[ident]
            try:
                mgr._tick()
            except ApiError:
                mgr._maybe_expire()  # the run() loop's degraded path

        for ident in sorted(self.live):
            acts.append((f"tick {ident}", lambda i=ident: tick(i)))
        acts.append((
            "advance 0.3", lambda: self.clock.advance(0.3)
        ))
        acts.append((
            f"advance {self.lease_duration + 0.1:g}",
            lambda: self.clock.advance(self.lease_duration + 0.1),
        ))
        if self.crash_budget > 0 and len(self.live) > 1:
            for ident in sorted(self.live):
                acts.append((
                    f"crash {ident}", lambda i=ident: self._act_crash(i)
                ))
        for ident in sorted(set(self.mgrs) - self.live):
            acts.append((
                f"restart {ident}", lambda i=ident: self._spawn(i)
            ))
        return acts

    def _act_crash(self, ident: str) -> None:
        self.live.discard(ident)
        self.crash_budget -= 1

    def fire_faults(self) -> Optional[str]:
        return None  # lease.cas_fail fires inside the CAS write path

    def check(self) -> List[str]:
        owned: Dict[int, str] = {}
        errs: List[str] = []
        for ident in sorted(self.live):
            mgr = self.mgrs[ident]
            valid = (
                self.clock.monotonic() - mgr._last_renew
                <= self.lease_duration
            )
            if not valid:
                continue  # self-expiry window: not an owner any more
            for shard in sorted(mgr._applied):
                if shard in owned:
                    errs.append(
                        f"shard {shard} doubly owned by {owned[shard]} "
                        f"and {ident} (both within renewal validity)"
                    )
                else:
                    owned[shard] = ident
        return errs


# ---------------------------------------------------------------------------
# gang machine (drives the REAL APIServer.txn_commit)
# ---------------------------------------------------------------------------

class GangMachine:
    """Two racing assembly planners committing one gang through the real
    ``txn_commit``, with stale-claim injection (competing resource-
    version bumps) and mid-assembly crashes."""

    name = "gang"
    default_faults = "gang.kill_mid_assembly=0.15:count=1"

    def __init__(self, size: int = 4, touch_budget: int = 3):
        self.size = size
        self.min_member = size
        self.touch_budget_total = touch_budget

    def reset(self, sched: Schedule, plane: FaultPlane,
              plant: Optional[str]) -> None:
        from volcano_tpu.apis import core
        from volcano_tpu.client.apiserver import APIServer

        self.sched = sched
        self.plane = plane
        self.plant = plant
        self.api = APIServer()
        self.ns = "default"
        self.pods = [f"gang-{i}" for i in range(self.size)]
        for name in self.pods:
            self.api.create(core.Pod(
                metadata=core.ObjectMeta(name=name, namespace=self.ns),
                spec=core.PodSpec(containers=[]),
                status=core.PodStatus(phase="Pending"),
            ))
        #: planner → claim list (plan-time resource versions) or None
        self.plans: Dict[str, Optional[List[dict]]] = {"A": None, "B": None}
        self.crashed: set = set()
        self.touch_budget = self.touch_budget_total
        self.done = False

    def teardown(self) -> None:
        pass

    # ---- actions ----

    def actions(self) -> List[Tuple[str, Callable[[], None]]]:
        acts: List[Tuple[str, Callable[[], None]]] = []
        if self.done:
            return acts
        for planner in ("A", "B"):
            if planner in self.crashed:
                continue
            if self.plans[planner] is None:
                acts.append((
                    f"plan {planner}",
                    lambda p=planner: self._act_plan(p),
                ))
            else:
                acts.append((
                    f"commit {planner}",
                    lambda p=planner: self._act_commit(p),
                ))
                acts.append((
                    f"crash {planner}",
                    lambda p=planner: self._act_crash(p),
                ))
        if self.touch_budget > 0:
            for i, name in enumerate(self.pods):
                acts.append((
                    f"touch {name}",
                    lambda n=name: self._act_touch(n),
                ))
        return acts

    def _act_plan(self, planner: str) -> None:
        """Snapshot claims at current store truth — the broker's
        plan_gang_assembly read, resource versions included."""
        claims = []
        for i, name in enumerate(self.pods):
            pod = self.api.get("Pod", self.ns, name)
            if pod is None or pod.spec.node_name:
                self.plans[planner] = None
                return  # gang already (partly) bound: planner defers
            claims.append({
                "namespace": self.ns, "name": name,
                "hostname": f"node-{planner.lower()}{i % 2}",
                "expected_rv": pod.metadata.resource_version,
            })
        self.plans[planner] = claims

    def _act_commit(self, planner: str) -> None:
        from volcano_tpu.client.apiserver import ApiError

        plan = self.plans[planner]
        self.plans[planner] = None
        if plan is None:
            return
        if self.plane.should("gang.kill_mid_assembly"):
            # the planner dies between planning and committing: the
            # orphaned assembly is discarded whole, nothing landed
            self.crashed.add(planner)
            return
        if self.plant == "partial-commit":
            # PLANTED BUG: replay the gang as per-member cas_binds,
            # ignoring per-item conflicts — the replay the VBUS
            # old-peer fallback exists to forbid
            for b in plan:
                try:
                    self.api.cas_bind(
                        b["namespace"], b["name"], b["hostname"],
                        expected_rv=b["expected_rv"],
                    )
                except ApiError:
                    continue
            self.done = True
            return
        resp = self.api.txn_commit(plan)
        if resp["committed"]:
            self.done = True
        # abort: discard-until-stable — the planner re-plans from
        # fresh truth on a later step

    def _act_crash(self, planner: str) -> None:
        self.plans[planner] = None
        self.crashed.add(planner)

    def _act_touch(self, name: str) -> None:
        """Bump one member's resourceVersion (a status write from a
        controller) — every plan holding the old rv is now stale."""
        pod = self.api.get("Pod", self.ns, name)
        if pod is None:
            return
        clone = pod.clone()
        clone.metadata.annotations = dict(clone.metadata.annotations or {})
        clone.metadata.annotations["touched"] = str(
            self.touch_budget_total - self.touch_budget
        )
        self.api.update_status(clone)
        self.touch_budget -= 1

    def fire_faults(self) -> Optional[str]:
        return None  # gang.kill_mid_assembly fires inside commit

    def check(self) -> List[str]:
        bound = sum(
            1 for name in self.pods
            if (pod := self.api.get("Pod", self.ns, name)) is not None
            and pod.spec.node_name
        )
        if 0 < bound < self.min_member:
            return [
                f"partial gang: {bound}/{self.size} members bound "
                f"(minMember={self.min_member}) — observable below "
                f"minMember"
            ]
        return []


MACHINES: Dict[str, Callable[[], object]] = {
    "election": ElectionMachine,
    "lease": LeaseMachine,
    "gang": GangMachine,
}


# ---------------------------------------------------------------------------
# the explorer loop
# ---------------------------------------------------------------------------

def run_schedule(machine, sid: int, max_steps: int = _MAX_STEPS,
                 plant: Optional[str] = None,
                 faults: Optional[str] = None,
                 trace_out=None) -> Tuple[Optional[Violation], int]:
    """Run one schedule; returns ``(violation_or_None, steps_taken)``.
    Deterministic: the same ``(machine, sid, plant, faults)`` replays
    the same trace bit-for-bit."""
    sched = Schedule(sid)
    spec = faults if faults is not None else machine.default_faults
    plane = FaultPlane(parse_faults(f"seed={sid};{spec}" if spec
                                    else f"seed={sid}"))
    trace: List[str] = []
    try:
        # inside the try: LeaseMachine.reset patches process globals
        # (module clock, _expired) before it constructs the apiserver
        # and managers — a failure mid-reset must still restore them
        machine.reset(sched, plane, plant)
        for step in range(max_steps):
            fault_label = machine.fire_faults()
            if fault_label is not None:
                trace.append(fault_label)
                if trace_out is not None:
                    print(f"  {step:3d}  {fault_label}", file=trace_out)
            acts = machine.actions()
            if not acts:
                break
            label, fn = acts[sched.choose(len(acts))]
            trace.append(label)
            if trace_out is not None:
                print(f"  {step:3d}  {label}", file=trace_out)
            fn()
            errs = machine.check()
            if errs:
                return Violation(
                    machine.name, sid, step, "; ".join(errs), trace,
                    plant=plant, faults=faults, max_steps=max_steps,
                ), step + 1
        return None, len(trace)
    finally:
        machine.teardown()


def explore(machine_names, schedules: int, max_steps: int = _MAX_STEPS,
            plant: Optional[str] = None, faults: Optional[str] = None,
            seed_base: int = 0, max_violations: int = 5) -> dict:
    """Run ``schedules`` distinct schedules per named machine."""
    out: Dict[str, dict] = {}
    for name in machine_names:
        machine = MACHINES[name]()
        violations: List[Violation] = []
        steps = 0
        ran = 0
        for sid in range(seed_base, seed_base + schedules):
            v, n = run_schedule(machine, sid, max_steps=max_steps,
                                plant=plant, faults=faults)
            steps += n
            ran += 1
            if v is not None:
                violations.append(v)
                if len(violations) >= max_violations:
                    break
        out[name] = {
            # schedules actually RUN, not requested: the loop stops at
            # max_violations, and the CI report must not attest to
            # coverage that never executed.  Everything here is plain
            # JSON — callers may json.dump the result directly
            "schedules": ran,
            "steps": steps,
            "violations": [v.to_dict() for v in violations],
        }
    return out


def main(argv: Optional[List[str]] = None, out=None) -> int:
    out = out or sys.stdout
    parser = argparse.ArgumentParser(
        prog="vtctl explore",
        description="deterministic interleaving explorer for the "
                    "election / lease / gang-assembly protocols",
    )
    parser.add_argument("--schedules", type=int, default=None,
                        help="schedules per machine (default 500; "
                             "--quick: 100)")
    parser.add_argument("--quick", action="store_true",
                        help="CI budget: 100 schedules per machine")
    parser.add_argument("--machine", action="append",
                        choices=sorted(MACHINES),
                        help="explore only this machine (repeatable; "
                             "default: all)")
    parser.add_argument("--max-steps", type=int, default=_MAX_STEPS,
                        help="actions per schedule (default "
                             f"{_MAX_STEPS}; Violation.render omits "
                             "the flag from replay commands at this "
                             "default, so the two must not drift)")
    parser.add_argument("--seed-base", type=int, default=0,
                        help="first schedule seed (default 0)")
    parser.add_argument("--plant", choices=PLANTS,
                        help="plant a known protocol bug (the detection "
                             "self-test; the run must FAIL)")
    parser.add_argument("--faults", default=None,
                        help="faults-plane spec overriding each "
                             "machine's default (same grammar as "
                             "VTPU_FAULTS; the seed clause is supplied "
                             "per schedule)")
    parser.add_argument("--replay", metavar="MACHINE:SEED",
                        help="re-run one schedule, printing its trace")
    parser.add_argument("--report", default=None,
                        help="write a JSON report here")
    parser.add_argument("--verbose", action="store_true",
                        help="keep the protocols' own INFO logging")
    args = parser.parse_args(argv)

    if not args.verbose:
        # thousands of schedules re-run the real lease/gang code paths;
        # their own INFO logging would drown the summary.  Scoped: main
        # is callable in-process (vtctl tests), so the level is
        # restored on the way out
        import logging

        # the package logger must be CONFIGURED before we override it:
        # the machines lazily import modules that pull in
        # volcano_tpu.utils.logging, whose first-import body sets the
        # package level to INFO — importing it after setLevel would
        # clobber the CRITICAL override (and the restore would write
        # back the pre-configuration NOTSET)
        import volcano_tpu.utils.logging  # noqa: F401

        logger = logging.getLogger("volcano_tpu")
        prev_level = logger.level
        logger.setLevel(logging.CRITICAL)
        try:
            return _run(args, out)
        finally:
            logger.setLevel(prev_level)
    return _run(args, out)


def _run(args, out) -> int:
    if args.replay:
        name, _, sid_s = args.replay.partition(":")
        if name not in MACHINES or not sid_s.lstrip("-").isdigit():
            print(f"--replay wants <machine>:<seed>, got {args.replay!r}",
                  file=out)
            return 2
        machine = MACHINES[name]()
        print(f"replaying {name} schedule {sid_s}:", file=out)
        v, steps = run_schedule(
            machine, int(sid_s), max_steps=args.max_steps,
            plant=args.plant, faults=args.faults, trace_out=out,
        )
        if v is not None:
            print(v.render(), file=out)
            return 1
        print(f"schedule {sid_s}: {steps} steps, invariants held",
              file=out)
        return 0

    schedules = (args.schedules if args.schedules is not None
                 else (100 if args.quick else 500))
    machines = args.machine or sorted(MACHINES)
    results = explore(
        machines, schedules, max_steps=args.max_steps,
        plant=args.plant, faults=args.faults, seed_base=args.seed_base,
    )
    failed = False
    total = 0
    for name in machines:
        r = results[name]
        total += r["schedules"]
        print(
            f"{name}: {r['schedules']} schedules, {r['steps']} steps, "
            f"{len(r['violations'])} violation(s)", file=out,
        )
        for vd in r["violations"]:
            print(Violation.from_dict(vd).render(), file=out)
            failed = True
    print(f"explore: {total} schedules total across "
          f"{len(machines)} machine(s)", file=out)
    if args.report:
        with open(args.report, "w", encoding="utf-8") as f:
            json.dump(results, f, indent=2)
            f.write("\n")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
