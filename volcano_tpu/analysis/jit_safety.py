"""Jit/tracer-safety pass — jitted functions must keep stable static
signatures.

Silent recompiles are the failure mode PR 4's row bucketing exists to
prevent: a jitted kernel whose Python-level control flow depends on
tracer *values* (not static arguments) either crashes at trace time or,
worse, retraces per distinct shape/value and quietly destroys the warm
jit cache.  Flagged inside any jit-wrapped function:

* ``JIT001`` — ``.item()`` on an array (host sync + concretization);
* ``JIT002`` — ``float()`` / ``int()`` / ``bool()`` on a non-constant
  (concretizes a tracer; at best a trace-time error, at worst a silent
  host round trip under ``jax.disable_jit``-style fallbacks);
* ``JIT003`` — an ``if`` / ``while`` test that references a non-static
  parameter directly (data-dependent Python branch on a tracer).
  References through ``.shape`` / ``.ndim`` / ``.dtype`` / ``len()``
  are static and allowed; parameters named in ``static_argnames`` /
  ``static_argnums`` are allowed.
* ``JIT004`` — a buffer passed to a ``donate_argnums`` position is read
  again after the donating call (reuse-after-donate: the buffer was
  invalidated).

Jit wrappers recognized: ``@jax.jit``, ``@functools.partial(jax.jit,
…)`` / ``@partial(jax.jit, …)``, and ``jax.jit(fn, …)`` over a local
``def`` in the same scope.  Waiver: ``# jit-ok: <reason>`` on the line.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from volcano_tpu.analysis.core import Finding, iter_source_files, SourceFile

PASS = "jit"
CODE_ITEM = "JIT001"
CODE_CONCRETIZE = "JIT002"
CODE_TRACER_BRANCH = "JIT003"
CODE_DONATE_REUSE = "JIT004"

_STATIC_ATTRS = {"shape", "ndim", "dtype", "size"}


def _is_jax_jit(node: ast.AST) -> bool:
    return (
        isinstance(node, ast.Attribute) and node.attr == "jit"
        and isinstance(node.value, ast.Name) and node.value.id == "jax"
    ) or (isinstance(node, ast.Name) and node.id == "jit")


def _jit_call_info(call: ast.Call) -> Optional[Dict]:
    """``jax.jit(...)`` / ``partial(jax.jit, ...)`` → {static, donate}."""
    if _is_jax_jit(call.func):
        args = call.args
    elif (
        isinstance(call.func, ast.Attribute) and call.func.attr == "partial"
        or isinstance(call.func, ast.Name) and call.func.id == "partial"
    ):
        if not (call.args and _is_jax_jit(call.args[0])):
            return None
        args = call.args[1:]
    else:
        return None
    static: Set[str] = set()
    static_nums: Set[int] = set()
    donate: Set[int] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            for el in ast.walk(kw.value):
                if isinstance(el, ast.Constant) and isinstance(el.value, str):
                    static.add(el.value)
        elif kw.arg in ("static_argnums", "donate_argnums"):
            nums = {
                el.value
                for el in ast.walk(kw.value)
                if isinstance(el, ast.Constant) and isinstance(el.value, int)
            }
            if kw.arg == "static_argnums":
                static_nums = nums
            else:
                donate = nums
    return {
        "static": static, "static_nums": static_nums, "donate": donate,
        "wrapped": args[0] if args else None,
    }


def _param_names(fn) -> List[str]:
    a = fn.args
    return [p.arg for p in (a.posonlyargs + a.args)]


class _JitBodyChecker(ast.NodeVisitor):
    def __init__(self, src: SourceFile, owner: str, tracer_params: Set[str],
                 findings: List[Finding]):
        self.src = src
        self.owner = owner
        self.tracer_params = tracer_params
        self.findings = findings

    def _emit(self, code: str, node: ast.AST, what: str, msg: str) -> None:
        if self.src.marker(node.lineno, "jit-ok"):
            return
        self.findings.append(Finding(
            PASS, code, self.src.rel, node.lineno,
            f"{self.owner}:{what}", msg,
        ))

    def visit_Call(self, node: ast.Call) -> None:
        if isinstance(node.func, ast.Attribute) and node.func.attr == "item":
            self._emit(
                CODE_ITEM, node, "item",
                "`.item()` inside a jitted function forces a host sync / "
                "concretization",
            )
        if (
            isinstance(node.func, ast.Name)
            and node.func.id in ("float", "int", "bool")
            and node.args
            and not isinstance(node.args[0], ast.Constant)
        ):
            self._emit(
                CODE_CONCRETIZE, node, node.func.id,
                f"`{node.func.id}()` on a non-constant inside jit "
                f"concretizes a tracer — use jnp casts or hoist out of "
                f"the jitted body",
            )
        self.generic_visit(node)

    def _tracer_refs(self, test: ast.AST) -> List[ast.Name]:
        """Name nodes in ``test`` that reference tracer params, minus
        static contexts (.shape/.ndim/.dtype/len())."""
        static_value_ids = set()
        for sub in ast.walk(test):
            if (
                isinstance(sub, ast.Attribute)
                and sub.attr in _STATIC_ATTRS
                and isinstance(sub.value, ast.Name)
            ):
                static_value_ids.add(id(sub.value))
            elif (
                isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Name)
                and sub.func.id in ("len", "isinstance", "type")
            ):
                for a in sub.args:
                    if isinstance(a, ast.Name):
                        static_value_ids.add(id(a))
            elif (
                isinstance(sub, ast.Compare)
                and any(isinstance(op, (ast.Is, ast.IsNot))
                        for op in sub.ops)
            ):
                # `x is None` checks identity, never a tracer value
                for a in [sub.left] + sub.comparators:
                    if isinstance(a, ast.Name):
                        static_value_ids.add(id(a))
        return [
            n for n in ast.walk(test)
            if isinstance(n, ast.Name)
            and n.id in self.tracer_params
            and id(n) not in static_value_ids
        ]

    def visit_If(self, node: ast.If) -> None:
        for ref in self._tracer_refs(node.test):
            self._emit(
                CODE_TRACER_BRANCH, node, ref.id,
                f"Python `if` on tracer parameter `{ref.id}` — "
                f"data-dependent branch retraces per value (use "
                f"jnp.where / lax.cond, or declare it in static_argnames)",
            )
            break
        self.generic_visit(node)

    def visit_While(self, node: ast.While) -> None:
        for ref in self._tracer_refs(node.test):
            self._emit(
                CODE_TRACER_BRANCH, node, ref.id,
                f"Python `while` on tracer parameter `{ref.id}` — use "
                f"lax.while_loop or declare it static",
            )
            break
        self.generic_visit(node)

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        # nested defs inherit the tracer params via closure
        for stmt in node.body:
            self.visit(stmt)

    visit_AsyncFunctionDef = visit_FunctionDef


def _check_jit_body(src: SourceFile, fn, info: Dict,
                    findings: List[Finding]) -> None:
    params = _param_names(fn)
    static = set(info["static"])
    for i in info["static_nums"]:
        if 0 <= i < len(params):
            static.add(params[i])
    tracer_params = {p for p in params if p not in static}
    checker = _JitBodyChecker(
        src, fn.name, tracer_params, findings,
    )
    for stmt in fn.body:
        checker.visit(stmt)


class _DonateTracker(ast.NodeVisitor):
    """Flag reads of a Name after it was passed in a donated position of
    a known donating callable (straight-line, per enclosing function)."""

    def __init__(self, src: SourceFile, donating: Dict[str, Set[int]],
                 findings: List[Finding]):
        self.src = src
        self.donating = donating
        self.findings = findings

    def _scan_linear(self, owner: str, body: List[ast.stmt]) -> None:
        #: name → line of the call that donated it
        donated: Dict[str, Tuple[int, str]] = {}
        for node in body:
            for sub in ast.walk(node):
                if isinstance(sub, ast.Name):
                    if isinstance(sub.ctx, ast.Store):
                        donated.pop(sub.id, None)  # rebound — fresh value
                    elif sub.id in donated:
                        at, callee = donated.pop(sub.id)
                        if not self.src.marker(sub.lineno, "jit-ok"):
                            self.findings.append(Finding(
                                PASS, CODE_DONATE_REUSE, self.src.rel,
                                sub.lineno, f"{owner}:{sub.id}",
                                f"`{sub.id}` was donated to `{callee}` at "
                                f"line {at} and read again — the donated "
                                f"buffer is invalid after the call",
                            ))
            # donations recorded AFTER scanning the node, so the call's
            # own argument read does not self-flag; an Assign target that
            # re-binds the donated name (buf = fn(buf, ...)) already
            # cleared it above via the Store visit ordering… walk order
            # is not guaranteed, so handle the common rebind explicitly:
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call) and isinstance(sub.func, ast.Name):
                    nums = self.donating.get(sub.func.id)
                    if not nums:
                        continue
                    for i, arg in enumerate(sub.args):
                        if i in nums and isinstance(arg, ast.Name):
                            donated[arg.id] = (sub.lineno, sub.func.id)
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        donated.pop(t.id, None)

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._scan_linear(node.name, node.body)
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef


def check_file(src: SourceFile) -> List[Finding]:
    findings: List[Finding] = []
    #: name → donate_argnums for jit-wrapped callables bound in this file
    donating: Dict[str, Set[int]] = {}
    defs: Dict[int, ast.FunctionDef] = {}
    for node in ast.walk(src.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.setdefault(node.name, node)

    for node in ast.walk(src.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                info = _jit_call_info(dec) if isinstance(dec, ast.Call) \
                    else ({"static": set(), "static_nums": set(),
                           "donate": set(), "wrapped": None}
                          if _is_jax_jit(dec) else None)
                if info is not None:
                    _check_jit_body(src, node, info, findings)
                    if info["donate"]:
                        donating[node.name] = info["donate"]
        elif isinstance(node, ast.Call):
            info = _jit_call_info(node)
            if info is None or info["wrapped"] is None:
                continue
            wrapped = info["wrapped"]
            if isinstance(wrapped, ast.Name) and wrapped.id in defs:
                _check_jit_body(src, defs[wrapped.id], info, findings)
        elif isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            # `g = jax.jit(f, donate_argnums=…)` — call sites donate
            # through the ASSIGNED name, so that is what the
            # reuse-after-donate tracker must watch
            info = _jit_call_info(node.value)
            if info is None or not info["donate"]:
                continue
            for t in node.targets:
                if isinstance(t, ast.Name):
                    donating[t.id] = set(info["donate"])
    if donating:
        _DonateTracker(src, donating, findings).visit(src.tree)
    return findings


def run(root: str) -> List[Finding]:
    findings: List[Finding] = []
    for src in iter_source_files(root, subdirs=("volcano_tpu/",)):
        findings.extend(check_file(src))
    return findings
