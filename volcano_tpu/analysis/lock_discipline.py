"""Lock-discipline pass — the staticcheck-style analog of Go's race
detector for this codebase's annotation convention.

An attribute assignment in a class body carrying (on its line, or in
the ``#:`` doc-comment block directly above)::

    self._items = deque()   # guarded-by: self._cv

declares that every read or write of ``self._items`` anywhere in the
class must happen lexically inside ``with self._cv:`` — with two
escapes:

* a function whose body carries ``# requires-lock: self._cv`` is a
  helper documented as "caller holds the lock"; its accesses are
  trusted (the call sites are checked, because they either hold the
  lock or are findings themselves);
* an access line carrying ``# unlocked-ok: <reason>`` is an explicit,
  reviewed waiver (e.g. a benign monotonic-flag read).

Module-level globals work the same way with a bare lock name::

    _breakers = {}   # guarded-by: _registry_lock

``__init__``/``__new__`` are exempt (construction precedes
publication).  A nested ``def`` RESETS the held-lock scope — closures
execute later, when the enclosing ``with`` has long exited — which is
exactly the bug class that motivates the reset.

Lock expressions are matched on their unparsed source text, so
``with self.api.locked():`` guards attributes declared
``# guarded-by: self.api.locked()``.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from volcano_tpu.analysis.core import Finding, iter_source_files, SourceFile

PASS = "lock"
#: guarded attribute touched outside its lock scope
CODE_UNLOCKED = "LCK001"
#: guarded-by annotation names a lock never taken anywhere in the class
CODE_DEAD_LOCK = "LCK002"

_EXEMPT_FUNCS = {"__init__", "__new__", "__del__"}


def _lock_exprs(with_node: ast.With) -> Set[str]:
    return {ast.unparse(item.context_expr) for item in with_node.items}


def _guarded_decls(src: SourceFile, body: List[ast.stmt]) -> Dict[str, str]:
    """``self.X = ...`` statements annotated ``# guarded-by: <lock>``
    → {attr: lock_expr}.  Scans every function in the class (attributes
    are overwhelmingly declared in ``__init__``, but lazily-initialized
    ones appear elsewhere)."""
    guarded: Dict[str, str] = {}

    def scan(stmts):
        for stmt in stmts:
            if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                lock = src.marker(stmt.lineno, "guarded-by")
                if lock:
                    targets = (
                        stmt.targets if isinstance(stmt, ast.Assign)
                        else [stmt.target]
                    )
                    for t in targets:
                        if (
                            isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"
                        ):
                            guarded[t.attr] = lock
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scan(stmt.body)
            elif isinstance(stmt, (ast.If, ast.For, ast.While, ast.With, ast.Try)):
                for sub in ast.iter_child_nodes(stmt):
                    if isinstance(sub, ast.stmt):
                        scan([sub])
                    elif isinstance(sub, (ast.excepthandler,)):
                        scan(sub.body)

    scan(body)
    return guarded


def _module_guarded(src: SourceFile) -> Dict[str, str]:
    """Module-level ``NAME = ...  # guarded-by: <lock>`` declarations."""
    guarded: Dict[str, str] = {}
    for stmt in src.tree.body:
        if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
            lock = src.marker(stmt.lineno, "guarded-by")
            if lock:
                targets = (
                    stmt.targets if isinstance(stmt, ast.Assign)
                    else [stmt.target]
                )
                for t in targets:
                    if isinstance(t, ast.Name):
                        guarded[t.id] = lock
    return guarded


class _AccessChecker(ast.NodeVisitor):
    """Walk one function body tracking the lexically-held lock set."""

    def __init__(
        self,
        src: SourceFile,
        owner: str,
        guarded_attrs: Dict[str, str],
        guarded_globals: Dict[str, str],
        findings: List[Finding],
        held: Optional[Set[str]] = None,
    ):
        self.src = src
        self.owner = owner  # "Class.method" or "function"
        self.guarded_attrs = guarded_attrs
        self.guarded_globals = guarded_globals
        self.findings = findings
        self.held: Set[str] = set(held or ())
        #: names locally bound in this scope shadow guarded globals
        self.local_names: Set[str] = set()
        #: names declared ``global`` — stores hit the module binding
        self.global_decls: Set[str] = set()

    # ---- lock scopes ----

    def visit_With(self, node: ast.With) -> None:
        prev = set(self.held)
        self.held |= _lock_exprs(node)
        for stmt in node.body:
            self.visit(stmt)
        self.held = prev
        # the with-items themselves (lock attribute reads) are exempt:
        # taking self._cv is how you GET into the guarded scope

    visit_AsyncWith = visit_With

    # ---- nested functions: closures run later, outside the lock ----

    def _visit_nested(self, node) -> None:
        req = self.src.func_marker(node, "requires-lock")
        held = {req} if req else set()
        sub = _AccessChecker(
            self.src, f"{self.owner}.{node.name}", self.guarded_attrs,
            self.guarded_globals, self.findings, held=held,
        )
        for stmt in node.body:
            sub.visit(stmt)

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._visit_nested(node)

    def visit_AsyncFunctionDef(self, node) -> None:
        self._visit_nested(node)

    def visit_Lambda(self, node: ast.Lambda) -> None:
        # a lambda is a closure too — reset the held set
        sub = _AccessChecker(
            self.src, f"{self.owner}.<lambda>", self.guarded_attrs,
            self.guarded_globals, self.findings, held=set(),
        )
        sub.visit(node.body)

    # ---- accesses ----

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if (
            isinstance(node.value, ast.Name)
            and node.value.id == "self"
            and node.attr in self.guarded_attrs
        ):
            lock = self.guarded_attrs[node.attr]
            if lock not in self.held and not self.src.marker(
                node.lineno, "unlocked-ok"
            ):
                self.findings.append(Finding(
                    PASS, CODE_UNLOCKED, self.src.rel, node.lineno,
                    f"{self.owner}:{node.attr}",
                    f"`self.{node.attr}` is guarded-by `{lock}` but "
                    f"touched outside a `with {lock}` scope",
                ))
        self.generic_visit(node)

    def visit_Global(self, node: ast.Global) -> None:
        self.global_decls.update(node.names)

    def visit_Name(self, node: ast.Name) -> None:
        if isinstance(node.ctx, ast.Store) and node.id not in self.global_decls:
            self.local_names.add(node.id)
            return
        if (
            node.id in self.guarded_globals
            and node.id not in self.local_names
        ):
            lock = self.guarded_globals[node.id]
            if lock not in self.held and not self.src.marker(
                node.lineno, "unlocked-ok"
            ):
                self.findings.append(Finding(
                    PASS, CODE_UNLOCKED, self.src.rel, node.lineno,
                    f"{self.owner}:{node.id}",
                    f"global `{node.id}` is guarded-by `{lock}` but "
                    f"touched outside a `with {lock}` scope",
                ))


def _check_class(
    src: SourceFile, cls: ast.ClassDef, guarded_globals: Dict[str, str],
    findings: List[Finding],
) -> None:
    guarded = _guarded_decls(src, cls.body)
    if not guarded:
        return
    locks_taken: Set[str] = set()
    for node in ast.walk(cls):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            locks_taken |= _lock_exprs(node)
    for fn in cls.body:
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if fn.name in _EXEMPT_FUNCS:
            continue
        req = src.func_marker(fn, "requires-lock")
        if req:
            locks_taken.add(req)
        held = {req} if req else set()
        checker = _AccessChecker(
            src, f"{cls.name}.{fn.name}", guarded, guarded_globals,
            findings, held=held,
        )
        for stmt in fn.body:
            checker.visit(stmt)
    for attr, lock in sorted(guarded.items()):
        if lock not in locks_taken:
            findings.append(Finding(
                PASS, CODE_DEAD_LOCK, src.rel, cls.lineno,
                f"{cls.name}.{attr}",
                f"guarded-by `{lock}` but `with {lock}` never appears in "
                f"class {cls.name} — stale annotation or missing locking",
            ))


def check_file(src: SourceFile) -> List[Finding]:
    findings: List[Finding] = []
    guarded_globals = _module_guarded(src)
    for node in src.tree.body:
        if isinstance(node, ast.ClassDef):
            _check_class(src, node, guarded_globals, findings)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if guarded_globals and node.name not in _EXEMPT_FUNCS:
                req = src.func_marker(node, "requires-lock")
                checker = _AccessChecker(
                    src, node.name, {}, guarded_globals, findings,
                    held={req} if req else set(),
                )
                for stmt in node.body:
                    checker.visit(stmt)
    return findings


def run(root: str) -> List[Finding]:
    findings: List[Finding] = []
    for src in iter_source_files(root, subdirs=("volcano_tpu/",)):
        findings.extend(check_file(src))
    return findings
