"""Runtime lock-order verifier — the deadlock-class detector.

The class of deadlock PR 3 hit (an admission review forwarded back to
the submitting connection, parking a reader on a lock its own thread
had to release) is invisible to unit tests until the exact interleave
fires.  This module makes it *systematically* detectable: with
``VTPU_LOCK_ORDER=1``, every ``threading.Lock`` / ``RLock`` /
``Condition`` **created by volcano_tpu code** is wrapped in an
instrumented proxy that records, per thread, the stack of locks held,
and adds an edge ``A → B`` to a global acquisition graph whenever a
thread acquires ``B`` while holding ``A``.  A cycle in that graph is a
lock-order inversion — two threads can deadlock under the right
interleave even if this run got lucky.

* Detection is immediate: the edge insert runs a reachability check and
  records a violation the moment an inversion appears (the report names
  both creation sites and both acquisition stacks).
* RLock re-entry is not an edge (same instance, same thread).
* ``Condition.wait`` is handled through the ``_release_save`` /
  ``_acquire_restore`` protocol, so the held-stack stays truthful
  across waits.
* Locks created outside ``volcano_tpu/`` are left untouched — the
  verifier watches the system under test, not pytest internals.

Wire-up: ``tests/conftest.py`` installs the verifier when
``VTPU_LOCK_ORDER=1`` and asserts :func:`check_acyclic` at session end;
CI runs the chaos + commit-plane suites under it.
"""

from __future__ import annotations

import os
import threading
import traceback
from typing import Dict, List, Optional, Set, Tuple

_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_real_lock = threading.Lock
_real_rlock = threading.RLock
_real_condition = threading.Condition


class LockOrderViolation:
    """One recorded inversion: acquiring ``to_site`` while holding
    ``from_site`` after the opposite order was already observed."""

    def __init__(self, cycle_sites: List[str], stack: str, thread: str):
        self.cycle_sites = cycle_sites
        self.stack = stack
        self.thread = thread

    def render(self) -> str:
        chain = " -> ".join(self.cycle_sites + [self.cycle_sites[0]])
        return (
            f"lock-order cycle {chain}\n  closed by thread {self.thread}"
            f" at:\n{self.stack}"
        )


class _Graph:
    """The cross-thread acquisition graph.  Nodes are lock *instances*
    (two locks born at one site are distinct — ABBA between two
    instances of the same class is a real deadlock); reports aggregate
    to creation sites for readability."""

    def __init__(self):
        self.mutex = _real_lock()
        #: lock id → creation site "file:line"
        self.sites: Dict[int, str] = {}
        #: edge (a, b): thread acquired b while holding a
        self.edges: Dict[int, Set[int]] = {}
        self.violations: List[LockOrderViolation] = []
        #: strong refs to every registered proxy — the graph is keyed by
        #: id(), so a GC'd proxy whose memory CPython reuses for a new
        #: lock would otherwise inherit the dead lock's edges and
        #: fabricate phantom cycles.  Bounded by the session's lock
        #: count (a few thousand across the whole suite).
        self._keep: List[object] = []
        self._tls = threading.local()

    # ---- per-thread held stack ----

    def held(self) -> List[Tuple[int, int]]:
        """[(lock_id, recursion_count)] for the calling thread."""
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    # ---- events ----

    def register(self, lock, site: str) -> None:
        """Accepts the proxy object itself (kept alive so its id stays
        unique for the graph's lifetime) or, in unit tests driving the
        graph directly, a bare int id."""
        with self.mutex:
            if isinstance(lock, int):
                self.sites[lock] = site
            else:
                self._keep.append(lock)
                self.sites[id(lock)] = site

    def acquired(self, lock_id: int, count: int = 1) -> None:
        stack = self.held()
        for i, (lid, n) in enumerate(stack):
            if lid == lock_id:
                stack[i] = (lid, n + count)
                return  # re-entry: no new edge
        new_edges = [(lid, lock_id) for lid, _n in stack]
        stack.append((lock_id, count))
        if not new_edges:
            return
        with self.mutex:
            for a, b in new_edges:
                peers = self.edges.setdefault(a, set())
                if b in peers:
                    continue
                peers.add(b)
                cycle = self._find_path(b, a)
                if cycle is not None:
                    # cycle is the path b → … → a; render() closes it
                    # back to b
                    self.violations.append(LockOrderViolation(
                        [self.sites.get(x, f"lock-{x}") for x in cycle],
                        "".join(traceback.format_stack(limit=12)[:-2]),
                        threading.current_thread().name,
                    ))

    def released(self, lock_id: int) -> int:
        """Drop one recursion level; returns remaining count.  A full
        release (``_release_save``) calls :meth:`released_all`."""
        stack = self.held()
        for i in range(len(stack) - 1, -1, -1):
            lid, n = stack[i]
            if lid == lock_id:
                if n <= 1:
                    del stack[i]
                    return 0
                stack[i] = (lid, n - 1)
                return n - 1
        return 0

    def released_all(self, lock_id: int) -> int:
        stack = self.held()
        for i in range(len(stack) - 1, -1, -1):
            lid, n = stack[i]
            if lid == lock_id:
                del stack[i]
                return n
        return 0

    def _find_path(self, start: int, goal: int) -> Optional[List[int]]:
        """DFS path start→goal (caller holds ``self.mutex``)."""
        seen = {start}
        path = [start]

        def dfs(node: int) -> bool:
            if node == goal:
                return True
            for nxt in self.edges.get(node, ()):
                if nxt in seen:
                    continue
                seen.add(nxt)
                path.append(nxt)
                if dfs(nxt):
                    return True
                path.pop()
            return False

        return path if dfs(start) else None

    def report(self) -> dict:
        with self.mutex:
            return {
                "locks": len(self.sites),
                "edges": sorted(
                    (self.sites.get(a, str(a)), self.sites.get(b, str(b)))
                    for a, peers in self.edges.items() for b in peers
                ),
                "violations": [v.render() for v in self.violations],
            }


_graph: Optional[_Graph] = None

#: optional sync-event listener (the happens-before race detector in
#: :mod:`~volcano_tpu.analysis.race` registers here): notified on every
#: acquire/release of an instrumented lock so vector clocks can ride
#: the SAME proxies the lock-order verifier installs.  ``released`` is
#: called BEFORE the inner lock is released (the lock's clock must be
#: published while the releasing thread still holds it) and
#: ``acquired`` after the inner acquire returns (the thread joins the
#: clock only once it owns the lock).
_listener = None


def set_listener(listener) -> None:
    global _listener
    _listener = listener


class _InstrumentedLock:
    """Proxy over a real Lock/RLock recording acquire/release order.
    Forwards the ``_release_save`` / ``_acquire_restore`` / ``_is_owned``
    protocol so ``threading.Condition`` (and its ``wait``) work
    unchanged on top of an instrumented RLock."""

    __slots__ = ("_inner", "_id", "_site")

    def __init__(self, inner, site: str):
        self._inner = inner
        self._id = id(self)
        self._site = site
        if _graph is not None:
            _graph.register(self, site)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got:
            if _graph is not None:
                _graph.acquired(self._id)
            if _listener is not None:
                _listener.lock_acquired(self._id)
        return got

    def release(self) -> None:
        if _listener is not None:
            # before the inner release: the clock must be on the lock
            # while this thread still owns it
            _listener.lock_released(self._id)
        self._inner.release()
        if _graph is not None:
            _graph.released(self._id)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False

    def locked(self) -> bool:
        return self._inner.locked()

    # ---- Condition protocol ----

    def _release_save(self):
        if _listener is not None:
            _listener.lock_released(self._id)
        state = self._inner._release_save() if hasattr(
            self._inner, "_release_save"
        ) else (self._inner.release() or None)
        if _graph is not None:
            count = _graph.released_all(self._id)
            return (state, count)
        return (state, 1)

    def _acquire_restore(self, saved) -> None:
        state, count = saved
        if hasattr(self._inner, "_acquire_restore"):
            self._inner._acquire_restore(state)
        else:
            self._inner.acquire()
        if _graph is not None:
            _graph.acquired(self._id, count=count)
        if _listener is not None:
            _listener.lock_acquired(self._id)

    def _is_owned(self) -> bool:
        if hasattr(self._inner, "_is_owned"):
            return self._inner._is_owned()
        # plain Lock fallback (threading.Condition's own trick)
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True

    def __repr__(self) -> str:
        return f"<InstrumentedLock {self._site} over {self._inner!r}>"


def _creation_site() -> Optional[str]:
    """First stack frame inside volcano_tpu/ but outside this module;
    None when the lock is created by foreign code (left raw)."""
    for frame in traceback.extract_stack()[-8:][::-1]:
        fn = frame.filename
        if fn == __file__ or os.sep + "threading.py" in fn:
            continue
        if fn.startswith(_PKG_DIR):
            return f"{os.path.relpath(fn, os.path.dirname(_PKG_DIR))}:{frame.lineno}"
        return None
    return None


def _make_lock():
    site = _creation_site()
    inner = _real_lock()
    return _InstrumentedLock(inner, site) if site else inner


def _make_rlock():
    site = _creation_site()
    inner = _real_rlock()
    return _InstrumentedLock(inner, site) if site else inner


def install() -> None:
    """Patch the ``threading`` lock factories.  ``Condition()`` with no
    explicit lock picks up the instrumented RLock automatically (it
    resolves ``RLock`` through the module global)."""
    global _graph
    if _graph is not None:
        return
    _graph = _Graph()
    threading.Lock = _make_lock
    threading.RLock = _make_rlock


def uninstall() -> None:
    global _graph
    threading.Lock = _real_lock
    threading.RLock = _real_rlock
    _graph = None


def enabled() -> bool:
    return _graph is not None


def report() -> dict:
    """Acquisition-graph summary (empty when not installed)."""
    return _graph.report() if _graph is not None else {
        "locks": 0, "edges": [], "violations": [],
    }


def violations() -> List[LockOrderViolation]:
    return list(_graph.violations) if _graph is not None else []


def check_acyclic() -> None:
    """Raise AssertionError naming every recorded inversion."""
    vs = violations()
    if vs:
        raise AssertionError(
            "lock-order verifier recorded %d inversion(s):\n%s"
            % (len(vs), "\n".join(v.render() for v in vs))
        )
