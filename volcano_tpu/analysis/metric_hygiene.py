"""Metric hygiene pass (MTR) — bounded label vocabularies and no
orphaned catalog entries.

Prometheus label values are series keys: an unbounded vocabulary
(job names, pod names, error strings) mints one series per distinct
value forever — the classic cardinality explosion every scrape then
pays for.  And a metric helper nobody calls is a catalog entry that
dashboards reference and operators trust while it silently exports
nothing.  Two codes:

* **MTR001 (unbounded label)** — every ``registry.inc`` /
  ``set_gauge`` / ``histogram`` call whose label dict carries a
  NON-LITERAL value must declare the vocabulary's bound: either the
  enclosing function's docstring names it (``result ∈ {scheduled,
  unschedulable, error}`` — the catalog's existing idiom) or a
  ``# label-vocab: <label> — <what bounds it>`` comment inside the
  function does.  The declaration is checked per label key; an
  undeclared dynamic label is a finding.  Routing a value through
  :func:`metrics.bounded_label` (the cardinality cap) and saying so in
  the declaration is the canonical fix for genuinely-operator-shaped
  input.
* **MTR002 (orphaned metric)** — a helper defined in
  ``volcano_tpu/metrics/metrics.py`` (``update_*`` / ``register_*`` /
  ``observe_*``) that no product module ever calls.  Tests don't
  count: a metric only a test observes is still dead in production.

Inline waiver: ``# mtr: <reason>`` on the offending line (the shared
marker discipline, reason mandatory).
"""

from __future__ import annotations

import ast
import re
from typing import List, Optional

from volcano_tpu.analysis.core import Finding, SourceFile, iter_source_files

PASS_ID = "mtr"
METRICS_FILE = "volcano_tpu/metrics/metrics.py"
_EMIT_METHODS = {"inc", "set_gauge", "histogram"}
_HELPER_PREFIXES = ("update_", "register_", "observe_")


def _is_registry(node: ast.AST) -> bool:
    """The emission receiver: a name/attribute chain ending in
    ``registry`` (module-level ``registry`` or ``metrics.registry``)."""
    if isinstance(node, ast.Name):
        return node.id == "registry"
    if isinstance(node, ast.Attribute):
        return node.attr == "registry"
    return False


def _vocab_declarations(src: SourceFile, func: ast.AST) -> str:
    """Every ``label-vocab:`` comment value inside the function span,
    joined — one declaration may bound several labels ("from, to —
    the executor rung names")."""
    end = getattr(func, "end_lineno", func.lineno)
    parts: List[str] = []
    for ln in range(func.lineno, end + 1):
        comment = src.comments.get(ln)
        if comment is None:
            continue
        body = comment.lstrip(":").strip()
        if body.startswith("label-vocab:"):
            parts.append(body[len("label-vocab:"):].strip())
    return " ".join(parts)


def _declares(label: str, docstring: str, vocab: str) -> bool:
    if f"{label} ∈" in docstring:
        return True
    return bool(re.search(rf"\b{re.escape(label)}\b", vocab))


def _check_call(
    src: SourceFile, func: Optional[ast.AST], call: ast.Call,
    findings: List[Finding],
) -> None:
    if not (isinstance(call.func, ast.Attribute)
            and call.func.attr in _EMIT_METHODS
            and _is_registry(call.func.value)):
        return
    if len(call.args) < 2:
        return
    if src.marker(call.lineno, "mtr"):
        return
    symbol = getattr(func, "name", "<module>")
    docstring = (ast.get_docstring(func) or "") if isinstance(
        func, (ast.FunctionDef, ast.AsyncFunctionDef)
    ) else ""
    vocab = _vocab_declarations(src, func) if func is not None else ""
    labels = call.args[1]
    if not isinstance(labels, ast.Dict):
        # a whole dict built elsewhere — undeclarable statically;
        # require the declaration comment naming what bounds it
        if not (docstring and "∈" in docstring) and not vocab:
            findings.append(Finding(
                PASS_ID, "MTR001", src.rel, call.lineno, symbol,
                "label dict is not a literal and no vocabulary is "
                "declared (docstring '∈' or '# label-vocab:')",
            ))
        return
    for key_node, value_node in zip(labels.keys, labels.values):
        if not isinstance(key_node, ast.Constant):
            continue
        if isinstance(value_node, ast.Constant):
            continue  # literal value — bounded by construction
        label = str(key_node.value)
        if not _declares(label, docstring, vocab):
            findings.append(Finding(
                PASS_ID, "MTR001", src.rel, call.lineno,
                f"{symbol}.{label}",
                f"label {label!r} takes a non-literal value with no "
                f"declared vocabulary — document the bound "
                f"('{label} ∈ {{...}}' in the docstring or a "
                f"'# label-vocab: {label} — ...' comment), or route "
                f"through metrics.bounded_label",
            ))


def _walk_with_scope(
    src: SourceFile, node: ast.AST, func: Optional[ast.AST],
    findings: List[Finding],
) -> None:
    for child in ast.iter_child_nodes(node):
        scope = func
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
            scope = child
        if isinstance(child, ast.Call):
            _check_call(src, func, child, findings)
        _walk_with_scope(src, child, scope, findings)


def _helpers(src: SourceFile) -> List[ast.FunctionDef]:
    """Metric helpers: module-level defs with an emitting prefix whose
    body actually touches the registry."""
    out = []
    for node in src.tree.body:
        if not isinstance(node, ast.FunctionDef):
            continue
        if not node.name.startswith(_HELPER_PREFIXES):
            continue
        emits = any(
            isinstance(sub, ast.Call) and isinstance(sub.func, ast.Attribute)
            and sub.func.attr in _EMIT_METHODS | {"observe"}
            for sub in ast.walk(node)
        )
        if emits:
            out.append(node)
    return out


def run(root: str) -> List[Finding]:
    findings: List[Finding] = []
    metrics_src: Optional[SourceFile] = None
    product_texts: List[str] = []
    for src in iter_source_files(root, subdirs=("volcano_tpu/",)):
        _walk_with_scope(src, src.tree, None, findings)
        if src.rel == METRICS_FILE:
            metrics_src = src
        elif not src.rel.startswith("volcano_tpu/metrics/"):
            product_texts.append(src.text)
    if metrics_src is not None:
        blob = "\n".join(product_texts)
        for helper in _helpers(metrics_src):
            if metrics_src.marker(helper.lineno, "mtr"):
                continue
            if not re.search(rf"\b{re.escape(helper.name)}\b", blob):
                findings.append(Finding(
                    PASS_ID, "MTR002", METRICS_FILE, helper.lineno,
                    helper.name,
                    f"metric helper {helper.name!r} is never called from "
                    f"any product module — wire it where the reference "
                    f"observes it, or delete the catalog entry",
                ))
    return findings
