"""Happens-before race detector — the enforcement layer over the
``# guarded-by:`` declarations.

The lexical lock-discipline pass (LCK001) proves that every *spelled*
access of a guarded attribute sits inside a ``with <lock>`` block — but
an access through an alias (``st = self._points; st[...] = ...`` from
another module) or a cross-module touch never spells ``self.<attr>``
and escapes the pass entirely.  PR 7 fixed six unlocked-access races
found lexically; this module finds the ones the text cannot show, at
runtime, with vector clocks:

* Every thread carries a vector clock.  Sync edges come from the SAME
  instrumented-lock proxies the lock-order verifier installs
  (``lock_order.set_listener``), plus ``threading.Thread`` start/join,
  ``queue.Queue`` put/get, and ``threading.Event`` set/wait —
  ``Condition`` wait/notify is ordered through its lock's clock via the
  proxies' ``_release_save``/``_acquire_restore`` protocol, which is
  the actual happens-before a condition variable provides.
* Every attribute declared ``# guarded-by: <lock>`` anywhere under
  ``volcano_tpu/`` is wrapped in a data descriptor
  (:func:`instrument_package`): each read/write from volcano_tpu code
  is checked against the variable's last-access epochs (a FastTrack-
  style write epoch + per-thread read epochs).  Two accesses, at least
  one a write, with no happens-before path between them, is a data
  race — regardless of which module, alias, or closure performed it.
* The lexical pass stays the *declaration* layer (what state is
  shared, which lock owns it); this detector is the *enforcement*
  layer (the declared discipline actually orders every access).

A declaration line may carry ``# race-ok: <reason>`` to waive runtime
tracking for one attribute (e.g. a benign monotonic flag read) — the
reason is mandatory, mirroring ``# unlocked-ok:``.

Wire-up mirrors ``lock_order``: ``tests/conftest.py`` installs the
detector under ``VTPU_RACE=1`` *before any volcano_tpu import*, fails
the test that recorded a fresh race (per-test attribution), fails the
session on any unwaived race, and dumps the full report as JSON when
``VTPU_RACE_REPORT=<path>`` is set.  CI runs the chaos, commit-plane,
federation and bus-HA suites under it.
"""

from __future__ import annotations

import itertools
import json
import os
import queue as _queue_mod
import sys
import threading
from typing import Dict, List, Optional, Tuple

from volcano_tpu.analysis import lock_order
from volcano_tpu.analysis.core import SourceFile, iter_source_files

_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_ROOT_DIR = os.path.dirname(_PKG_DIR)

#: reports kept; past this the detector stops recording (a broken
#: build would otherwise fill memory with one cascading race)
_MAX_REPORTS = 200

#: stack frames remembered per access (file:line strings — cheap
#: ``sys._getframe`` walk, not a full traceback render)
_SITE_DEPTH = 4


def _short(path: str) -> str:
    if path.startswith(_ROOT_DIR):
        return os.path.relpath(path, _ROOT_DIR)
    return path


def _fmt_site(site) -> List[str]:
    """Render the raw ``(filename, lineno)`` pairs a site captures —
    lazily, at report time, never on the per-access hot path."""
    return [f"{_short(fn)}:{lineno}" for fn, lineno in site]


class RaceReport:
    """One detected race: two accesses to ``symbol`` (at least one a
    write) with no happens-before edge between them."""

    def __init__(self, symbol: str, kind: str,
                 prev_thread: str, prev_site: List[Tuple[str, int]],
                 cur_thread: str, cur_site: List[Tuple[str, int]]):
        self.symbol = symbol
        self.kind = kind  # "write-write" | "read-write" | "write-read"
        self.prev_thread = prev_thread
        self.prev_site = prev_site  # raw (filename, lineno) pairs
        self.cur_thread = cur_thread
        self.cur_site = cur_site

    @property
    def key(self) -> Tuple:
        first = lambda s: s[0] if s else None  # noqa: E731
        return (self.symbol, self.kind, first(self.prev_site),
                first(self.cur_site))

    def render(self) -> str:
        prev = "\n    ".join(_fmt_site(self.prev_site)) or "?"
        cur = "\n    ".join(_fmt_site(self.cur_site)) or "?"
        return (
            f"data race ({self.kind}) on {self.symbol}\n"
            f"  earlier access by {self.prev_thread} at:\n    {prev}\n"
            f"  racing access by {self.cur_thread} at:\n    {cur}"
        )

    def to_dict(self) -> dict:
        return {
            "symbol": self.symbol, "kind": self.kind,
            "prev_thread": self.prev_thread,
            "prev_site": _fmt_site(self.prev_site),
            "cur_thread": self.cur_thread,
            "cur_site": _fmt_site(self.cur_site),
        }


class _ThreadState:
    __slots__ = ("idx", "vc", "name", "busy")

    def __init__(self, idx: int, name: str):
        self.idx = idx
        #: vector clock: thread idx → logical time
        self.vc: Dict[int, int] = {idx: 1}
        self.name = name
        #: re-entrancy latch: a GC pass triggered by the detector's own
        #: allocations can run a ``__del__`` that releases an
        #: instrumented lock, re-entering the detector while its mutex
        #: is held — those nested events are skipped (a destructor is
        #: not a synchronization point), which is what keeps the
        #: non-reentrant mutex deadlock-free
        self.busy = False


class _VarState:
    """FastTrack-style shadow state for one (instance, attribute)."""

    __slots__ = ("write", "write_site", "write_thread", "reads")

    def __init__(self):
        #: last write epoch (thread idx, clock) or None
        self.write: Optional[Tuple[int, int]] = None
        self.write_site: List[str] = []
        self.write_thread = ""
        #: thread idx → (clock, site, thread name) for reads since the
        #: last ordered write
        self.reads: Dict[int, Tuple[int, List[str], str]] = {}


_det_ids = itertools.count()


class Detector:
    """The vector-clock engine.  One instance is installed globally by
    :func:`install`; tests may drive a private instance directly."""

    def __init__(self, restrict_to_pkg: bool = True):
        # raw primitives — the detector must never run through the
        # instrumented proxies it listens to
        self._mutex = lock_order._real_lock()
        self._tids = itertools.count(1)
        self._tls = threading.local()
        #: namespaces this detector's entries in the `_race_vc0` /
        #: `_race_vcf` thread attributes: thread indices are a
        #: PER-DETECTOR numbering, so a private test detector adopting
        #: a clock the globally installed one stamped on the thread
        #: would fabricate happens-before edges (colliding indices) and
        #: mask real races
        self._det_id = next(_det_ids)
        #: sync-object id → vector clock (locks by proxy id, queues and
        #: events by object id)
        self._sync: Dict[int, Dict[int, int]] = {}
        #: queues/events pinned alive while their clock exists — locks
        #: are already pinned by lock_order's registry, but a gc'd
        #: Queue's recycled id would hand its stale clock to an
        #: unrelated object and fabricate happens-before edges (false
        #: negatives).  Only send() creates _sync entries, so pinning
        #: at send time closes the hazard.
        self._keep_sync: Dict[int, object] = {}
        #: (id(instance), attr-symbol) → shadow state; instances are
        #: kept alive by the strong key holder so a recycled id cannot
        #: inherit a dead object's epochs (the lock_order._keep rule)
        self._vars: Dict[Tuple[int, str], _VarState] = {}
        self._keep: List[object] = []
        self.reports: List[RaceReport] = []
        self._seen_keys: set = set()
        self.restrict_to_pkg = restrict_to_pkg
        #: accesses checked (observability for tests / the report)
        self.n_accesses = 0

    # ---- per-thread clocks ----

    def _state(self) -> _ThreadState:
        st = getattr(self._tls, "st", None)
        if st is None:
            # NEVER threading.current_thread() here: during
            # _bootstrap_inner the thread sets its started Event BEFORE
            # registering in _active, and current_thread() would mint a
            # _DummyThread whose __init__ sets ITS started Event —
            # infinite recursion through the patched Event.set.  A
            # non-creating _active lookup is enough; threads started
            # through the patched Thread.start refine name + parent
            # clock in child_started().
            ident = threading.get_ident()
            cur = threading._active.get(ident)
            idx = next(self._tids)
            st = _ThreadState(
                idx, cur.name if cur is not None else f"thread-{ident}"
            )
            forked = getattr(cur, "_race_vc0", None) if cur else None
            parent = forked.get(self._det_id) if forked else None
            if parent:
                self._join(st.vc, parent)
            self._tls.st = st
        return st

    def child_started(self, thread: threading.Thread) -> None:
        """First call on a child thread started through the patched
        ``Thread.start``: adopt the parent's forked clock (idempotent —
        joins are monotone) and the thread's real name."""
        st = self._state()
        st.name = thread.name
        forked = getattr(thread, "_race_vc0", None)
        parent = forked.get(self._det_id) if forked else None
        if parent:
            self._join(st.vc, parent)

    @staticmethod
    def _join(into: Dict[int, int], other: Dict[int, int]) -> None:
        for k, v in other.items():
            if v > into.get(k, 0):
                into[k] = v

    # ---- sync edges ----

    def send(self, obj_id: int, pin: Optional[object] = None) -> None:
        """Publish the calling thread's clock onto a sync object
        (lock release, queue put, event set, thread fork).  ``pin``
        keeps an un-registered sync object (queue, event) alive so its
        id cannot be recycled while its clock is live."""
        st = self._state()
        if st.busy:
            return
        st.busy = True
        try:
            with self._mutex:
                if pin is not None and obj_id not in self._keep_sync:
                    self._keep_sync[obj_id] = pin
                vc = self._sync.setdefault(obj_id, {})
                self._join(vc, st.vc)
            st.vc[st.idx] = st.vc.get(st.idx, 0) + 1
        finally:
            st.busy = False

    def recv(self, obj_id: int) -> None:
        """Adopt a sync object's clock (lock acquire, queue get, event
        wait, thread join)."""
        st = self._state()
        if st.busy:
            return
        st.busy = True
        try:
            with self._mutex:
                vc = self._sync.get(obj_id)
                if vc:
                    self._join(st.vc, vc)
        finally:
            st.busy = False

    # the lock_order listener protocol
    def lock_released(self, lock_id: int) -> None:
        self.send(lock_id)

    def lock_acquired(self, lock_id: int) -> None:
        self.recv(lock_id)

    # thread lifecycle (patched Thread.start/join call these)
    def fork(self, thread: threading.Thread) -> None:
        st = self._state()
        forked = getattr(thread, "_race_vc0", None)
        if forked is None:
            forked = {}
            thread._race_vc0 = forked
        forked[self._det_id] = dict(st.vc)
        st.vc[st.idx] = st.vc.get(st.idx, 0) + 1

    def joined(self, thread: threading.Thread) -> None:
        finals = getattr(thread, "_race_vcf", None)
        final = finals.get(self._det_id) if finals else None
        if final:
            st = self._state()
            self._join(st.vc, final)

    def thread_exit(self, thread: threading.Thread) -> None:
        st = getattr(self._tls, "st", None)
        if st is not None:
            finals = getattr(thread, "_race_vcf", None)
            if finals is None:
                finals = {}
                thread._race_vcf = finals
            finals[self._det_id] = dict(st.vc)

    # ---- tracked accesses ----

    def _site(self, frame) -> List[Tuple[str, int]]:
        """Raw ``(filename, lineno)`` pairs — the walk must happen at
        access time (frames mutate as execution continues), but the
        path-shortening/string formatting is deferred to report
        rendering: this runs on EVERY tracked read inside the global
        detector mutex, and the strings are discarded unless a race is
        later reported against this epoch."""
        out: List[Tuple[str, int]] = []
        f = frame
        while f is not None and len(out) < _SITE_DEPTH:
            fn = f.f_code.co_filename
            if not fn.startswith("<"):
                out.append((fn, f.f_lineno))
            f = f.f_back
        return out

    def record(self, obj, symbol: str, is_write: bool, frame) -> None:
        """One read/write of a tracked attribute.  ``frame`` is the
        accessing frame (the descriptor passes its caller)."""
        if self.restrict_to_pkg:
            fn = frame.f_code.co_filename
            if not fn.startswith(_PKG_DIR):
                return  # tests/bench poking at internals: not product
        st = self._state()
        if st.busy:
            return
        st.busy = True
        try:
            self._record_locked(obj, symbol, is_write, frame, st)
        finally:
            st.busy = False

    def _record_locked(self, obj, symbol: str, is_write: bool, frame,
                       st: _ThreadState) -> None:
        my = st.vc
        clk = my.get(st.idx, 0)
        key = (id(obj), symbol)
        with self._mutex:
            self.n_accesses += 1
            var = self._vars.get(key)
            if var is None:
                var = self._vars[key] = _VarState()
                self._keep.append(obj)
            races: List[Tuple[str, str, list]] = []
            w = var.write
            if w is not None and my.get(w[0], 0) < w[1]:
                races.append((
                    "write-write" if is_write else "write-read",
                    var.write_thread, var.write_site,
                ))
            if is_write:
                for ridx, (rclk, rsite, rname) in var.reads.items():
                    if ridx != st.idx and my.get(ridx, 0) < rclk:
                        races.append(("read-write", rname, rsite))
            site = None
            if races and len(self.reports) < _MAX_REPORTS:
                site = self._site(frame)
                for kind, pname, psite in races:
                    rep = RaceReport(symbol, kind, pname, psite,
                                     st.name, site)
                    if rep.key not in self._seen_keys:
                        self._seen_keys.add(rep.key)
                        self.reports.append(rep)
            if is_write:
                var.write = (st.idx, clk)
                var.write_site = site if site is not None else \
                    self._site(frame)
                var.write_thread = st.name
                # a write ordered after (or racing — reported once)
                # every read resets the read set: FastTrack's
                # read-clear, which also stops cascade reports
                var.reads.clear()
            else:
                var.reads[st.idx] = (clk, self._site(frame), st.name)

    # ---- reporting ----

    def report(self) -> dict:
        with self._mutex:
            return {
                "accesses": self.n_accesses,
                "tracked_vars": len(self._vars),
                "races": [r.to_dict() for r in self.reports],
            }


_detector: Optional[Detector] = None

_orig_thread_start = threading.Thread.start
_orig_thread_join = threading.Thread.join
#: the clock transfer hooks `_put`/`_get`, not `put`/`get`: those run
#: while the queue's own mutex is held, so the channel-clock merge is
#: atomic with the item transfer AND only happens on success — hooking
#: around `put` would either fabricate a producer→consumer edge when a
#: bounded put raises Full (send-before-put), or open a window where a
#: consumer gets the item before the producer's clock lands
#: (send-after-put → false positive).  Each class defines its own
#: `_put`/`_get` (Lifo/Priority override), so all three are patched.
_QUEUE_CLASSES = (
    _queue_mod.Queue, _queue_mod.LifoQueue, _queue_mod.PriorityQueue,
)
_orig_queue_internals = {
    cls: (cls._put, cls._get) for cls in _QUEUE_CLASSES
}
_orig_event_set = threading.Event.set
_orig_event_wait = threading.Event.wait


def _patched_start(self):
    det = _detector
    if det is not None:
        det.fork(self)
        orig_run = self.run

        def _run_capturing_final_clock():
            d0 = _detector
            if d0 is not None:
                d0.child_started(self)
            try:
                orig_run()
            finally:
                # published BEFORE _bootstrap_inner wakes joiners, so
                # a join that returns always sees the final clock
                d = _detector
                if d is not None:
                    d.thread_exit(self)

        self.run = _run_capturing_final_clock
    return _orig_thread_start(self)


def _patched_join(self, timeout=None):
    _orig_thread_join(self, timeout)
    det = _detector
    # the edge is recorded only when the thread is observed dead — and
    # in CPython that observation IS a synchronization: both a
    # completed join and is_alive() itself acquire the dying thread's
    # tstate lock, which _bootstrap_inner releases AFTER our wrapped
    # run published the final clock.  Residual corner: a timed-out
    # join whose thread dies in the gap AND whose tstate lock was
    # already reaped by a THIRD thread's is_alive() — this thread then
    # adopts the clock off a flag read it never synchronized on.  No
    # product path does that (timed-join shutdown sites don't share a
    # corpse across observers); accepting it avoids the alternative —
    # treating every timed join as non-synchronizing — which would
    # false-positive every join(timeout)-then-cleanup shutdown path.
    if det is not None and not self.is_alive():
        det.joined(self)


def _make_patched_put(orig):
    def _patched_put(self, item):
        orig(self, item)
        # under self.mutex (queue.put holds it around _put): atomic
        # with the insertion, unreachable when a bounded put raises
        det = _detector
        if det is not None:
            det.send(id(self), pin=self)
    return _patched_put


def _make_patched_get(orig):
    def _patched_get(self):
        # under self.mutex: every completed _put's clock is already on
        # the channel, including the popped item's producer
        det = _detector
        if det is not None:
            det.recv(id(self))
        return orig(self)
    return _patched_get


def _patched_event_set(self):
    det = _detector
    if det is not None:
        det.send(id(self), pin=self)
    return _orig_event_set(self)


def _patched_event_wait(self, timeout=None):
    got = _orig_event_wait(self, timeout)
    det = _detector
    if det is not None and got:
        det.recv(id(self))
    return got


# ---- guarded-state discovery + descriptor instrumentation ----

class GuardedAttr:
    """One ``# guarded-by:`` declaration found in the tree."""

    __slots__ = ("module", "cls", "attr", "lock", "waived")

    def __init__(self, module: str, cls: str, attr: str, lock: str,
                 waived: Optional[str]):
        self.module = module
        self.cls = cls
        self.attr = attr
        self.lock = lock
        self.waived = waived

    @property
    def symbol(self) -> str:
        return f"{self.module}:{self.cls}.{self.attr}"


def scan_guarded(root: Optional[str] = None) -> List[GuardedAttr]:
    """Every class-attribute ``# guarded-by:`` declaration under
    ``volcano_tpu/`` with its ``# race-ok:`` waiver, if any.  Module
    globals stay lexical-only (there is no portable runtime hook for a
    module binding) — the LCK pass keeps covering those."""
    import ast

    root = root or _ROOT_DIR
    out: List[GuardedAttr] = []
    for src in iter_source_files(root, subdirs=("volcano_tpu/",)):
        module = src.rel[:-3].replace("/", ".")
        if module.endswith(".__init__"):
            module = module[: -len(".__init__")]
        for node in src.tree.body:
            if not isinstance(node, ast.ClassDef):
                continue
            for attr, lock, lineno in _class_guarded(src, node):
                out.append(GuardedAttr(
                    module, node.name, attr, lock,
                    src.marker(lineno, "race-ok"),
                ))
    return out


def _class_guarded(src: SourceFile, cls) -> List[Tuple[str, str, int]]:
    import ast

    found: Dict[str, Tuple[str, int]] = {}
    for node in ast.walk(cls):
        if not isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            continue
        lock = src.marker(node.lineno, "guarded-by")
        if not lock:
            continue
        targets = (
            node.targets if isinstance(node, ast.Assign) else [node.target]
        )
        for t in targets:
            if (
                isinstance(t, ast.Attribute)
                and isinstance(t.value, ast.Name)
                and t.value.id == "self"
            ):
                found.setdefault(t.attr, (lock, node.lineno))
    return [(a, lk, ln) for a, (lk, ln) in sorted(found.items())]


class _TrackedAttr:
    """Data descriptor interposed on a guarded class attribute.  Values
    live under the SAME name in the instance ``__dict__`` (a data
    descriptor wins the lookup either way, and instances constructed
    before instrumentation keep working) or delegate to the original
    slot descriptor for ``__slots__`` classes — semantics, including
    ``hasattr`` and ``vars()``, are unchanged."""

    def __init__(self, det: Detector, name: str, symbol: str,
                 slot=None, class_default=None, has_default: bool = False):
        self.det = det
        self.name = name
        self.symbol = symbol
        self.slot = slot
        self.storage = name
        self.class_default = class_default
        self.has_default = has_default

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        self.det.record(obj, self.symbol, False, sys._getframe(1))
        if self.slot is not None:
            return self.slot.__get__(obj, objtype)
        try:
            return obj.__dict__[self.storage]
        except KeyError:
            if self.has_default:
                return self.class_default
            raise AttributeError(self.name) from None

    def __set__(self, obj, value):
        self.det.record(obj, self.symbol, True, sys._getframe(1))
        if self.slot is not None:
            self.slot.__set__(obj, value)
        else:
            obj.__dict__[self.storage] = value

    def __delete__(self, obj):
        self.det.record(obj, self.symbol, True, sys._getframe(1))
        if self.slot is not None:
            self.slot.__delete__(obj)
        else:
            try:
                del obj.__dict__[self.storage]
            except KeyError:
                raise AttributeError(self.name) from None


def instrument_class(det: Detector, cls: type, attrs, prefix: str) -> int:
    """Install tracked descriptors for ``attrs`` on ``cls``; returns
    how many were installed."""
    n = 0
    for attr in attrs:
        existing = cls.__dict__.get(attr)
        if isinstance(existing, _TrackedAttr):
            continue
        slot = None
        class_default = None
        has_default = False
        if existing is not None:
            if hasattr(type(existing), "__set__") and hasattr(
                type(existing), "__get__"
            ):
                slot = existing  # member_descriptor from __slots__
            else:
                class_default = existing  # plain class-level default
                has_default = True
        setattr(cls, attr, _TrackedAttr(
            det, attr, f"{prefix}.{attr}",
            slot=slot, class_default=class_default,
            has_default=has_default,
        ))
        n += 1
    return n


def instrument_package(root: Optional[str] = None) -> dict:
    """Import every module carrying guarded declarations and wrap the
    declared attributes.  Returns a summary dict (counts + skips) for
    the report.  Must run after :func:`install` and before the system
    under test constructs instances (conftest calls it at import
    time)."""
    import importlib

    det = _detector
    assert det is not None, "race.install() first"
    decls = scan_guarded(root)
    by_class: Dict[Tuple[str, str], List[GuardedAttr]] = {}
    for d in decls:
        by_class.setdefault((d.module, d.cls), []).append(d)
    installed = 0
    waived: List[str] = []
    skipped: List[str] = []
    for (module, cls_name), ds in sorted(by_class.items()):
        try:
            mod = importlib.import_module(module)
            cls = getattr(mod, cls_name, None)
        except Exception as e:  # noqa: BLE001 — a module that cannot
            # import under the test env is skipped, named in the report
            skipped.append(f"{module}: {e}")
            continue
        if cls is None or not isinstance(cls, type):
            skipped.append(f"{module}.{cls_name}: not importable as a class")
            continue
        live = [d.attr for d in ds if not d.waived]
        waived.extend(d.symbol for d in ds if d.waived)
        installed += instrument_class(
            det, cls, live, f"{module}.{cls_name}"
        )
    return {
        "instrumented_attrs": installed,
        "waived": sorted(waived),
        "skipped": sorted(skipped),
    }


def install(restrict_to_pkg: bool = True) -> Detector:
    """Install the global detector: lock-proxy listener + thread/queue/
    event patches.  Idempotent.  Must precede every volcano_tpu import
    so each lock construction runs through the instrumented factory."""
    global _detector
    if _detector is not None:
        return _detector
    lock_order.install()
    _detector = Detector(restrict_to_pkg=restrict_to_pkg)
    lock_order.set_listener(_detector)
    threading.Thread.start = _patched_start
    threading.Thread.join = _patched_join
    for cls, (oput, oget) in _orig_queue_internals.items():
        cls._put = _make_patched_put(oput)
        cls._get = _make_patched_get(oget)
    threading.Event.set = _patched_event_set
    threading.Event.wait = _patched_event_wait
    return _detector


def uninstall() -> None:
    global _detector
    lock_order.set_listener(None)
    threading.Thread.start = _orig_thread_start
    threading.Thread.join = _orig_thread_join
    for cls, (oput, oget) in _orig_queue_internals.items():
        cls._put = oput
        cls._get = oget
    threading.Event.set = _orig_event_set
    threading.Event.wait = _orig_event_wait
    _detector = None


def enabled() -> bool:
    return _detector is not None


def get_detector() -> Optional[Detector]:
    return _detector


def races() -> List[RaceReport]:
    return list(_detector.reports) if _detector is not None else []


def report() -> dict:
    if _detector is None:
        return {"accesses": 0, "tracked_vars": 0, "races": []}
    return _detector.report()


def dump_report(path: str, extra: Optional[dict] = None) -> None:
    data = report()
    if extra:
        data.update(extra)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=2)
        f.write("\n")


def check_clean() -> None:
    """Raise AssertionError naming every recorded race."""
    rs = races()
    if rs:
        raise AssertionError(
            "happens-before race detector recorded %d race(s):\n%s"
            % (len(rs), "\n".join(r.render() for r in rs))
        )
