"""VBUS serde/version-drift pass — the v1-stamping rule PR 6's review
caught by hand, made machine-checked.

Six invariants over the bus protocol surface:

* ``SRD001`` — every object kind registered in
  ``bus/protocol.py::KINDS`` has a serde round-trip exemplar in
  ``tests/test_bus.py::SERDE_EXEMPLARS`` (the parameterized round-trip
  test covers exactly that mapping, so a kind added to the registry
  without a fixture fails the lint before it fails in production).
* ``SRD002`` — every op the server dispatches
  (``bus/server.py::_execute``) is version-registered in
  ``protocol.OP_VERSIONS``.  An unregistered op has no declared
  compatibility story.
* ``SRD003`` — every op introduced after ``MIN_VERSION`` must be
  version-gated on the client: the ``bus/remote.py`` method that sends
  it must carry the old-peer fallback (textually, it handles the
  ``unknown bus op`` typed error).  Version skew costs throughput,
  never correctness.
* ``SRD004`` — an op the client sends that the server does not handle
  (or vice versa: a registered op nobody dispatches) is drift between
  the two halves of the protocol.
* ``SRD005`` — the README's VBUS version-ladder paragraph must declare
  the CURRENT protocol version (``max(OP_VERSIONS.values())``) and
  name every registered op.  PR 11 caught the ladder still reading
  "version 3" three versions late — by hand; this makes the doc-drift
  machine-checked.  Judged only when README.md exists (a repo
  checkout), like SRD001.
* ``SRD006`` — the exemplar corpus must round-trip through BOTH wire
  codecs: some test in ``tests/test_bus.py`` must drive
  ``SERDE_EXEMPLARS`` through the binary (``CODEC_BINARY``) framing,
  not just JSON.  A kind whose encoded form survives JSON but not
  msgpack (bytes values, non-string map keys) would otherwise ship
  undetected the day a binary peer connects.

This pass imports ``volcano_tpu.bus.protocol`` (our own package — the
registries are the source of truth) and parses ``server.py`` /
``remote.py`` / the test module as AST.
"""

from __future__ import annotations

import ast
import os
from typing import List, Optional, Set

from volcano_tpu.analysis.core import Finding, SourceFile

PASS = "serde"
CODE_NO_ROUNDTRIP = "SRD001"
CODE_UNREGISTERED_OP = "SRD002"
CODE_UNGATED_OP = "SRD003"
CODE_OP_DRIFT = "SRD004"
CODE_DOC_DRIFT = "SRD005"
CODE_NO_BINARY_ROUNDTRIP = "SRD006"

_PROTO = "volcano_tpu/bus/protocol.py"
_SERVER = "volcano_tpu/bus/server.py"
_REMOTE = "volcano_tpu/bus/remote.py"
_TESTS = "tests/test_bus.py"
_README = "README.md"

#: the README version-ladder paragraph opens with this phrase
_LADDER_RE = r"wire protocol is at \*\*VBUS version (\d+)\*\*"


def _load(root: str, rel: str) -> Optional[SourceFile]:
    path = os.path.join(root, rel)
    if not os.path.exists(path):
        return None
    with open(path, encoding="utf-8") as f:
        return SourceFile(path, rel, f.read())


def _server_ops(src: SourceFile) -> Set[str]:
    """String constants compared against ``op`` in ``_execute``."""
    ops: Set[str] = set()
    for node in ast.walk(src.tree):
        if not (isinstance(node, ast.FunctionDef) and node.name == "_execute"):
            continue
        for sub in ast.walk(node):
            if isinstance(sub, ast.Compare) and len(sub.comparators) == 1:
                left, right = sub.left, sub.comparators[0]
                for a, b in ((left, right), (right, left)):
                    if (
                        isinstance(a, ast.Name) and a.id == "op"
                        and isinstance(b, ast.Constant)
                        and isinstance(b.value, str)
                    ):
                        ops.add(b.value)
    return ops


def _client_ops(src: SourceFile) -> dict:
    """op name → enclosing function source text, for every
    ``{"op": "<name>", ...}`` payload literal in remote.py."""
    ops = {}
    for node in ast.walk(src.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        fn_src = ast.get_source_segment(src.text, node) or ""
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Dict):
                continue
            for k, v in zip(sub.keys, sub.values):
                if (
                    isinstance(k, ast.Constant) and k.value == "op"
                    and isinstance(v, ast.Constant)
                    and isinstance(v.value, str)
                ):
                    # outermost enclosing function wins (first visit)
                    ops.setdefault(v.value, fn_src)
    return ops


def _has_binary_roundtrip(src: SourceFile) -> bool:
    """True when some test function drives the ``SERDE_EXEMPLARS``
    corpus through the binary framing — textually, its source
    references both the corpus and ``CODEC_BINARY``."""
    for node in ast.walk(src.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if not node.name.startswith("test"):
            continue
        fn_src = ast.get_source_segment(src.text, node) or ""
        if "SERDE_EXEMPLARS" in fn_src and "CODEC_BINARY" in fn_src:
            return True
    return False


def _exemplar_kinds(src: SourceFile) -> Optional[Set[str]]:
    """Keys of the module-level ``SERDE_EXEMPLARS`` mapping, or None
    when the mapping does not exist at all."""
    for node in src.tree.body:
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
            value = node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
            value = node.value
        else:
            continue
        for t in targets:
            if isinstance(t, ast.Name) and t.id == "SERDE_EXEMPLARS":
                keys: Set[str] = set()
                if isinstance(value, ast.Dict):
                    for k in value.keys:
                        if isinstance(k, ast.Constant) and isinstance(
                            k.value, str
                        ):
                            keys.add(k.value)
                return keys
    return None


def run(root: str) -> List[Finding]:
    from volcano_tpu.bus import protocol

    findings: List[Finding] = []

    # ---- SRD001: round-trip exemplar per registered kind ----
    # Judged only when the tests tree is present (a repo checkout).  An
    # installed package has no tests/ directory — flagging every kind
    # there would make `vtctl lint` unusable outside the repo.
    tests = _load(root, _TESTS)
    if tests is not None:
        exemplars = _exemplar_kinds(tests)
        for kind in sorted(protocol.KINDS):
            if exemplars is None or kind not in exemplars:
                findings.append(Finding(
                    PASS, CODE_NO_ROUNDTRIP, _TESTS, 1, kind,
                    f"kind `{kind}` is registered in bus/protocol.py "
                    f"KINDS but has no serde round-trip exemplar in "
                    f"{_TESTS}::SERDE_EXEMPLARS",
                ))
        # SRD006: the same corpus must survive the binary framing too
        if exemplars is not None and not _has_binary_roundtrip(tests):
            findings.append(Finding(
                PASS, CODE_NO_BINARY_ROUNDTRIP, _TESTS, 1,
                "binary-roundtrip",
                f"{_TESTS} round-trips SERDE_EXEMPLARS through JSON "
                f"only — no test drives the corpus through the binary "
                f"framing (protocol.CODEC_BINARY), so a kind whose "
                f"encoding survives JSON but not msgpack would ship "
                f"undetected",
            ))

    # ---- op registries ----
    op_versions = getattr(protocol, "OP_VERSIONS", None)
    server = _load(root, _SERVER)
    remote = _load(root, _REMOTE)
    server_ops = _server_ops(server) if server is not None else set()
    client_ops = _client_ops(remote) if remote is not None else {}

    if op_versions is None:
        for op in sorted(server_ops):
            findings.append(Finding(
                PASS, CODE_UNREGISTERED_OP, _PROTO, 1, op,
                "bus/protocol.py declares no OP_VERSIONS registry — every "
                "op needs a declared protocol version",
            ))
        return findings

    # SRD002: server dispatches an op with no declared version
    for op in sorted(server_ops - set(op_versions)):
        findings.append(Finding(
            PASS, CODE_UNREGISTERED_OP, _SERVER, 1, op,
            f"server dispatches op `{op}` but protocol.OP_VERSIONS does "
            f"not declare its introduction version",
        ))

    # SRD003: post-v1 ops must carry the old-peer fallback client-side
    for op, version in sorted(op_versions.items()):
        if version <= protocol.MIN_VERSION:
            continue
        fn_src = client_ops.get(op)
        if fn_src is not None and "unknown bus op" not in fn_src:
            findings.append(Finding(
                PASS, CODE_UNGATED_OP, _REMOTE, 1, op,
                f"op `{op}` was introduced at protocol v{version} > "
                f"MIN_VERSION={protocol.MIN_VERSION} but the client "
                f"method sending it has no old-peer fallback (must "
                f"handle the `unknown bus op` typed error)",
            ))

    # SRD004: drift between the two halves
    for op in sorted(set(client_ops) - server_ops):
        findings.append(Finding(
            PASS, CODE_OP_DRIFT, _REMOTE, 1, op,
            f"client sends op `{op}` that bus/server.py _execute never "
            f"dispatches",
        ))
    for op in sorted(set(op_versions) - server_ops):
        findings.append(Finding(
            PASS, CODE_OP_DRIFT, _PROTO, 1, op,
            f"protocol.OP_VERSIONS declares op `{op}` that bus/server.py "
            f"_execute never dispatches",
        ))

    # ---- SRD005: README version ladder tracks OP_VERSIONS ----
    readme_path = os.path.join(root, _README)
    if os.path.exists(readme_path):
        with open(readme_path, encoding="utf-8") as f:
            readme = f.read()
        findings.extend(_check_ladder(readme, op_versions))
    return findings


def _check_ladder(readme: str, op_versions) -> List[Finding]:
    """The VBUS version-ladder paragraph (located by its "wire protocol
    is at **VBUS version N**" opener, ending at the next heading) must
    declare ``max(OP_VERSIONS.values())`` and mention every registered
    op as a backticked token."""
    import re

    findings: List[Finding] = []
    current = max(op_versions.values())
    m = re.search(_LADDER_RE, readme)
    if m is None:
        return [Finding(
            PASS, CODE_DOC_DRIFT, _README, 1, "version-ladder",
            "README has no VBUS version-ladder paragraph (expected "
            "'wire protocol is at **VBUS version N**') — the protocol "
            "surface must be documented",
        )]
    lineno = readme.count("\n", 0, m.start()) + 1
    declared = int(m.group(1))
    if declared != current:
        findings.append(Finding(
            PASS, CODE_DOC_DRIFT, _README, lineno, "version-ladder",
            f"README declares VBUS version {declared} but "
            f"protocol.OP_VERSIONS tops out at v{current} — the stale "
            f"ladder paragraph again",
        ))
    # the section runs to the next markdown HEADING ("# " .. "###### ")
    # outside a code fence — a bare "\n#" search would truncate at a
    # `# comment` line inside a fenced shell example
    section_end = None
    in_fence = False
    pos = m.end()
    for line_m in re.finditer(r"^(.*)$", readme[pos:], re.MULTILINE):
        line = line_m.group(1)
        if line.lstrip().startswith("```"):
            in_fence = not in_fence
        elif not in_fence and re.match(r"#{1,6} ", line):
            section_end = pos + line_m.start()
            break
    section = readme[m.start(): section_end]
    mentioned = set(re.findall(r"`([a-z0-9_]+)`", section))
    for op in sorted(set(op_versions) - mentioned):
        findings.append(Finding(
            PASS, CODE_DOC_DRIFT, _README, lineno, op,
            f"op `{op}` (v{op_versions[op]}) is registered in "
            f"protocol.OP_VERSIONS but the README version-ladder "
            f"paragraph never names it",
        ))
    return findings
