"""Scheduler internal API — the pure data model of a scheduling session.

Reference: pkg/scheduler/api.  ClusterInfo/JobInfo/TaskInfo/NodeInfo/
QueueInfo plus Resource arithmetic.  This host-side model is the source of
truth for session semantics; the device path packs it into tensors
(volcano_tpu.ops.pack) and must produce identical bindings.
"""

from volcano_tpu.api.types import (
    TaskStatus,
    NodePhase,
    allocated_status,
    ValidateResult,
)
from volcano_tpu.api.resource import Resource, MIN_MILLI_CPU, MIN_MEMORY, MIN_MILLI_SCALAR
from volcano_tpu.api.job_info import TaskInfo, JobInfo, new_task_info
from volcano_tpu.api.node_info import NodeInfo
from volcano_tpu.api.queue_info import QueueInfo, NamespaceInfo, NamespaceCollection
from volcano_tpu.api.cluster_info import ClusterInfo
from volcano_tpu.api.unschedule_info import FitError, FitErrors

__all__ = [
    "TaskStatus",
    "NodePhase",
    "allocated_status",
    "ValidateResult",
    "Resource",
    "MIN_MILLI_CPU",
    "MIN_MEMORY",
    "MIN_MILLI_SCALAR",
    "TaskInfo",
    "JobInfo",
    "new_task_info",
    "NodeInfo",
    "QueueInfo",
    "NamespaceInfo",
    "NamespaceCollection",
    "ClusterInfo",
    "FitError",
    "FitErrors",
]
