"""ClusterInfo — the immutable snapshot a session computes on.

Reference: pkg/scheduler/api/cluster_info.go.
"""

from __future__ import annotations

from typing import Dict

from volcano_tpu.api.job_info import JobInfo
from volcano_tpu.api.node_info import NodeInfo
from volcano_tpu.api.queue_info import NamespaceInfo, QueueInfo


class ClusterInfo:
    def __init__(self):
        self.jobs: Dict[str, JobInfo] = {}
        self.nodes: Dict[str, NodeInfo] = {}
        self.queues: Dict[str, QueueInfo] = {}
        self.namespace_info: Dict[str, NamespaceInfo] = {}
        #: PVCs keyed "ns/name" — consumed by the volume-binding
        #: predicate (the vendored VolumeBindingChecker analogue).
        self.pvcs: Dict[str, object] = {}

    def __repr__(self) -> str:
        return (
            f"Cluster: {len(self.jobs)} jobs, {len(self.nodes)} nodes, "
            f"{len(self.queues)} queues"
        )
