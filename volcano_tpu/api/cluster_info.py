"""ClusterInfo — the immutable snapshot a session computes on.

Reference: pkg/scheduler/api/cluster_info.go.
"""

from __future__ import annotations

from typing import Dict

from volcano_tpu.api.job_info import JobInfo
from volcano_tpu.api.node_info import NodeInfo
from volcano_tpu.api.queue_info import NamespaceInfo, QueueInfo


class ClusterInfo:
    def __init__(self):
        self.jobs: Dict[str, JobInfo] = {}
        self.nodes: Dict[str, NodeInfo] = {}
        self.queues: Dict[str, QueueInfo] = {}
        self.namespace_info: Dict[str, NamespaceInfo] = {}
        #: PVCs keyed "ns/name" — consumed by the volume-binding
        #: predicate (the vendored VolumeBindingChecker analogue).
        self.pvcs: Dict[str, object] = {}
        #: PackEpoch describing what changed since the warm packer's last
        #: consumed revision (cache/cache.py); None for caches that do
        #: not track dirtiness (tests' fakes, custom Cache impls).
        self.pack_epoch = None
        #: clone-pool generation for opt-in snapshot reuse (cache.snapshot
        #: ↔ cache.release_session_clones handshake)
        self.clone_gen: int = 0

    def __repr__(self) -> str:
        return (
            f"Cluster: {len(self.jobs)} jobs, {len(self.nodes)} nodes, "
            f"{len(self.queues)} queues"
        )
