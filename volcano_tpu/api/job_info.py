"""TaskInfo and JobInfo — per-session views of pods and pod groups.

Reference: pkg/scheduler/api/job_info.go.
"""

from __future__ import annotations

from typing import Dict, Optional

from volcano_tpu.api.resource import empty_resource, Resource
from volcano_tpu.api.types import (
    allocated_status,
    ALLOCATED_STATUSES,
    TaskStatus,
)
from volcano_tpu.api.unschedule_info import FitErrors
from volcano_tpu.apis import core, scheduling

#: status sets for the readiness rollups (job_info.go:346-398) — hot on
#: every PriorityQueue compare, so plain set membership
_READY_STATUSES = frozenset(ALLOCATED_STATUSES | {TaskStatus.Succeeded})
_VALID_STATUSES = frozenset(
    ALLOCATED_STATUSES
    | {TaskStatus.Succeeded, TaskStatus.Pipelined, TaskStatus.Pending}
)


def _task_status_from_pod(pod: core.Pod) -> TaskStatus:
    """Map pod phase + nodeName + deletion to TaskStatus (job_info.go getTaskStatus)."""
    phase = pod.status.phase
    if phase == "Running":
        if pod.metadata.deletion_timestamp is not None:
            return TaskStatus.Releasing
        return TaskStatus.Running
    if phase == "Pending":
        if pod.metadata.deletion_timestamp is not None:
            return TaskStatus.Releasing
        if pod.spec.node_name:
            return TaskStatus.Bound
        return TaskStatus.Pending
    if phase == "Succeeded":
        return TaskStatus.Succeeded
    if phase == "Failed":
        return TaskStatus.Failed
    return TaskStatus.Unknown


def get_job_id(pod: core.Pod) -> str:
    gn = pod.metadata.annotations.get(scheduling.GROUP_NAME_ANNOTATION_KEY, "")
    if gn:
        return f"{pod.metadata.namespace}/{gn}"
    return ""


class TaskInfo:
    """One pod in the scheduler (job_info.go:38-93)."""

    __slots__ = (
        "uid",
        "job",
        "name",
        "namespace",
        "resreq",
        "init_resreq",
        "node_name",
        "status",
        "priority",
        "volume_ready",
        "pod",
    )

    def __init__(
        self,
        uid: str,
        job: str,
        name: str,
        namespace: str,
        resreq: Resource,
        init_resreq: Optional[Resource] = None,
        node_name: str = "",
        status: TaskStatus = TaskStatus.Pending,
        priority: int = 1,
        pod: Optional[core.Pod] = None,
    ):
        self.uid = uid
        self.job = job
        self.name = name
        self.namespace = namespace
        self.resreq = resreq
        # share, not clone: both attributes are immutable post-construction
        # (see clone() below)
        self.init_resreq = init_resreq if init_resreq is not None else resreq
        self.node_name = node_name
        self.status = status
        self.priority = priority
        self.volume_ready = False
        self.pod = pod

    @property
    def best_effort(self) -> bool:
        return self.resreq.is_empty()

    def clone(self) -> "TaskInfo":
        # __new__ bypass — two clones per placement (statement/node copy
        # + cache bind copy) put this on the session hot path.
        t = TaskInfo.__new__(TaskInfo)
        t.uid = self.uid
        t.job = self.job
        t.name = self.name
        t.namespace = self.namespace
        # resreq/init_resreq are IMMUTABLE after new_task_info — every
        # accounting op copies into owner-held accumulators (job.allocated,
        # node.idle, ...), never mutates a task's request in place
        # (job_info.go clones here; the invariant makes sharing safe and
        # removes two Resource copies per placement).  Anything that needs
        # a different request must REPLACE the attribute, not mutate it.
        t.resreq = self.resreq
        t.init_resreq = self.init_resreq
        t.node_name = self.node_name
        t.status = self.status
        t.priority = self.priority
        t.volume_ready = self.volume_ready
        t.pod = self.pod
        return t

    @property
    def creation_timestamp(self) -> float:
        return self.pod.metadata.creation_timestamp if self.pod else 0.0

    def __repr__(self) -> str:
        return (
            f"Task ({self.uid}:{self.namespace}/{self.name}): job {self.job}, "
            f"status {self.status.name}, pri {self.priority}, resreq {self.resreq}"
        )


def pod_request_resource(pod: core.Pod) -> Resource:
    """Summed container requests (the reference's GetPodResourceRequest
    without the init-container max — i.e. exactly what NodeInfo
    accounting charges per held task).  The single copy shared by
    new_task_info, the federation spill ledger, and the federation
    policy checker, so spill candidate selection and equivalence
    verification can never drift from the scheduler's own node
    accounting.  Init containers are deliberately excluded: the running
    steady state is what node Used/Idle tracks."""
    resreq = Resource()
    for c in pod.spec.containers:
        resreq.add(Resource.from_resource_list(c.resources.get("requests") or {}))
    return resreq


def new_task_info(pod: core.Pod) -> TaskInfo:
    """Build a TaskInfo from a Pod (job_info.go:68-93).

    Resreq sums container requests; InitResreq additionally maxes with init
    containers (pod_info.go:53-79).  Each quantity is converted to milli
    units *before* summing, exactly like the reference's per-quantity
    MilliValue — summing raw floats first would accumulate binary-float
    error (0.1+0.1+0.1 → 301 mCPU after ceil).
    """
    resreq = pod_request_resource(pod)
    init_resreq = resreq.clone()
    for c in pod.spec.init_containers:
        init_resreq.set_max(Resource.from_resource_list(c.resources.get("requests") or {}))
    # freeze: both objects are shared across every clone of this task
    # (see TaskInfo.clone), so an in-place mutation anywhere would skew
    # all of them — the guard makes that fail loudly under __debug__
    resreq.freeze()
    init_resreq.freeze()
    return TaskInfo(
        uid=pod.metadata.uid or f"{pod.metadata.namespace}/{pod.metadata.name}",
        job=get_job_id(pod),
        name=pod.metadata.name,
        namespace=pod.metadata.namespace,
        resreq=resreq,
        init_resreq=init_resreq,
        node_name=pod.spec.node_name,
        status=_task_status_from_pod(pod),
        priority=pod.spec.priority if pod.spec.priority is not None else 1,
        pod=pod,
    )


class JobInfo:
    """One PodGroup's worth of tasks (job_info.go:127-309)."""

    def __init__(self, uid: str, name: str = "", namespace: str = ""):
        self.uid = uid
        self.name = name
        self.namespace = namespace
        self.queue: str = ""
        self.priority: int = 0
        self.min_available: int = 0
        self.pod_group: Optional[scheduling.PodGroup] = None
        self.creation_timestamp: float = 0.0

        self.tasks: Dict[str, TaskInfo] = {}
        self.task_status_index: Dict[TaskStatus, Dict[str, TaskInfo]] = {}
        #: count of tasks in _READY_STATUSES, maintained by _index/_unindex
        #: — ready_task_num() is on the per-comparison hot path (PQ job
        #: order, gang readiness) and the bucket-sum recompute was ~4% of
        #: the whole generic apply loop
        self.ready_num: int = 0

        self.allocated: Resource = empty_resource()
        self.total_request: Resource = empty_resource()

        # diagnostics (job_info.go NodesFitDelta / NodesFitErrors)
        self.nodes_fit_delta: Dict[str, Resource] = {}
        self.nodes_fit_errors: Dict[str, FitErrors] = {}
        self.job_fit_errors: str = ""

    # ---- task bookkeeping ----

    def _index(self, task: TaskInfo) -> None:
        bucket = self.task_status_index.setdefault(task.status, {})
        # the dict write is idempotent under a watch-echo double add
        # (cache._add_task races its own bind echo) — the counter must
        # be too, so only count a uid actually entering the bucket
        if task.uid not in bucket and task.status in _READY_STATUSES:
            self.ready_num += 1
        bucket[task.uid] = task

    def _unindex(self, task: TaskInfo) -> None:
        bucket = self.task_status_index.get(task.status)
        if bucket and task.uid in bucket:
            del bucket[task.uid]
            if not bucket:
                del self.task_status_index[task.status]
            if task.status in _READY_STATUSES:
                self.ready_num -= 1

    def add_task_info(self, task: TaskInfo) -> None:
        self.tasks[task.uid] = task
        self._index(task)
        if allocated_status(task.status):
            self.allocated.add(task.resreq)
        self.total_request.add(task.resreq)

    def update_task_status(self, task: TaskInfo, status: TaskStatus) -> None:
        """Move a task between status buckets, maintaining Allocated rollup
        (job_info.go UpdateTaskStatus)."""
        existing = self.tasks.get(task.uid)
        if existing is not None:
            self.delete_task_info(existing)
        task.status = status
        self.add_task_info(task)

    def delete_task_info(self, task: TaskInfo) -> None:
        stored = self.tasks.pop(task.uid, None)
        if stored is None:
            return
        self._unindex(stored)
        if allocated_status(stored.status):
            self.allocated.sub(stored.resreq)
        self.total_request.sub_unchecked(stored.resreq)

    def set_pod_group(self, pg: scheduling.PodGroup) -> None:
        self.name = pg.metadata.name
        self.namespace = pg.metadata.namespace
        self.min_available = pg.spec.min_member
        self.queue = pg.spec.queue
        self.creation_timestamp = pg.metadata.creation_timestamp
        self.pod_group = pg

    # ---- readiness (job_info.go:346-398) ----

    def ready_task_num(self) -> int:
        return self.ready_num

    def waiting_task_num(self) -> int:
        return len(self.task_status_index.get(TaskStatus.Pipelined, {}))

    def valid_task_num(self) -> int:
        return sum(
            len(tasks)
            for status, tasks in self.task_status_index.items()
            if status in _VALID_STATUSES
        )

    def ready(self) -> bool:
        return self.ready_task_num() >= self.min_available

    def pipelined(self) -> bool:
        return self.waiting_task_num() + self.ready_task_num() >= self.min_available

    def fit_error(self) -> str:
        """Status histogram message for unschedulable jobs (job_info.go:327-344)."""
        reasons = {status.name: len(tasks) for status, tasks in self.task_status_index.items()}
        reasons["minAvailable"] = self.min_available
        hist = sorted(f"{v} {k}" for k, v in reasons.items())
        return f"pod group is not ready, {', '.join(hist)}."

    def clone(self) -> "JobInfo":
        # Field-level copy (same rationale as NodeInfo.clone): replaying
        # add_task_info per task re-sums allocated/total_request and
        # rebuilds the index at ~4µs/task — at 50k tasks that's the
        # second-largest snapshot cost.  The copy keeps the cache's
        # incrementally-maintained rollups as-is.  __new__ bypass: the
        # __init__ route re-created five dicts and two Resources per job
        # just to overwrite them — measurable at 10k-job snapshots.
        info = JobInfo.__new__(JobInfo)
        info.uid = self.uid
        info.name = self.name
        info.namespace = self.namespace
        info.queue = self.queue
        info.priority = self.priority
        info.min_available = self.min_available
        info.pod_group = self.pod_group
        info.creation_timestamp = self.creation_timestamp
        info.allocated = self.allocated.clone()
        info.total_request = self.total_request.clone()
        info.ready_num = self.ready_num
        info.nodes_fit_delta = {}
        info.nodes_fit_errors = {}
        info.job_fit_errors = ""
        tasks = info.tasks = {}
        index = info.task_status_index = {}
        for uid, t in self.tasks.items():
            ti = t.clone()
            tasks[uid] = ti
            bucket = index.get(ti.status)
            if bucket is None:
                bucket = index[ti.status] = {}
            bucket[uid] = ti
        return info

    def __repr__(self) -> str:
        return (
            f"Job ({self.uid}): namespace {self.namespace} ({self.queue}), "
            f"name {self.name}, minAvailable {self.min_available}"
        )
