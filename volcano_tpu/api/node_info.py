"""NodeInfo — per-session resource accounting for one node.

Reference: pkg/scheduler/api/node_info.go.
"""

from __future__ import annotations

from typing import Dict, Optional

from volcano_tpu.api.job_info import TaskInfo
from volcano_tpu.api.resource import empty_resource, Resource
from volcano_tpu.api.types import NodePhase, TaskStatus
from volcano_tpu.apis import core


class NodeInfo:
    """Idle/Used/Releasing/Pipelined accounting (node_info.go:27-58)."""

    def __init__(self, node: Optional[core.Node] = None):
        self.node = node
        self.name = node.metadata.name if node else ""
        self.releasing = empty_resource()
        self.pipelined = empty_resource()
        self.used = empty_resource()
        self.tasks: Dict[str, TaskInfo] = {}
        self.others: Dict[str, object] = {}
        if node is not None:
            self.allocatable = Resource.from_resource_list(node.status.allocatable)
            self.idle = self.allocatable.clone()
            self.capability = Resource.from_resource_list(node.status.capacity)
        else:
            self.idle = empty_resource()
            self.allocatable = empty_resource()
            self.capability = empty_resource()
        self.phase = NodePhase.NotReady
        self.reason = "UnInitialized"
        self._set_node_state(node, self.allocatable)

    # ---- state ----

    def _set_node_state(
        self, node: Optional[core.Node], allocatable: Optional[Resource] = None
    ) -> None:
        if node is None:
            self.phase, self.reason = NodePhase.NotReady, "UnInitialized"
            return
        if allocatable is None:
            allocatable = Resource.from_resource_list(node.status.allocatable)
        if not self.used.less_equal(allocatable):
            self.phase, self.reason = NodePhase.NotReady, "OutOfSync"
            return
        for cond in node.status.conditions:
            if cond.type == "Ready" and cond.status != "True":
                self.phase, self.reason = NodePhase.NotReady, "NotReady"
                return
        self.phase, self.reason = NodePhase.Ready, ""

    def ready(self) -> bool:
        return self.phase == NodePhase.Ready

    def set_node(self, node: core.Node) -> None:
        """Refresh from the API object, re-deriving Idle/Used from held tasks
        (node_info.go:158-190)."""
        allocatable = Resource.from_resource_list(node.status.allocatable)
        self._set_node_state(node, allocatable)
        if not self.ready():
            return
        self.node = node
        self.name = node.metadata.name
        self.allocatable = allocatable
        self.capability = Resource.from_resource_list(node.status.capacity)
        self.releasing = empty_resource()
        self.pipelined = empty_resource()
        self.idle = allocatable.clone()
        self.used = empty_resource()
        for task in self.tasks.values():
            if task.status == TaskStatus.Releasing:
                self.idle.sub(task.resreq)
                self.releasing.add(task.resreq)
                self.used.add(task.resreq)
            elif task.status == TaskStatus.Pipelined:
                self.pipelined.add(task.resreq)
            else:
                self.idle.sub(task.resreq)
                self.used.add(task.resreq)

    def future_idle(self) -> Resource:
        """Idle + Releasing − Pipelined (node_info.go:56-58)."""
        return self.idle.clone().add(self.releasing).sub_unchecked(self.pipelined)

    # ---- task accounting (node_info.go:205-275) ----

    def _allocate_idle(self, task: TaskInfo) -> None:
        if not task.resreq.less_equal(self.idle):
            self.phase, self.reason = NodePhase.NotReady, "OutOfSync"
            raise ValueError(f"Selected node {self.name} NotReady")
        self.idle.sub(task.resreq)

    def add_task(self, task: TaskInfo) -> None:
        key = task.uid
        if key in self.tasks:
            raise ValueError(f"task {task.namespace}/{task.name} already on node {self.name}")
        # Hold a copy so later status changes don't skew accounting.
        ti = task.clone()
        if self.node is not None:
            if ti.status == TaskStatus.Releasing:
                self._allocate_idle(ti)
                self.releasing.add(ti.resreq)
                self.used.add(ti.resreq)
            elif ti.status == TaskStatus.Pipelined:
                self.pipelined.add(ti.resreq)
            else:
                self._allocate_idle(ti)
                self.used.add(ti.resreq)
        self.tasks[key] = ti

    def remove_task(self, task: TaskInfo) -> None:
        stored = self.tasks.get(task.uid)
        if stored is None:
            raise KeyError(f"task {task.namespace}/{task.name} not on node {self.name}")
        if self.node is not None:
            if stored.status == TaskStatus.Releasing:
                self.releasing.sub_unchecked(stored.resreq)
                self.idle.add(stored.resreq)
                self.used.sub_unchecked(stored.resreq)
            elif stored.status == TaskStatus.Pipelined:
                self.pipelined.sub_unchecked(stored.resreq)
            else:
                self.idle.add(stored.resreq)
                self.used.sub_unchecked(stored.resreq)
        del self.tasks[task.uid]

    def update_task(self, task: TaskInfo) -> None:
        self.remove_task(task)
        self.add_task(task)

    def clone(self) -> "NodeInfo":
        # Field-level copy.  The reference clones by replay
        # (node_info.go: NewNodeInfo + AddTask per task), which re-parses
        # the node's quantity strings and re-runs per-task accounting —
        # ~150µs/node, the dominant cost of the session snapshot at 10k
        # nodes.  The copy keeps the incrementally-maintained accounting
        # exactly as the cache holds it (replay would also re-normalize
        # float op order; the cache's sequences are already the canonical
        # ones — see fast_apply's bit-identity contract).
        res = NodeInfo.__new__(NodeInfo)
        res.node = self.node
        res.name = self.name
        res.releasing = self.releasing.clone()
        res.pipelined = self.pipelined.clone()
        res.used = self.used.clone()
        res.idle = self.idle.clone()
        res.allocatable = self.allocatable.clone()
        res.capability = self.capability.clone()
        res.tasks = {uid: t.clone() for uid, t in self.tasks.items()}
        res.others = self.others
        res.phase = self.phase
        res.reason = self.reason
        return res

    @property
    def labels(self) -> Dict[str, str]:
        return self.node.metadata.labels if self.node else {}

    def __repr__(self) -> str:
        return f"Node ({self.name}): idle <{self.idle}>, used <{self.used}>"
