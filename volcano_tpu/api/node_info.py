"""NodeInfo — per-session resource accounting for one node.

Reference: pkg/scheduler/api/node_info.go.
"""

from __future__ import annotations

from typing import Dict, Optional

from volcano_tpu.api.resource import Resource, empty_resource
from volcano_tpu.api.types import NodePhase, TaskStatus
from volcano_tpu.api.job_info import TaskInfo
from volcano_tpu.apis import core


class NodeInfo:
    """Idle/Used/Releasing/Pipelined accounting (node_info.go:27-58)."""

    def __init__(self, node: Optional[core.Node] = None):
        self.node = node
        self.name = node.metadata.name if node else ""
        self.releasing = empty_resource()
        self.pipelined = empty_resource()
        self.used = empty_resource()
        self.tasks: Dict[str, TaskInfo] = {}
        self.others: Dict[str, object] = {}
        if node is not None:
            self.allocatable = Resource.from_resource_list(node.status.allocatable)
            self.idle = self.allocatable.clone()
            self.capability = Resource.from_resource_list(node.status.capacity)
        else:
            self.idle = empty_resource()
            self.allocatable = empty_resource()
            self.capability = empty_resource()
        self.phase = NodePhase.NotReady
        self.reason = "UnInitialized"
        self._set_node_state(node, self.allocatable)

    # ---- state ----

    def _set_node_state(
        self, node: Optional[core.Node], allocatable: Optional[Resource] = None
    ) -> None:
        if node is None:
            self.phase, self.reason = NodePhase.NotReady, "UnInitialized"
            return
        if allocatable is None:
            allocatable = Resource.from_resource_list(node.status.allocatable)
        if not self.used.less_equal(allocatable):
            self.phase, self.reason = NodePhase.NotReady, "OutOfSync"
            return
        for cond in node.status.conditions:
            if cond.type == "Ready" and cond.status != "True":
                self.phase, self.reason = NodePhase.NotReady, "NotReady"
                return
        self.phase, self.reason = NodePhase.Ready, ""

    def ready(self) -> bool:
        return self.phase == NodePhase.Ready

    def set_node(self, node: core.Node) -> None:
        """Refresh from the API object, re-deriving Idle/Used from held tasks
        (node_info.go:158-190)."""
        allocatable = Resource.from_resource_list(node.status.allocatable)
        self._set_node_state(node, allocatable)
        if not self.ready():
            return
        self.node = node
        self.name = node.metadata.name
        self.allocatable = allocatable
        self.capability = Resource.from_resource_list(node.status.capacity)
        self.releasing = empty_resource()
        self.pipelined = empty_resource()
        self.idle = allocatable.clone()
        self.used = empty_resource()
        for task in self.tasks.values():
            if task.status == TaskStatus.Releasing:
                self.idle.sub(task.resreq)
                self.releasing.add(task.resreq)
                self.used.add(task.resreq)
            elif task.status == TaskStatus.Pipelined:
                self.pipelined.add(task.resreq)
            else:
                self.idle.sub(task.resreq)
                self.used.add(task.resreq)

    def future_idle(self) -> Resource:
        """Idle + Releasing − Pipelined (node_info.go:56-58)."""
        return self.idle.clone().add(self.releasing).sub_unchecked(self.pipelined)

    # ---- task accounting (node_info.go:205-275) ----

    def _allocate_idle(self, task: TaskInfo) -> None:
        if not task.resreq.less_equal(self.idle):
            self.phase, self.reason = NodePhase.NotReady, "OutOfSync"
            raise ValueError(f"Selected node {self.name} NotReady")
        self.idle.sub(task.resreq)

    def add_task(self, task: TaskInfo) -> None:
        key = task.uid
        if key in self.tasks:
            raise ValueError(f"task {task.namespace}/{task.name} already on node {self.name}")
        # Hold a copy so later status changes don't skew accounting.
        ti = task.clone()
        if self.node is not None:
            if ti.status == TaskStatus.Releasing:
                self._allocate_idle(ti)
                self.releasing.add(ti.resreq)
                self.used.add(ti.resreq)
            elif ti.status == TaskStatus.Pipelined:
                self.pipelined.add(ti.resreq)
            else:
                self._allocate_idle(ti)
                self.used.add(ti.resreq)
        self.tasks[key] = ti

    def remove_task(self, task: TaskInfo) -> None:
        stored = self.tasks.get(task.uid)
        if stored is None:
            raise KeyError(f"task {task.namespace}/{task.name} not on node {self.name}")
        if self.node is not None:
            if stored.status == TaskStatus.Releasing:
                self.releasing.sub_unchecked(stored.resreq)
                self.idle.add(stored.resreq)
                self.used.sub_unchecked(stored.resreq)
            elif stored.status == TaskStatus.Pipelined:
                self.pipelined.sub_unchecked(stored.resreq)
            else:
                self.idle.add(stored.resreq)
                self.used.sub_unchecked(stored.resreq)
        del self.tasks[task.uid]

    def update_task(self, task: TaskInfo) -> None:
        self.remove_task(task)
        self.add_task(task)

    def clone(self) -> "NodeInfo":
        res = NodeInfo(self.node)
        for task in self.tasks.values():
            res.add_task(task)
        res.others = self.others
        return res

    @property
    def labels(self) -> Dict[str, str]:
        return self.node.metadata.labels if self.node else {}

    def __repr__(self) -> str:
        return f"Node ({self.name}): idle <{self.idle}>, used <{self.used}>"
