"""QueueInfo, NamespaceInfo and the namespace weight collection.

Reference: pkg/scheduler/api/queue_info.go and namespace_info.go.
"""

from __future__ import annotations

from typing import Dict, Optional

from volcano_tpu.apis import scheduling

DEFAULT_NAMESPACE_WEIGHT = 1
NAMESPACE_WEIGHT_KEY = "namespace.weight"


class QueueInfo:
    """Weighted queue (queue_info.go:29-66)."""

    def __init__(self, queue: scheduling.Queue):
        self.uid = queue.metadata.name
        self.name = queue.metadata.name
        self.weight = queue.spec.weight
        self.queue = queue

    def clone(self) -> "QueueInfo":
        return QueueInfo(self.queue)

    @property
    def creation_timestamp(self) -> float:
        return self.queue.metadata.creation_timestamp


class NamespaceInfo:
    """Namespace + scheduling weight (namespace_info.go:33-53)."""

    def __init__(self, name: str, weight: int = DEFAULT_NAMESPACE_WEIGHT):
        self.name = name
        self.weight = weight

    def get_weight(self) -> int:
        return self.weight if self.weight > 0 else DEFAULT_NAMESPACE_WEIGHT


class NamespaceCollection:
    """Derives a namespace's weight from its ResourceQuotas: the weight is
    the max over quotas of the ``namespace.weight`` hard limit, defaulting
    to 1 (namespace_info.go:74-141).  Modeled directly on weighted quota
    dicts: quota name → weight value.
    """

    def __init__(self, name: str):
        self.name = name
        self._quota_weights: Dict[str, int] = {}

    def update(self, quota_name: str, weight: Optional[int]) -> None:
        if weight is None:
            self._quota_weights.pop(quota_name, None)
        else:
            self._quota_weights[quota_name] = int(weight)

    def delete(self, quota_name: str) -> None:
        self._quota_weights.pop(quota_name, None)

    def snapshot(self) -> NamespaceInfo:
        weight = max(self._quota_weights.values(), default=DEFAULT_NAMESPACE_WEIGHT)
        return NamespaceInfo(self.name, max(weight, 0) or DEFAULT_NAMESPACE_WEIGHT)
