"""Resource vector arithmetic — the scheduler's unit of account.

Reference: pkg/scheduler/api/resource_info.go.  Host-side this is exact
float64 math identical to the reference; on device the same quantities are
packed as int32 lanes (cpu milli / memory bytes-quantized / scalar milli) by
volcano_tpu.ops.pack, where the tolerance thresholds below become integer
comparisons.
"""

from __future__ import annotations

from typing import Dict, Iterable, Optional

from volcano_tpu.apis import quantity

# Tolerance floors (resource_info.go:70-72): quantities below these are
# treated as zero by IsEmpty/IsZero and as equal by LessEqual.
MIN_MILLI_CPU = 10.0
MIN_MILLI_SCALAR = 10.0
MIN_MEMORY = 10.0 * 1024 * 1024

CPU = "cpu"
MEMORY = "memory"
PODS = "pods"


class Resource:
    """Dense resource vector: milli_cpu + memory + scalar map.

    ``max_task_num`` mirrors the reference's MaxTaskNum: carried for the
    pod-count predicate only, never part of arithmetic
    (resource_info.go:37-39).

    ``readonly`` is the freeze guard for shared-aliased instances
    (TaskInfo resreq/init_resreq are shared across clones, job_info.py):
    once :meth:`freeze` is called, the in-place mutators raise under
    ``__debug__`` so a violation of the documented immutability invariant
    fails loudly instead of silently skewing every snapshot sharing the
    object.  ``clone()`` always yields a mutable copy.
    """

    __slots__ = ("milli_cpu", "memory", "scalars", "max_task_num", "readonly")

    def __init__(
        self,
        milli_cpu: float = 0.0,
        memory: float = 0.0,
        scalars: Optional[Dict[str, float]] = None,
        max_task_num: int = 0,
    ):
        self.milli_cpu = float(milli_cpu)
        self.memory = float(memory)
        self.scalars: Dict[str, float] = dict(scalars) if scalars else {}
        self.max_task_num = max_task_num
        self.readonly = False

    def freeze(self) -> "Resource":
        """Mark shared-immutable; chainable."""
        self.readonly = True
        return self

    def _raise_frozen(self) -> None:
        raise AssertionError(
            "in-place mutation of a frozen (shared-aliased) Resource; "
            "clone() first or REPLACE the owning attribute"
        )

    # ---- constructors ----

    @classmethod
    def from_resource_list(cls, rl: Dict[str, object]) -> "Resource":
        """Build from a k8s ResourceList (resource_info.go:74-93).

        cpu → milli, memory → bytes, pods → max_task_num, scalars → milli.
        """
        r = cls()
        for name, q in (rl or {}).items():
            if name == CPU:
                r.milli_cpu += quantity.milli_value(q)
            elif name == MEMORY:
                r.memory += quantity.int_value(q)
            elif name == PODS:
                r.max_task_num += int(quantity.int_value(q))
            else:
                r.scalars[name] = r.scalars.get(name, 0.0) + quantity.milli_value(q)
        return r

    def clone(self) -> "Resource":
        # __new__ bypass: clone is on the per-task hot path (two clones
        # per placement via TaskInfo.clone) and __init__'s defensive
        # float()/dict() coercions double its cost on already-valid state.
        r = Resource.__new__(Resource)
        r.milli_cpu = self.milli_cpu
        r.memory = self.memory
        r.scalars = dict(self.scalars)
        r.max_task_num = self.max_task_num
        r.readonly = False  # a copy is always mutable
        return r

    # ---- predicates ----

    def is_empty(self) -> bool:
        """All dimensions below the tolerance floor (resource_info.go:96-108)."""
        if not (self.milli_cpu < MIN_MILLI_CPU and self.memory < MIN_MEMORY):
            return False
        return all(v < MIN_MILLI_SCALAR for v in self.scalars.values())

    def is_zero(self, name: str) -> bool:
        if name == CPU:
            return self.milli_cpu < MIN_MILLI_CPU
        if name == MEMORY:
            return self.memory < MIN_MEMORY
        if name not in self.scalars:
            return True
        return self.scalars[name] < MIN_MILLI_SCALAR

    # ---- arithmetic (mutating, chainable — mirrors the Go API) ----

    def add(self, rr: "Resource") -> "Resource":
        if __debug__ and self.readonly:
            self._raise_frozen()
        self.milli_cpu += rr.milli_cpu
        self.memory += rr.memory
        for name, v in rr.scalars.items():
            self.scalars[name] = self.scalars.get(name, 0.0) + v
        return self

    def sub(self, rr: "Resource") -> "Resource":
        """Subtract; asserts sufficiency like the reference
        (resource_info.go:146 via pkg/scheduler/util/assert — log and
        continue by default, fatal under VOLCANO_TPU_PANIC_ON_UNEXPECTED)."""
        from volcano_tpu.utils.asserts import assertf

        assertf(
            rr.less_equal(self),
            "resource is not sufficient to do operation: <%s> sub <%s>",
            self, rr,
        )
        return self.sub_unchecked(rr)

    def sub_unchecked(self, rr: "Resource") -> "Resource":
        """Subtract allowing negative lanes.

        The reference's Sub assert is env-gated and non-fatal by default
        (pkg/scheduler/util/assert); accounting paths (FutureIdle, node
        remove) rely on that leniency, so they use this variant.
        """
        if __debug__ and self.readonly:
            self._raise_frozen()
        self.milli_cpu -= rr.milli_cpu
        self.memory -= rr.memory
        for name, v in rr.scalars.items():
            self.scalars[name] = self.scalars.get(name, 0.0) - v
        return self

    def multi(self, ratio: float) -> "Resource":
        if __debug__ and self.readonly:
            self._raise_frozen()
        self.milli_cpu *= ratio
        self.memory *= ratio
        for name in self.scalars:
            self.scalars[name] *= ratio
        return self

    def set_max(self, rr: "Resource") -> "Resource":
        """Elementwise max in place (resource_info.go:162-187)."""
        if __debug__ and self.readonly:
            self._raise_frozen()
        self.milli_cpu = max(self.milli_cpu, rr.milli_cpu)
        self.memory = max(self.memory, rr.memory)
        for name, v in rr.scalars.items():
            self.scalars[name] = max(self.scalars.get(name, 0.0), v)
        return self

    def fit_delta(self, rr: "Resource") -> "Resource":
        """Available minus requested, with tolerance margins; negative lanes
        mark insufficient resources (resource_info.go:193-213)."""
        if __debug__ and self.readonly:
            self._raise_frozen()
        if rr.milli_cpu > 0:
            self.milli_cpu -= rr.milli_cpu + MIN_MILLI_CPU
        if rr.memory > 0:
            self.memory -= rr.memory + MIN_MEMORY
        for name, v in rr.scalars.items():
            if v > 0:
                self.scalars[name] = self.scalars.get(name, 0.0) - (v + MIN_MILLI_SCALAR)
        return self

    # ---- comparisons ----

    def less(self, rr: "Resource") -> bool:
        """Strictly less on every dimension (resource_info.go:226-264)."""
        if not self.milli_cpu < rr.milli_cpu:
            return False
        if not self.memory < rr.memory:
            return False
        if not self.scalars:
            # Without scalars on the left, right must have meaningful scalars.
            return all(v > MIN_MILLI_SCALAR for v in rr.scalars.values()) if rr.scalars else True
        if not rr.scalars:
            return False
        return all(v < rr.scalars.get(name, 0.0) for name, v in self.scalars.items())

    def less_equal(self, rr: "Resource") -> bool:
        """Less-or-within-tolerance on every dimension (resource_info.go:292-326)."""

        def le(l: float, r: float, diff: float) -> bool:
            return l < r or abs(l - r) < diff

        if not le(self.milli_cpu, rr.milli_cpu, MIN_MILLI_CPU):
            return False
        if not le(self.memory, rr.memory, MIN_MEMORY):
            return False
        for name, v in self.scalars.items():
            if v <= MIN_MILLI_SCALAR:
                continue
            if not le(v, rr.scalars.get(name, 0.0) if rr.scalars else 0.0, MIN_MILLI_SCALAR):
                return False
        return True

    def less_equal_strict(self, rr: "Resource") -> bool:
        """Exact <= on every dimension (resource_info.go:267-289)."""
        if self.milli_cpu > rr.milli_cpu or self.memory > rr.memory:
            return False
        return all(v <= rr.scalars.get(name, 0.0) for name, v in self.scalars.items())

    def diff(self, rr: "Resource"):
        """Return (increased, decreased) vs ``rr`` (resource_info.go:329-361)."""
        inc, dec = Resource(), Resource()
        if self.milli_cpu > rr.milli_cpu:
            inc.milli_cpu = self.milli_cpu - rr.milli_cpu
        else:
            dec.milli_cpu = rr.milli_cpu - self.milli_cpu
        if self.memory > rr.memory:
            inc.memory = self.memory - rr.memory
        else:
            dec.memory = rr.memory - self.memory
        for name, v in self.scalars.items():
            rv = rr.scalars.get(name, 0.0)
            if v > rv:
                inc.scalars[name] = v - rv
            else:
                dec.scalars[name] = rv - v
        return inc, dec

    # ---- access ----

    def get(self, name: str) -> float:
        if name == CPU:
            return self.milli_cpu
        if name == MEMORY:
            return self.memory
        return self.scalars.get(name, 0.0)

    def set_scalar(self, name: str, value: float) -> None:
        if __debug__ and self.readonly:
            self._raise_frozen()
        self.scalars[name] = value

    def resource_names(self) -> Iterable[str]:
        return [CPU, MEMORY, *self.scalars.keys()]

    # ---- misc ----

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, Resource)
            and self.milli_cpu == other.milli_cpu
            and self.memory == other.memory
            and {k: v for k, v in self.scalars.items() if v}
            == {k: v for k, v in other.scalars.items() if v}
        )

    def __repr__(self) -> str:
        s = f"cpu {self.milli_cpu:.2f}, memory {self.memory:.2f}"
        for name, v in self.scalars.items():
            s += f", {name} {v:.2f}"
        return s


def empty_resource() -> Resource:
    return Resource()


def min_resource(l: Resource, r: Resource) -> Resource:
    """Elementwise min (reference: pkg/scheduler/plugins/util helpers.Min)."""
    out = Resource(min(l.milli_cpu, r.milli_cpu), min(l.memory, r.memory))
    for name in set(l.scalars) | set(r.scalars):
        out.scalars[name] = min(l.scalars.get(name, 0.0), r.scalars.get(name, 0.0))
    return out


def share(l: float, r: float) -> float:
    """allocated/total with the reference's zero conventions
    (pkg/scheduler/plugins/util/helpers — Share)."""
    if r == 0:
        return 1.0 if l > 0 else 0.0
    return l / r
