"""Task/node status enums and callback result types.

Reference: pkg/scheduler/api/types.go.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass


class TaskStatus(enum.IntFlag):
    """Status of a task/pod in the scheduler (types.go:26-58)."""

    Pending = enum.auto()
    Allocated = enum.auto()
    Pipelined = enum.auto()
    Binding = enum.auto()
    Bound = enum.auto()
    Running = enum.auto()
    Releasing = enum.auto()
    Succeeded = enum.auto()
    Failed = enum.auto()
    Unknown = enum.auto()


#: Statuses whose resources are held on a node ("occupied").
#: Reference: types.go AllocatedStatus (Bound/Binding/Running/Allocated).
#: Frozenset membership instead of Flag arithmetic — enum ``__and__``
#: dominated the scheduler's hot comparator path (ready_task_num is
#: evaluated on every PriorityQueue compare).
ALLOCATED_STATUSES = frozenset(
    (TaskStatus.Bound, TaskStatus.Binding, TaskStatus.Running, TaskStatus.Allocated)
)


def allocated_status(status: TaskStatus) -> bool:
    return status in ALLOCATED_STATUSES


class NodePhase(enum.IntEnum):
    Ready = 1
    NotReady = 2


@dataclass
class ValidateResult:
    """Result of a JobValid callback (types.go ValidateResult)."""

    pass_: bool = True
    reason: str = ""
    message: str = ""
