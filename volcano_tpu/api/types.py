"""Task/node status enums and callback result types.

Reference: pkg/scheduler/api/types.go.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass


class TaskStatus(enum.IntFlag):
    """Status of a task/pod in the scheduler (types.go:26-58)."""

    Pending = enum.auto()
    Allocated = enum.auto()
    Pipelined = enum.auto()
    Binding = enum.auto()
    Bound = enum.auto()
    Running = enum.auto()
    Releasing = enum.auto()
    Succeeded = enum.auto()
    Failed = enum.auto()
    Unknown = enum.auto()


#: Statuses whose resources are held on a node ("occupied").
#: Reference: types.go AllocatedStatus (Bound/Binding/Running/Allocated).
_ALLOCATED = (
    TaskStatus.Bound | TaskStatus.Binding | TaskStatus.Running | TaskStatus.Allocated
)


def allocated_status(status: TaskStatus) -> bool:
    return bool(status & _ALLOCATED)


class NodePhase(enum.IntEnum):
    Ready = 1
    NotReady = 2


@dataclass
class ValidateResult:
    """Result of a JobValid callback (types.go ValidateResult)."""

    pass_: bool = True
    reason: str = ""
    message: str = ""
