"""Fit-error bookkeeping for unschedulable tasks.

Reference: pkg/scheduler/api/unschedule_info.go.
"""

from __future__ import annotations

from collections import Counter
from typing import Dict, List

# Well-known predicate failure reasons.
NODE_RESOURCE_FIT_FAILED = "node(s) resource fit failed"
NODE_POD_NUMBER_EXCEEDED = "node(s) pod number exceeded"
NODE_SELECTOR_MISMATCH = "node(s) didn't match node selector"
NODE_AFFINITY_MISMATCH = "node(s) didn't match node affinity"
NODE_TAINT_UNTOLERATED = "node(s) had taints that the pod didn't tolerate"
NODE_PORT_CONFLICT = "node(s) didn't have free ports for the requested pod ports"
NODE_UNSCHEDULABLE = "node(s) were unschedulable"
NODE_NOT_READY = "node(s) were not ready"
POD_AFFINITY_MISMATCH = "node(s) didn't match pod affinity/anti-affinity"


class FitError(Exception):
    """A task failed a predicate on one node."""

    def __init__(self, task, node, *reasons: str):
        self.task_name = getattr(task, "name", str(task))
        self.node_name = getattr(node, "name", str(node))
        self.reasons: List[str] = list(reasons)
        super().__init__(
            f"task {self.task_name} on node {self.node_name}: {', '.join(self.reasons)}"
        )


class FitErrors:
    """Aggregated per-node fit errors for one task (unschedule_info.go:22-110)."""

    def __init__(self):
        self.nodes: Dict[str, FitError] = {}
        self._message: str = ""

    def set_node_error(self, node_name: str, err: FitError) -> None:
        self.nodes[node_name] = err

    def set_error(self, message: str) -> None:
        self._message = message

    def error(self) -> str:
        if self._message:
            return self._message
        histogram: Counter = Counter()
        for err in self.nodes.values():
            for reason in err.reasons:
                histogram[reason] += 1
        parts = sorted(f"{count} {reason}" for reason, count in histogram.items())
        return f"0/{len(self.nodes)} nodes are available: {', '.join(parts)}."
