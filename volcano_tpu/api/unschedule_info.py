"""Fit-error bookkeeping for unschedulable tasks.

Reference: pkg/scheduler/api/unschedule_info.go.
"""

from __future__ import annotations

import re
from collections import Counter
from typing import Dict, List, Optional, Tuple

# Well-known predicate failure reasons.
NODE_RESOURCE_FIT_FAILED = "node(s) resource fit failed"
NODE_POD_NUMBER_EXCEEDED = "node(s) pod number exceeded"
NODE_SELECTOR_MISMATCH = "node(s) didn't match node selector"
NODE_AFFINITY_MISMATCH = "node(s) didn't match node affinity"
NODE_TAINT_UNTOLERATED = "node(s) had taints that the pod didn't tolerate"
NODE_PORT_CONFLICT = "node(s) didn't have free ports for the requested pod ports"
NODE_UNSCHEDULABLE = "node(s) were unschedulable"
NODE_NOT_READY = "node(s) were not ready"
POD_AFFINITY_MISMATCH = "node(s) didn't match pod affinity/anti-affinity"


class FitError(Exception):
    """A task failed a predicate on one node."""

    def __init__(self, task, node, *reasons: str):
        self.task_name = getattr(task, "name", str(task))
        self.node_name = getattr(node, "name", str(node))
        self.reasons: List[str] = list(reasons)
        super().__init__(
            f"task {self.task_name} on node {self.node_name}: {', '.join(self.reasons)}"
        )


def format_fit_errors(total_nodes: int, histogram: Dict[str, int]) -> str:
    """The reference's aggregate message (unschedule_info.go Error()):
    ``0/N nodes are available: <count> <reason>, ...`` with the parts
    lexicographically sorted.  The single copy of the format string —
    host-collected FitErrors and device-derived reason counts both
    render through it, which is what makes the two byte-comparable."""
    parts = sorted(f"{count} {reason}" for reason, count in histogram.items())
    return f"0/{total_nodes} nodes are available: {', '.join(parts)}."


_FIT_ERROR_RE = re.compile(r"^0/(\d+) nodes are available: (.*)\.$")


def parse_fit_errors(message: str) -> Optional[Tuple[int, Dict[str, int]]]:
    """Inverse of :func:`format_fit_errors` → (total_nodes, histogram),
    or None when the message is not an aggregate fit-error message
    (e.g. a gang job_fit_errors summary).  Consumed by ``vtctl
    describe``, which aggregates reason histograms back out of recorded
    Unschedulable events."""
    m = _FIT_ERROR_RE.match(message.strip())
    if m is None:
        return None
    histogram: Dict[str, int] = {}
    for part in m.group(2).split(", "):
        count, _, reason = part.partition(" ")
        if not count.isdigit() or not reason:
            return None
        histogram[reason] = histogram.get(reason, 0) + int(count)
    return int(m.group(1)), histogram


class FitErrors:
    """Aggregated per-node fit errors for one task (unschedule_info.go:22-110)."""

    def __init__(self):
        self.nodes: Dict[str, FitError] = {}
        self._message: str = ""
        #: device-derived reason histogram (ops/explain synthesis) —
        #: set instead of per-node FitError entries when the counts came
        #: off the accelerator and per-node attribution was not retained
        self._histogram: Optional[Dict[str, int]] = None
        self._total_nodes: int = 0

    def set_node_error(self, node_name: str, err: FitError) -> None:
        self.nodes[node_name] = err

    def set_error(self, message: str) -> None:
        self._message = message

    def set_histogram(self, total_nodes: int, histogram: Dict[str, int]) -> None:
        """Install an already-reduced reason histogram (the device
        explain path) in place of per-node errors."""
        self._histogram = dict(histogram)
        self._total_nodes = total_nodes

    def histogram(self) -> Dict[str, int]:
        """reason → node count, whichever way this FitErrors was built."""
        if self._histogram is not None:
            return dict(self._histogram)
        histogram: Counter = Counter()
        for err in self.nodes.values():
            for reason in err.reasons:
                histogram[reason] += 1
        return dict(histogram)

    def error(self) -> str:
        if self._message:
            return self._message
        total = (
            self._total_nodes if self._histogram is not None else len(self.nodes)
        )
        return format_fit_errors(total, self.histogram())
