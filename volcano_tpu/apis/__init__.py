"""API definitions (reference: pkg/apis/{batch,bus,scheduling} + core k8s types).

Self-contained typed object model: core Kubernetes objects (Pod, Node, ...),
the batch Job CRD with lifecycle policies, scheduling PodGroup/Queue, and the
bus Command channel.  Everything is a plain dataclass with ``to_dict`` /
``from_dict`` so objects round-trip through YAML/JSON for the CLI and the
in-memory API server.
"""
