"""Batch API: the Job CRD with lifecycle policies.

Reference: pkg/apis/batch/v1alpha1/job.go — JobSpec (tasks, policies,
plugins, queue, maxRetry, TTL), lifecycle Events/Actions, JobPhases and
JobStatus with phase counts + Version + RetryCount fencing.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from volcano_tpu.apis.core import K8sObject, PodTemplateSpec

# ---- Lifecycle events (job.go:124-144) ----
ANY_EVENT = "*"
POD_FAILED_EVENT = "PodFailed"
POD_EVICTED_EVENT = "PodEvicted"
JOB_UNKNOWN_EVENT = "Unknown"
TASK_COMPLETED_EVENT = "TaskCompleted"
OUT_OF_SYNC_EVENT = "OutOfSync"
COMMAND_ISSUED_EVENT = "CommandIssued"

VALID_EVENTS = {
    ANY_EVENT,
    POD_FAILED_EVENT,
    POD_EVICTED_EVENT,
    JOB_UNKNOWN_EVENT,
    TASK_COMPLETED_EVENT,
    OUT_OF_SYNC_EVENT,
    COMMAND_ISSUED_EVENT,
}

# ---- Lifecycle actions (job.go:149-172) ----
ABORT_JOB_ACTION = "AbortJob"
RESTART_JOB_ACTION = "RestartJob"
RESTART_TASK_ACTION = "RestartTask"
TERMINATE_JOB_ACTION = "TerminateJob"
COMPLETE_JOB_ACTION = "CompleteJob"
RESUME_JOB_ACTION = "ResumeJob"
SYNC_JOB_ACTION = "SyncJob"
ENQUEUE_JOB_ACTION = "EnqueueJob"

VALID_ACTIONS = {
    ABORT_JOB_ACTION,
    RESTART_JOB_ACTION,
    RESTART_TASK_ACTION,
    TERMINATE_JOB_ACTION,
    COMPLETE_JOB_ACTION,
    RESUME_JOB_ACTION,
}

# ---- Job phases (job.go:224-245) ----
JOB_PENDING = "Pending"
JOB_ABORTING = "Aborting"
JOB_ABORTED = "Aborted"
JOB_RUNNING = "Running"
JOB_RESTARTING = "Restarting"
JOB_COMPLETING = "Completing"
JOB_COMPLETED = "Completed"
JOB_TERMINATING = "Terminating"
JOB_TERMINATED = "Terminated"
JOB_FAILED = "Failed"

# Annotations stamped on every pod the job controller creates
# (reference: job_controller_util.go:102-105).
TASK_SPEC_KEY = "volcano-tpu.io/task-spec"
JOB_NAME_KEY = "volcano-tpu.io/job-name"
JOB_VERSION_KEY = "volcano-tpu.io/job-version"
DEFAULT_TASK_SPEC = "default"


@dataclass
class LifecyclePolicy:
    """Event/ExitCode → Action mapping (job.go:175-200)."""

    action: str = ""
    event: str = ""
    events: List[str] = field(default_factory=list)
    exit_code: Optional[int] = None
    timeout_seconds: Optional[float] = None

    def matches_event(self, event: str) -> bool:
        return (
            event == self.event
            or event in self.events
            or self.event == ANY_EVENT
            or ANY_EVENT in self.events
        )


@dataclass
class TaskSpec:
    name: str = ""
    replicas: int = 1
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)
    policies: List[LifecyclePolicy] = field(default_factory=list)


@dataclass
class VolumeSpec:
    mount_path: str = ""
    volume_claim_name: str = ""
    volume_claim: Dict[str, object] = field(default_factory=dict)


@dataclass
class JobSpec:
    scheduler_name: str = "volcano-tpu"
    min_available: int = 0
    volumes: List[VolumeSpec] = field(default_factory=list)
    tasks: List[TaskSpec] = field(default_factory=list)
    policies: List[LifecyclePolicy] = field(default_factory=list)
    # plugin name → arguments, e.g. {"ssh": [], "svc": [], "env": []}
    plugins: Dict[str, List[str]] = field(default_factory=dict)
    queue: str = "default"
    max_retry: int = 3
    ttl_seconds_after_finished: Optional[int] = None
    priority_class_name: str = ""


@dataclass
class JobCondition:
    status: str = ""
    last_transition_time: float = 0.0


@dataclass
class JobState:
    phase: str = JOB_PENDING
    reason: str = ""
    message: str = ""
    last_transition_time: float = 0.0


@dataclass
class JobStatus:
    state: JobState = field(default_factory=JobState)
    min_available: int = 0
    pending: int = 0
    running: int = 0
    succeeded: int = 0
    failed: int = 0
    terminating: int = 0
    unknown: int = 0
    version: int = 0
    retry_count: int = 0
    # kind/name of resources the controller created for the job
    # (services, configmaps, secrets) — job.go:303-306.
    controlled_resources: Dict[str, str] = field(default_factory=dict)


@dataclass
class Job(K8sObject):
    spec: JobSpec = field(default_factory=JobSpec)
    status: JobStatus = field(default_factory=JobStatus)
