"""Bus API: the Command CR — out-of-band operation channel.

Reference: pkg/apis/bus/v1alpha1/types.go:11-28.  A Command carries an
action aimed at a target object (Job or Queue); the owning controller
consumes and deletes it.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from volcano_tpu.apis.core import K8sObject, OwnerReference


@dataclass
class Command(K8sObject):
    action: str = ""
    target_object: OwnerReference = field(default_factory=OwnerReference)
    reason: str = ""
    message: str = ""
