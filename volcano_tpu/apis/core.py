"""Core Kubernetes-style objects used by the framework.

Only the fields the reference framework actually reads/writes are modeled
(e.g. Pod: requests/ports/selector/affinity/tolerations/priority; Node:
allocatable/capacity/taints/labels/conditions).  Affinity is kept as the
k8s dict schema and interpreted by the predicate/score layers.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from volcano_tpu.apis import serde


@dataclass
class OwnerReference:
    api_version: str = ""
    kind: str = ""
    name: str = ""
    uid: str = ""
    controller: bool = False
    block_owner_deletion: bool = False


@dataclass
class ObjectMeta:
    name: str = ""
    namespace: str = "default"
    uid: str = ""
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    creation_timestamp: float = 0.0
    resource_version: int = 0
    owner_references: List[OwnerReference] = field(default_factory=list)
    deletion_timestamp: Optional[float] = None

    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


@dataclass
class K8sObject:
    """Base for all API objects: kind + metadata + dict round-trip."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)

    @property
    def kind(self) -> str:
        return type(self).__name__

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace

    def key(self) -> str:
        return self.metadata.key()

    def to_dict(self) -> dict:
        out = serde.to_dict(self)
        out["kind"] = self.kind
        return out

    @classmethod
    def from_dict(cls, data: dict):
        data = {k: v for k, v in data.items() if k not in ("kind", "apiVersion")}
        return serde.from_dict(cls, data)

    def clone(self):
        # deepcopy, NOT a to_dict/from_dict round trip: the store clones
        # on every get/update/notify, and the serde walk's typing
        # dispatch made each clone ~10x a structural copy — at 50k-pod
        # commit batches the round trip WAS the relay floor.  Objects
        # built from the wire still normalize through from_dict; a clone
        # of a well-formed object is structurally identical either way.
        import copy

        return copy.deepcopy(self)


@dataclass
class Toleration:
    key: str = ""
    operator: str = "Equal"  # Equal | Exists
    value: str = ""
    effect: str = ""  # "" tolerates all effects
    toleration_seconds: Optional[int] = None


@dataclass
class Taint:
    key: str = ""
    value: str = ""
    effect: str = "NoSchedule"  # NoSchedule | PreferNoSchedule | NoExecute


@dataclass
class ContainerPort:
    container_port: int = 0
    host_port: int = 0
    protocol: str = "TCP"
    name: str = ""


@dataclass
class EnvVar:
    name: str = ""
    value: str = ""


@dataclass
class VolumeMount:
    name: str = ""
    mount_path: str = ""
    sub_path: str = ""
    read_only: bool = False


@dataclass
class Container:
    name: str = "main"
    image: str = ""
    command: List[str] = field(default_factory=list)
    args: List[str] = field(default_factory=list)
    # {"requests": {"cpu": "1", ...}, "limits": {...}}
    resources: Dict[str, Dict[str, object]] = field(default_factory=dict)
    ports: List[ContainerPort] = field(default_factory=list)
    env: List[EnvVar] = field(default_factory=list)
    volume_mounts: List[VolumeMount] = field(default_factory=list)
    working_dir: str = ""


@dataclass
class Volume:
    name: str = ""
    # one of: {"persistentVolumeClaim": {"claimName": ...}}, {"configMap": ...},
    # {"secret": {"secretName": ...}}, {"emptyDir": {}} — kept schemaless.
    source: Dict[str, object] = field(default_factory=dict)


@dataclass
class PodSpec:
    containers: List[Container] = field(default_factory=list)
    init_containers: List[Container] = field(default_factory=list)
    node_name: str = ""
    node_selector: Dict[str, str] = field(default_factory=dict)
    # k8s affinity schema: nodeAffinity / podAffinity / podAntiAffinity dicts.
    affinity: Dict[str, object] = field(default_factory=dict)
    tolerations: List[Toleration] = field(default_factory=list)
    scheduler_name: str = ""
    priority: Optional[int] = None
    priority_class_name: str = ""
    restart_policy: str = "OnFailure"
    hostname: str = ""
    subdomain: str = ""
    service_account_name: str = ""
    volumes: List[Volume] = field(default_factory=list)


@dataclass
class PodCondition:
    type: str = ""
    status: str = ""
    reason: str = ""
    message: str = ""


@dataclass
class PodStatus:
    phase: str = "Pending"  # Pending|Running|Succeeded|Failed|Unknown
    reason: str = ""
    message: str = ""
    conditions: List[PodCondition] = field(default_factory=list)
    # exit code of first failed container, surfaced for lifecycle policies.
    exit_code: Optional[int] = None


@dataclass
class Pod(K8sObject):
    spec: PodSpec = field(default_factory=PodSpec)
    status: PodStatus = field(default_factory=PodStatus)


@dataclass
class PodTemplateSpec:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSpec = field(default_factory=PodSpec)


@dataclass
class NodeCondition:
    type: str = "Ready"
    status: str = "True"
    reason: str = ""


@dataclass
class NodeSpec:
    taints: List[Taint] = field(default_factory=list)
    unschedulable: bool = False


@dataclass
class NodeStatus:
    allocatable: Dict[str, object] = field(default_factory=dict)
    capacity: Dict[str, object] = field(default_factory=dict)
    conditions: List[NodeCondition] = field(default_factory=lambda: [NodeCondition()])


@dataclass
class Node(K8sObject):
    spec: NodeSpec = field(default_factory=NodeSpec)
    status: NodeStatus = field(default_factory=NodeStatus)


@dataclass
class PriorityClass(K8sObject):
    value: int = 0
    global_default: bool = False


@dataclass
class ConfigMap(K8sObject):
    data: Dict[str, str] = field(default_factory=dict)


@dataclass
class Secret(K8sObject):
    data: Dict[str, str] = field(default_factory=dict)
    type: str = "Opaque"


@dataclass
class ServicePort:
    name: str = ""
    port: int = 0
    protocol: str = "TCP"


@dataclass
class ServiceSpec:
    selector: Dict[str, str] = field(default_factory=dict)
    cluster_ip: str = ""
    ports: List[ServicePort] = field(default_factory=list)


@dataclass
class Service(K8sObject):
    spec: ServiceSpec = field(default_factory=ServiceSpec)


@dataclass
class PersistentVolumeClaim(K8sObject):
    spec: Dict[str, object] = field(default_factory=dict)
    status: Dict[str, object] = field(default_factory=dict)


@dataclass
class NetworkPolicy(K8sObject):
    spec: Dict[str, object] = field(default_factory=dict)


@dataclass
class Event(K8sObject):
    """Kubernetes Event — the user-facing audit trail.  ``count``
    aggregates repeats of the same (object, type, reason) — the message
    is deliberately NOT part of the aggregation key, matching the k8s
    correlator, so variable-detail repeats collapse into one Event
    (whose message refreshes to the latest occurrence)."""

    involved_object: Dict[str, str] = field(default_factory=dict)
    type: str = "Normal"
    reason: str = ""
    message: str = ""
    count: int = 1
