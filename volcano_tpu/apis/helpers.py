"""Shared API helpers (reference: pkg/apis/helpers/helpers.go)."""

from __future__ import annotations

from volcano_tpu.apis.core import K8sObject, OwnerReference


def owner_reference(obj: K8sObject, controller: bool = True) -> OwnerReference:
    """Build an OwnerReference to ``obj`` (helpers.go CreatedBy* helpers)."""
    return OwnerReference(
        api_version="volcano-tpu.io/v1",
        kind=obj.kind,
        name=obj.metadata.name,
        uid=obj.metadata.uid,
        controller=controller,
        block_owner_deletion=True,
    )


def is_controlled_by(obj: K8sObject, owner: K8sObject) -> bool:
    for ref in obj.metadata.owner_references:
        if ref.controller and ref.uid == owner.metadata.uid:
            return True
    return False


def generate_podgroup_name(pod_or_job: K8sObject) -> str:
    """PodGroup name derived from its owning object (helpers.go)."""
    return f"podgroup-{pod_or_job.metadata.uid or pod_or_job.metadata.name}"
