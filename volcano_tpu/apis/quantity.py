"""Kubernetes resource-quantity parsing.

Mirrors the subset of k8s.io/apimachinery resource.Quantity semantics the
reference scheduler relies on (reference: pkg/scheduler/api/resource_info.go
NewResource — MilliValue for cpu/scalars, Value for memory/pods).

Quantities are decimal strings; ``milli_value``/``int_value`` must be exact
like Go's infinite-precision Quantity math, so they scale with Fraction
rather than float multiplication (float 13*1e-3 = 0.013000000000000001,
which a naive ceil would inflate to 14m).
"""

from __future__ import annotations

import functools
import math
from fractions import Fraction

_BINARY_SUFFIXES = {
    "Ki": 1024,
    "Mi": 1024**2,
    "Gi": 1024**3,
    "Ti": 1024**4,
    "Pi": 1024**5,
    "Ei": 1024**6,
}
_DECIMAL_SUFFIXES = {
    "n": Fraction(1, 10**9),
    "u": Fraction(1, 10**6),
    "m": Fraction(1, 10**3),
    "k": Fraction(10**3),
    "M": Fraction(10**6),
    "G": Fraction(10**9),
    "T": Fraction(10**12),
    "P": Fraction(10**15),
    "E": Fraction(10**18),
}


def _parse_exact(value) -> Fraction:
    """Parse a k8s quantity ("100m", "1Gi", 2, "1.5") exactly."""
    if isinstance(value, int):
        return Fraction(value)
    if isinstance(value, float):
        return Fraction(str(value))
    s = str(value).strip()
    if not s:
        return Fraction(0)
    for suffix, mult in _BINARY_SUFFIXES.items():
        if s.endswith(suffix):
            return Fraction(s[: -len(suffix)]) * mult
    if s[-1] in _DECIMAL_SUFFIXES and not s[-1].isdigit():
        return Fraction(s[:-1]) * _DECIMAL_SUFFIXES[s[-1]]
    # Scientific notation ("1e3") and plain decimals both land here.
    if "e" in s or "E" in s:
        mantissa, _, exp = s.partition("e" if "e" in s else "E")
        return Fraction(mantissa) * Fraction(10) ** int(exp)
    return Fraction(s)


# Quantity inputs are immutable scalars (str/int/float) drawn from a
# small vocabulary in practice ("250m", "1Gi", ... repeated across every
# pod of a template), and Fraction arithmetic is the single hottest part
# of feeding 50k pods into the cache — cache the exact results.
@functools.lru_cache(maxsize=4096)
def parse_quantity(value) -> float:
    """Parse a k8s quantity to a float base value."""
    return float(_parse_exact(value))


@functools.lru_cache(maxsize=4096)
def milli_value(value) -> float:
    """Quantity → milli units, rounded up (resource.Quantity.MilliValue)."""
    return float(math.ceil(_parse_exact(value) * 1000))


@functools.lru_cache(maxsize=4096)
def int_value(value) -> float:
    """Quantity → integer base value, rounded up (resource.Quantity.Value)."""
    return float(math.ceil(_parse_exact(value)))


def format_quantity(value: float) -> str:
    """Best-effort human formatting for ints/floats (used by CLI output)."""
    if value == int(value):
        return str(int(value))
    return f"{value:g}"
