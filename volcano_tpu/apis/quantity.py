"""Kubernetes resource-quantity parsing.

Mirrors the subset of k8s.io/apimachinery resource.Quantity semantics the
reference scheduler relies on (reference: pkg/scheduler/api/resource_info.go
NewResource — MilliValue for cpu/scalars, Value for memory/pods).
"""

from __future__ import annotations

import math

_BINARY_SUFFIXES = {
    "Ki": 1024,
    "Mi": 1024**2,
    "Gi": 1024**3,
    "Ti": 1024**4,
    "Pi": 1024**5,
    "Ei": 1024**6,
}
_DECIMAL_SUFFIXES = {
    "n": 1e-9,
    "u": 1e-6,
    "m": 1e-3,
    "k": 1e3,
    "M": 1e6,
    "G": 1e9,
    "T": 1e12,
    "P": 1e15,
    "E": 1e18,
}


def parse_quantity(value) -> float:
    """Parse a k8s quantity ("100m", "1Gi", 2, "1.5") to a float base value."""
    if isinstance(value, (int, float)):
        return float(value)
    s = str(value).strip()
    if not s:
        return 0.0
    for suffix, mult in _BINARY_SUFFIXES.items():
        if s.endswith(suffix):
            return float(s[: -len(suffix)]) * mult
    if s[-1] in _DECIMAL_SUFFIXES and not s[-1].isdigit():
        return float(s[:-1]) * _DECIMAL_SUFFIXES[s[-1]]
    return float(s)


def milli_value(value) -> float:
    """Quantity → milli units, rounded up (resource.Quantity.MilliValue)."""
    return float(math.ceil(parse_quantity(value) * 1000))


def int_value(value) -> float:
    """Quantity → integer base value, rounded up (resource.Quantity.Value)."""
    return float(math.ceil(parse_quantity(value)))


def format_quantity(value: float) -> str:
    """Best-effort human formatting for ints/floats (used by CLI output)."""
    if value == int(value):
        return str(int(value))
    return f"{value:g}"
