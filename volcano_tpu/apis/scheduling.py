"""Scheduling API: PodGroup and Queue.

Reference: pkg/apis/scheduling/v1alpha2/types.go (single hub version here —
the reference's v1alpha1/v1alpha2 dual-version plumbing is a Kubernetes
migration artifact with no behavioral content).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List

from volcano_tpu.apis.core import K8sObject

# PodGroup phases (types.go:42-57)
POD_GROUP_PENDING = "Pending"
POD_GROUP_RUNNING = "Running"
POD_GROUP_UNKNOWN = "Unknown"
POD_GROUP_INQUEUE = "Inqueue"

# PodGroup condition types / reasons (types.go:61-113)
POD_GROUP_UNSCHEDULABLE_TYPE = "Unschedulable"
POD_GROUP_SCHEDULED_TYPE = "Scheduled"
NOT_ENOUGH_RESOURCES_REASON = "NotEnoughResources"
NOT_ENOUGH_PODS_REASON = "NotEnoughTasks"

# Queue states (types.go:30-39)
QUEUE_STATE_OPEN = "Open"
QUEUE_STATE_CLOSED = "Closed"
QUEUE_STATE_CLOSING = "Closing"
QUEUE_STATE_UNKNOWN = "Unknown"

# Annotation linking a Pod to its PodGroup (v1alpha2 GroupNameAnnotationKey).
GROUP_NAME_ANNOTATION_KEY = "scheduling.volcano-tpu.io/group-name"


@dataclass
class PodGroupCondition:
    type: str = ""
    status: str = ""
    transition_id: str = ""
    last_transition_time: float = 0.0
    reason: str = ""
    message: str = ""


@dataclass
class PodGroupSpec:
    min_member: int = 0
    queue: str = "default"
    priority_class_name: str = ""
    # Aggregate resource floor for minMember tasks; gate for enqueue.
    min_resources: Dict[str, object] = field(default_factory=dict)


@dataclass
class PodGroupStatus:
    phase: str = POD_GROUP_PENDING
    conditions: List[PodGroupCondition] = field(default_factory=list)
    running: int = 0
    succeeded: int = 0
    failed: int = 0


@dataclass
class PodGroup(K8sObject):
    spec: PodGroupSpec = field(default_factory=PodGroupSpec)
    status: PodGroupStatus = field(default_factory=PodGroupStatus)


@dataclass
class QueueSpec:
    weight: int = 1
    capability: Dict[str, object] = field(default_factory=dict)
    state: str = QUEUE_STATE_OPEN


@dataclass
class QueueStatus:
    state: str = ""
    unknown: int = 0
    pending: int = 0
    running: int = 0
    inqueue: int = 0


@dataclass
class Queue(K8sObject):
    spec: QueueSpec = field(default_factory=QueueSpec)
    status: QueueStatus = field(default_factory=QueueStatus)
