"""Dual-version scheduling API: v1alpha1/v1alpha2 shims + conversion.

Reference: pkg/apis/scheduling/{v1alpha1,v1alpha2}/types.go with the hub
conversion scheme in pkg/apis/scheduling/scheme/scheme.go, consumed by
the cache's dual informer set (pkg/scheduler/cache/cache.go:393-424 —
AddPodGroupV1alpha1/V1alpha2, AddQueueV1alpha1/V1alpha2).

The hub (volcano_tpu/apis/scheduling.py) matches v1alpha2's shape; the
versioned types differ exactly where the reference's do:

  * v1alpha1 Queue has NO spec.state and NO status {state, inqueue}
    (QueueState/Inqueue were added in v1alpha2);
  * PodGroup is field-identical across versions (the v1alpha2 file only
    adds queue event/action enums, not PodGroup fields).

Conversion therefore defaults a v1alpha1 queue's state to Open on the
way in and drops state/inqueue on the way out — byte-faithful to what
scheme.Convert does through the hub types.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict

from volcano_tpu.apis import scheduling
from volcano_tpu.apis.core import K8sObject


# ---- v1alpha1 types (pkg/apis/scheduling/v1alpha1/types.go) ----


@dataclass
class PodGroupV1alpha1(K8sObject):
    """Field-identical to the hub PodGroup; distinct type = distinct
    apiVersion on the wire."""

    spec: scheduling.PodGroupSpec = field(default_factory=scheduling.PodGroupSpec)
    status: scheduling.PodGroupStatus = field(
        default_factory=scheduling.PodGroupStatus
    )


@dataclass
class QueueSpecV1alpha1:
    weight: int = 1
    capability: Dict[str, object] = field(default_factory=dict)
    # no `state` — QueueState is v1alpha2-only


@dataclass
class QueueStatusV1alpha1:
    unknown: int = 0
    pending: int = 0
    running: int = 0
    # no `state` / `inqueue` — v1alpha2-only


@dataclass
class QueueV1alpha1(K8sObject):
    spec: QueueSpecV1alpha1 = field(default_factory=QueueSpecV1alpha1)
    status: QueueStatusV1alpha1 = field(default_factory=QueueStatusV1alpha1)


# v1alpha2 is the hub shape — aliases make the version explicit at call
# sites (the reference's v1alpha2 structs are what the hub mirrors).
PodGroupV1alpha2 = scheduling.PodGroup
QueueV1alpha2 = scheduling.Queue


# ---- conversions (scheme.go Convert through the hub) ----


def pod_group_v1alpha1_to_hub(pg: PodGroupV1alpha1) -> scheduling.PodGroup:
    # scheme.Convert deep-copies: the hub object must not alias the
    # versioned input (cache state would otherwise mutate silently when
    # the caller keeps writing to its object).
    src = pg.clone()
    return scheduling.PodGroup(metadata=src.metadata, spec=src.spec, status=src.status)


def pod_group_hub_to_v1alpha1(pg: scheduling.PodGroup) -> PodGroupV1alpha1:
    src = pg.clone()
    return PodGroupV1alpha1(metadata=src.metadata, spec=src.spec, status=src.status)


def queue_v1alpha1_to_hub(q: QueueV1alpha1) -> scheduling.Queue:
    q = q.clone()
    return scheduling.Queue(
        metadata=q.metadata,
        spec=scheduling.QueueSpec(
            weight=q.spec.weight,
            capability=dict(q.spec.capability),
            state=scheduling.QUEUE_STATE_OPEN,  # defaulted on conversion
        ),
        status=scheduling.QueueStatus(
            unknown=q.status.unknown,
            pending=q.status.pending,
            running=q.status.running,
        ),
    )


def queue_hub_to_v1alpha1(q: scheduling.Queue) -> QueueV1alpha1:
    q = q.clone()
    return QueueV1alpha1(
        metadata=q.metadata,
        spec=QueueSpecV1alpha1(
            weight=q.spec.weight, capability=dict(q.spec.capability)
        ),
        status=QueueStatusV1alpha1(
            unknown=q.status.unknown,
            pending=q.status.pending,
            running=q.status.running,
        ),
    )
