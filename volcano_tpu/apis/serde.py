"""Generic dataclass ↔ dict (de)serialization with k8s-style camelCase keys.

All API objects round-trip through plain dicts so the CLI can read/write YAML
and the in-memory API server can deep-copy objects cheaply.
"""

from __future__ import annotations

import copy
import dataclasses
import re
import typing

_CAMEL_RE = re.compile(r"(?<!^)(?=[A-Z])")


def snake(name: str) -> str:
    return _CAMEL_RE.sub("_", name).lower()


def camel(name: str) -> str:
    head, *tail = name.split("_")
    return head + "".join(p.capitalize() for p in tail)


def _unwrap_optional(tp):
    if typing.get_origin(tp) is typing.Union:
        args = [a for a in typing.get_args(tp) if a is not type(None)]
        if len(args) == 1:
            return args[0]
    return tp


def _from_value(tp, value):
    tp = _unwrap_optional(tp)
    origin = typing.get_origin(tp)
    if value is None:
        return None
    if dataclasses.is_dataclass(tp):
        return from_dict(tp, value)
    if origin in (list, typing.List):
        (elem,) = typing.get_args(tp)
        return [_from_value(elem, v) for v in value]
    if origin in (dict, typing.Dict):
        _, val_t = typing.get_args(tp)
        return {k: _from_value(val_t, v) for k, v in value.items()}
    return copy.deepcopy(value)


def from_dict(cls, data):
    """Build dataclass ``cls`` from a dict with camelCase or snake_case keys."""
    if data is None:
        return None
    if dataclasses.is_dataclass(data.__class__):
        return copy.deepcopy(data)
    hints = typing.get_type_hints(cls)
    names = {f.name for f in dataclasses.fields(cls)}
    kwargs = {}
    for key, value in data.items():
        name = key if key in names else snake(key)
        if name not in names:
            continue
        kwargs[name] = _from_value(hints[name], value)
    return cls(**kwargs)


def _to_value(value, drop_empty: bool):
    if dataclasses.is_dataclass(value.__class__) and not isinstance(value, type):
        return to_dict(value, drop_empty=drop_empty)
    if isinstance(value, list):
        return [_to_value(v, drop_empty) for v in value]
    if isinstance(value, dict):
        return {k: _to_value(v, drop_empty) for k, v in value.items()}
    return copy.deepcopy(value)


def to_dict(obj, drop_empty: bool = True) -> dict:
    """Dataclass → dict with camelCase keys; empty/None fields dropped."""
    out = {}
    for f in dataclasses.fields(obj):
        value = getattr(obj, f.name)
        if drop_empty and (value is None or value == [] or value == {}):
            continue
        out[camel(f.name)] = _to_value(value, drop_empty)
    return out
