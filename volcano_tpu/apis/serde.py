"""Generic dataclass ↔ dict (de)serialization with k8s-style camelCase keys.

All API objects round-trip through plain dicts so the CLI can read/write YAML
and the in-memory API server can deep-copy objects cheaply.
"""

from __future__ import annotations

import copy
import dataclasses
import functools
import re
import typing

_CAMEL_RE = re.compile(r"(?<!^)(?=[A-Z])")


@functools.lru_cache(maxsize=4096)
def snake(name: str) -> str:
    return _CAMEL_RE.sub("_", name).lower()


@functools.lru_cache(maxsize=4096)
def camel(name: str) -> str:
    head, *tail = name.split("_")
    return head + "".join(p.capitalize() for p in tail)


#: cls → (resolved type hints, field-name set).  ``get_type_hints``
#: re-compiles every PEP-563 string annotation on every call — at one
#: call per from_dict it dominated the whole store (every clone(),
#: every bus frame, every commit) with ~0.8 ms of typing machinery per
#: object; the hints are immutable per class, so resolve once.
_CLASS_INFO: dict = {}


def _class_info(cls):
    cached = _CLASS_INFO.get(cls)
    if cached is None:
        hints = typing.get_type_hints(cls)
        names = frozenset(f.name for f in dataclasses.fields(cls))
        cached = (hints, names)
        _CLASS_INFO[cls] = cached
    return cached


def _unwrap_optional(tp):
    if typing.get_origin(tp) is typing.Union:
        args = [a for a in typing.get_args(tp) if a is not type(None)]
        if len(args) == 1:
            return args[0]
    return tp


def _from_value(tp, value):
    tp = _unwrap_optional(tp)
    origin = typing.get_origin(tp)
    if value is None:
        return None
    if dataclasses.is_dataclass(tp):
        return from_dict(tp, value)
    if origin in (list, typing.List):
        (elem,) = typing.get_args(tp)
        return [_from_value(elem, v) for v in value]
    if origin in (dict, typing.Dict):
        _, val_t = typing.get_args(tp)
        return {k: _from_value(val_t, v) for k, v in value.items()}
    return copy.deepcopy(value)


def from_dict(cls, data):
    """Build dataclass ``cls`` from a dict with camelCase or snake_case keys."""
    if data is None:
        return None
    if dataclasses.is_dataclass(data.__class__):
        return copy.deepcopy(data)
    hints, names = _class_info(cls)
    kwargs = {}
    for key, value in data.items():
        name = key if key in names else snake(key)
        if name not in names:
            continue
        kwargs[name] = _from_value(hints[name], value)
    return cls(**kwargs)


def _to_value(value, drop_empty: bool):
    if dataclasses.is_dataclass(value.__class__) and not isinstance(value, type):
        return to_dict(value, drop_empty=drop_empty)
    if isinstance(value, list):
        return [_to_value(v, drop_empty) for v in value]
    if isinstance(value, dict):
        return {k: _to_value(v, drop_empty) for k, v in value.items()}
    return copy.deepcopy(value)


#: cls → ((field name, camelCase name), ...) — ``dataclasses.fields``
#: plus the camel conversion per call showed up on the bus fan-out
#: profile (every watch notify encodes old+new); both are immutable
#: per class.
_FIELD_NAMES: dict = {}


def _field_names(cls):
    cached = _FIELD_NAMES.get(cls)
    if cached is None:
        cached = tuple(
            (f.name, camel(f.name)) for f in dataclasses.fields(cls)
        )
        _FIELD_NAMES[cls] = cached
    return cached


def to_dict(obj, drop_empty: bool = True) -> dict:
    """Dataclass → dict with camelCase keys; empty/None fields dropped."""
    out = {}
    for name, camel_name in _field_names(obj.__class__):
        value = getattr(obj, name)
        if drop_empty and (value is None or value == [] or value == {}):
            continue
        out[camel_name] = _to_value(value, drop_empty)
    return out
