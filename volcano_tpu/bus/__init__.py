"""Out-of-process API-server bus.

The reference system is three independently deployed binaries plus a
CLI meeting at a network API server; this package is that meeting
point for the standalone build:

* ``BusServer`` serves an in-process ``APIServer`` store over TCP with
  CRUD, list, watch streams (resume, bookmarks, 410-Gone relist), and
  remote admission review.
* ``RemoteAPIServer`` is the drop-in client: the same interface as the
  in-process store, plus reconnect/backoff and informer-grade watch
  resync.
* ``connect_bus`` resolves a ``--bus tcp://host:port`` flag into a
  backend: remote when given, fresh in-process store otherwise.

Run the daemon with ``python -m volcano_tpu.cmd.apiserver``.
"""

from volcano_tpu.bus.protocol import (
    BusError,
    BusTimeoutError,
    parse_bus_endpoints,
    parse_bus_url,
)
from volcano_tpu.bus.remote import RemoteAPIServer
from volcano_tpu.bus.server import BusServer
from volcano_tpu.bus.wal import PersistentAPIServer


def connect_bus(bus: str = "", timeout: float = 10.0, wait: float = 30.0):
    """``--bus`` flag resolution shared by every binary (daemon mains,
    vtctl, local_up): an address returns a ``RemoteAPIServer`` that is
    already reachable — or raises ``BusError`` after ``wait`` seconds,
    so misconfiguration fails loudly at startup instead of as an
    endless reconnect loop behind a green healthz.  The address may be
    a comma-separated endpoint list (``tcp://a,tcp://b`` — replicated
    apiservers); the client dials across the list and fails over on
    replica death.  Empty returns a standalone in-process
    ``APIServer``."""
    if bus:
        api = RemoteAPIServer(bus, timeout=timeout)
        if not api.wait_ready(wait):
            api.close()
            raise BusError(f"bus {bus} unreachable after {wait:.0f}s")
        return api
    from volcano_tpu.client.apiserver import APIServer

    return APIServer()


__all__ = [
    "BusError",
    "BusServer",
    "BusTimeoutError",
    "PersistentAPIServer",
    "RemoteAPIServer",
    "connect_bus",
    "parse_bus_endpoints",
    "parse_bus_url",
]
