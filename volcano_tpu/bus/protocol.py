"""Bus wire protocol: framing, object serde, and error mapping.

The out-of-process bus generalizes the compute-plane boundary
(serving/compute_plane.py) from "ship one packed snapshot" to "be the
API server": the same length-prefixed, versioned frame discipline, but
carrying JSON-encoded API objects and watch streams over TCP.

Frame layout (little-endian):

    ``VBUS`` magic + u16 version + u16 message type + u32 correlation id
    + u32 payload length, then a JSON payload.

The correlation id demultiplexes one duplex connection: for T_REQ /
T_RESP / T_ERROR it is the client-assigned request id; for watch frames
(T_WATCH_EVENT / T_BOOKMARK) it is the client-assigned watch id; for
admission review frames (T_ADMIT_REQ / T_ADMIT_RESP) it is the
server-assigned review id.  Payloads are pure JSON — like the compute
plane, the wire is free of pickle so an untrusted peer cannot execute
code.

Objects cross the wire via the same dataclass round-trip the in-process
store uses for ``clone()`` (apis/serde), so a remote create/get is
byte-equivalent to an in-process one.  ``KINDS`` is the decode registry:
every K8sObject kind the store can hold.
"""

from __future__ import annotations

import json
import socket
import struct
from typing import Dict, List, Optional, Tuple

try:  # optional: binary framing needs msgpack; JSON framing never does
    import msgpack  # type: ignore[import-not-found]
except ImportError:  # pragma: no cover - environment without msgpack
    msgpack = None

#: whether this build can speak the v8 binary codec at all.  When
#: msgpack is absent the hello negotiation simply never offers binary,
#: so every connection (and the WAL) stays JSON — no feature flag, no
#: error path.
HAS_BINARY = msgpack is not None

from volcano_tpu.apis import batch, core, scheduling, scheme
from volcano_tpu.apis import bus as apis_bus
from volcano_tpu.client.apiserver import (
    AdmissionError,
    AlreadyExistsError,
    ApiError,
    ConflictError,
    NotFoundError,
)

MAGIC = b"VBUS"
#: v2 adds the coalesced ``commit_batch`` request op (one frame carrying
#: N binds + evictions + audit events + status writebacks, applied as a
#: single store transaction).  v3 adds the ``watch_batch`` op: a watch
#: established through it may receive coalesced ``T_WATCH_BATCH``
#: frames (N watch events in one frame, batched on the server's writer
#: thread) instead of one ``T_WATCH_EVENT`` frame per object — the
#: README known-gap on watch fan-out under commit_batch bursts.  v4
#: adds the ``cas_bind`` op: one optimistic-concurrency binding write
#: (bind iff the pod is still unbound and its resourceVersion matches)
#: — the federation spillover primitive, one round trip instead of a
#: get + CAS update.  v5 adds the replicated-bus surface: ``bus_status``
#: (role / leader / term / WAL + replication introspection — the
#: ``vtctl bus status`` op) and the leader/follower log-shipping ops
#: ``repl_append`` / ``repl_snapshot`` / ``repl_commit``
#: (bus/replication.py).  v6 adds ``txn_commit``: an atomic
#: multi-object transaction — N ``cas_bind``s checked and applied
#: all-or-nothing under one store lock hold, logged as ONE WAL record
#: and replicated as a unit — the cross-shard gang-assembly primitive
#: (federation/broker.py).  The frame LAYOUT is unchanged throughout,
#: so frames are STAMPED with MIN_VERSION — a v1 peer accepts every
#: frame at the framing layer, and a newer client talking to an older
#: server detects the unknown op from the typed error and falls back
#: (per-object binds for ``commit_batch``; a plain ``watch`` for
#: ``watch_batch``; get + CAS ``update`` for ``cas_bind``; a degraded
#: ``role: unknown`` payload for ``bus_status`` — bus/remote.py.  An
#: old peer cannot be a replica at all, so the repl ops have no
#: fallback to degrade to: a replica group must be version-homogeneous
#: and a follower simply logs and retries against an old leader.
#: ``txn_commit``'s fallback is an ABORT, never a per-object replay: a
#: v5 peer cannot apply half a gang atomically, so the client reports
#: the whole transaction unsupported and the gang broker stays in the
#: honest pre-v6 refusal mode — version skew costs the cross-shard
#: gang feature, never the no-partial-gang invariant).  v7 adds the
#: elastic-membership surface: ``repl_prevote`` (a candidate probes
#: whether peers would support its promotion BEFORE incrementing the
#: term — a partitioned rejoiner can no longer depose a healthy
#: leader) and the dynamic-membership ops ``bus_add_replica`` /
#: ``bus_remove_replica`` (one replica at a time through a
#: WAL-recorded, replicated membership-config record).  A pre-v7 peer
#: answers ``unknown bus op``: the membership ops then fail with a
#: typed "dynamic membership unsupported" error (no fallback CAN exist
#: — an old peer has no config log to record the change in), and a
#: pre-vote that cannot be asked counts as a denial (safety over
#: liveness; an old peer cannot be a v7 replica anyway).  v8 adds the
#: binary codec: ``bus_hello`` negotiates a per-connection body
#: encoding (msgpack) and is the FIRST version to change what a frame
#: carries — so v8 is also the first version a frame is ever stamped
#: with.  The stamp is per frame, not per connection: JSON bodies ride
#: frames stamped MIN_VERSION exactly as before (a v1 peer accepts
#: them), msgpack bodies ride frames stamped 8, and the receiver
#: decodes by the stamp alone.  That makes the hello race-free — the
#: hello response is decodable whichever codec it arrives in — and
#: keeps the v1-fallback discipline intact: binary frames are sent
#: ONLY after the peer answered the hello with ``binary``, and a
#: pre-v8 peer answers ``unknown bus op`` to the hello itself, which
#: degrades the connection to JSON (never an error).
#: VERSION is the protocol revision this build speaks; receivers
#: accept [MIN_VERSION, VERSION].
VERSION = 8
#: oldest frame version this build still decodes — and the version
#: JSON-body frames carry, since their layout has not changed since v1.
#: Binary-body frames are stamped VERSION: the body encoding IS the
#: layout change, and the stamp is how the receiver tells them apart.
MIN_VERSION = 1

#: per-connection body codecs the hello exchange negotiates
CODEC_JSON = "json"
CODEC_BINARY = "binary"

T_REQ = 1            # client → server: one store operation
T_RESP = 2           # server → client: success payload for a T_REQ
T_ERROR = 3          # server → client: typed failure for a T_REQ
T_WATCH_EVENT = 4    # server → client: one watch event (id = watch id)
T_BOOKMARK = 5       # server → client: watch progress marker
# 6 reserved (was an unused stream-lost signal; 410-Gone is expressed
# as the watch response's ``resumed: false`` instead)
T_PING = 7
T_PONG = 8
T_ADMIT_REQ = 9      # server → client: admission review request
T_ADMIT_RESP = 10    # client → server: admission review verdict
#: server → client: N coalesced watch events in one frame.  Payload is
#: ``{"events": [{"watch_id": w, ...entry}, ...]}`` — each entry is
#: exactly a T_WATCH_EVENT payload plus the watch id it belongs to (one
#: connection multiplexes many watches, and the correlation-id slot can
#: carry only one).  Sent ONLY on watches established via the
#: ``watch_batch`` op, so a v1/v2 peer never sees the type.
T_WATCH_BATCH = 11

_HEADER = struct.Struct("<4sHHII")

#: decode registry — every kind the in-process store can hold.  Kind
#: names are class names (K8sObject.kind), so registration is mechanical.
KINDS: Dict[str, type] = {
    cls.__name__: cls
    for cls in (
        core.Pod, core.Node, core.PriorityClass, core.ConfigMap,
        core.Secret, core.Service, core.PersistentVolumeClaim,
        core.NetworkPolicy, core.Event,
        batch.Job,
        scheduling.PodGroup, scheduling.Queue,
        scheme.PodGroupV1alpha1, scheme.QueueV1alpha1,
        apis_bus.Command,
    )
}

#: request-op → protocol version that introduced it.  The compatibility
#: registry the serde-drift lint (volcano_tpu/analysis/serde_drift.py)
#: checks: every op the server dispatches must be declared here, and an
#: op introduced after MIN_VERSION must carry the client-side old-peer
#: fallback (the ``unknown bus op`` typed-error path) — the v1-stamping
#: rule PR 6's review enforced by hand.
OP_VERSIONS: Dict[str, int] = {
    "create": 1,
    "update": 1,
    "update_status": 1,
    "get": 1,
    "list": 1,
    "delete": 1,
    "watch": 1,
    "unwatch": 1,
    "register_admission": 1,
    "commit_batch": 2,
    "watch_batch": 3,
    "cas_bind": 4,
    "bus_status": 5,
    "repl_append": 5,
    "repl_snapshot": 5,
    "repl_commit": 5,
    "txn_commit": 6,
    "repl_prevote": 7,
    "bus_add_replica": 7,
    "bus_remove_replica": 7,
    "bus_hello": 8,
}

#: wire error name → exception class; unknown names fall back to ApiError.
#: NotLeaderError (defined below) registers itself after its definition.
ERRORS: Dict[str, type] = {
    cls.__name__: cls
    for cls in (
        ApiError, NotFoundError, AlreadyExistsError, ConflictError,
        AdmissionError,
    )
}


class BusError(ApiError):
    """Bus transport failure (connection refused/lost, protocol error).

    Subclasses ApiError so existing ``except ApiError`` call sites (CLI,
    electors, controllers) degrade gracefully instead of crashing."""


class BusTimeoutError(BusError):
    """A request did not complete within its per-call timeout."""


class NotLeaderError(ApiError):
    """A write (or leader-only op) landed on a replica that cannot take
    it.  ``leader`` carries the answering replica's current leader view
    (``tcp://host:port``, or None mid-election) so the client can redial
    the leader DIRECTLY instead of rotating the endpoint list blindly —
    the structured form of the ``"not leader"`` message-sniffing the
    failover drill used to pay a full rotation for."""

    def __init__(self, message: str, leader: Optional[str] = None):
        super().__init__(message)
        self.leader = leader


ERRORS[NotLeaderError.__name__] = NotLeaderError


def encode_obj(obj) -> Optional[dict]:
    """API object → wire dict (kind included for the decode registry)."""
    return None if obj is None else obj.to_dict()


def decode_obj(data: Optional[dict]):
    """Wire dict → API object via the kind registry."""
    if data is None:
        return None
    kind = data.get("kind")
    cls = KINDS.get(kind)
    if cls is None:
        raise BusError(f"unknown kind on the wire: {kind!r}")
    return cls.from_dict(data)


def error_payload(exc: Exception) -> dict:
    name = type(exc).__name__
    if name not in ERRORS:
        name = "ApiError"
    out = {"error": name, "message": str(exc)}
    leader = getattr(exc, "leader", None)
    if leader:
        # the leader-hint channel: a follower answering "not leader"
        # names the leader so the client's next dial is direct
        out["leader"] = leader
    return out


def raise_error(payload: dict) -> None:
    cls = ERRORS.get(payload.get("error", ""), ApiError)
    if cls is NotLeaderError:
        raise NotLeaderError(payload.get("message", "remote error"),
                             leader=payload.get("leader"))
    raise cls(payload.get("message", "remote error"))


def parse_bus_url(url: str) -> Tuple[str, int]:
    """``tcp://host:port`` → (host, port).  A bare ``host:port`` is
    accepted for convenience.  ``shm://host:port`` parses identically:
    the address still names the TCP endpoint (the shm ring directory is
    derived from it, and TCP is the attach-failure fallback), the
    scheme just asks the client to try the same-host ring first."""
    if url.startswith("tcp://"):
        url = url[len("tcp://"):]
    elif url.startswith("shm://"):
        url = url[len("shm://"):]
    elif "://" in url:
        raise ValueError(
            f"unsupported bus scheme in {url!r} (use tcp:// or shm://)")
    host, sep, port = url.rpartition(":")
    if not sep or not port.isdigit():
        raise ValueError(f"bus address needs host:port, got {url!r}")
    return host or "127.0.0.1", int(port)


def parse_bus_endpoints(urls: str) -> List[Tuple[str, int]]:
    """``tcp://a:1,tcp://b:2,...`` → [(host, port), ...] — the
    replicated-apiserver form of ``--bus``: a client dials the list in
    order until one answers, and redials across it on failure, so a
    dead replica never strands a daemon."""
    out: List[Tuple[str, int]] = []
    for part in urls.split(","):
        part = part.strip()
        if part:
            out.append(parse_bus_url(part))
    if not out:
        raise ValueError(f"bus endpoint list is empty: {urls!r}")
    return out


def encode_payload(payload: dict, codec: str = CODEC_JSON) -> bytes:
    """Serialize one frame body.  Split out of :func:`send_frame` so the
    bus server can serialize a watch event ONCE and fan the cached bytes
    out to every subscriber (the correlation id lives in the frame
    header, so the body bytes are subscriber-independent)."""
    if codec == CODEC_BINARY:
        return msgpack.packb(payload, use_bin_type=True)
    return json.dumps(payload, separators=(",", ":")).encode()


def decode_payload(body: bytes, codec: str = CODEC_JSON) -> dict:
    """Deserialize one frame body (the inverse of encode_payload)."""
    if not body:
        return {}
    if codec == CODEC_BINARY:
        if msgpack is None:
            raise BusError("binary frame received but msgpack is unavailable")
        return msgpack.unpackb(body, raw=False)
    return json.loads(body.decode())


def send_frame_raw(sock: socket.socket, mtype: int, corr_id: int,
                   body: bytes, codec: str = CODEC_JSON) -> None:
    """Send a frame whose body is already serialized in ``codec``."""
    # JSON bodies are stamped MIN_VERSION: their layout is v1's, so
    # version-skewed peers never reject at the framing layer —
    # capability skew surfaces as an op-level typed error instead (the
    # commit_batch fallback path).  Binary bodies are stamped VERSION:
    # the stamp is the per-frame codec marker the receiver decodes by,
    # and a pre-v8 peer (which could not decode the body anyway) rejects
    # at the header — but binary is only ever sent to a peer that asked
    # for it through the bus_hello negotiation.
    version = VERSION if codec == CODEC_BINARY else MIN_VERSION
    sock.sendall(_HEADER.pack(MAGIC, version, mtype, corr_id, len(body)) + body)


def send_frame(sock: socket.socket, mtype: int, corr_id: int, payload: dict,
               codec: str = CODEC_JSON) -> None:
    send_frame_raw(sock, mtype, corr_id, encode_payload(payload, codec), codec)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed mid-frame")
        buf.extend(chunk)
    return bytes(buf)


def recv_frame(sock: socket.socket) -> Tuple[int, int, dict]:
    head = _recv_exact(sock, _HEADER.size)
    magic, version, mtype, corr_id, length = _HEADER.unpack(head)
    if magic != MAGIC:
        raise ValueError("bad magic")
    if not (MIN_VERSION <= version <= VERSION):
        raise ValueError(f"unsupported bus protocol version {version}")
    body = _recv_exact(sock, length) if length else b""
    # the codec is read off the frame, not off connection state: a v8
    # stamp means a msgpack body, anything older is JSON.  This is what
    # makes the hello exchange race-free — the response decodes
    # correctly whichever codec the server sent it in.
    codec = CODEC_BINARY if version >= 8 else CODEC_JSON
    return mtype, corr_id, decode_payload(body, codec)


# ---- WAL record codec ----------------------------------------------------
#
# WAL records adopt the SAME body encoding as the wire so replication can
# ship record bytes verbatim to followers without a decode/re-encode leg.
# The on-disk codec is sniffed from the first byte on read: a JSON record
# opens with '{' (0x7b), a msgpack map opens with a fixmap/map16/map32
# marker — so old JSON logs recover under a binary-default build and
# vice versa, record by record.

_MSGPACK_MAP_MARKERS = frozenset(
    list(range(0x80, 0x90)) + [0xDE, 0xDF])


def encode_record(record: dict, codec: Optional[str] = None) -> bytes:
    """Serialize one WAL record.  ``codec=None`` picks the build
    default: binary when msgpack is importable, JSON otherwise."""
    if codec is None:
        codec = CODEC_BINARY if HAS_BINARY else CODEC_JSON
    return encode_payload(record, codec)


def decode_record(payload: bytes) -> dict:
    """Deserialize one WAL record, sniffing the codec from its first
    byte (both codecs open a top-level map with a distinct marker)."""
    if payload[:1] == b"{":
        return json.loads(payload.decode())
    if payload and payload[0] in _MSGPACK_MAP_MARKERS:
        return decode_payload(payload, CODEC_BINARY)
    raise ValueError(
        f"unrecognized WAL record codec (first byte {payload[:1]!r})")
