"""``RemoteAPIServer`` — the promised drop-in swap for the in-process bus.

Implements the exact surface of ``client.apiserver.APIServer`` (CRUD,
list, optimistic-concurrency update, status subresource, watch with
initial sync, admission registration) over one TCP connection to a
``vtpu-apiserver``.  Every consumer — KubeClient/VolcanoClient/
SchedulerClient, the controllers, the scheduler cache informers, the
leader elector, vtctl — runs unchanged against either backend.

Resilience model (the client-go informer contract):

* **Reconnect**: a lost connection is re-dialed forever with
  exponential backoff plus jitter; in-flight calls fail fast with
  ``BusError`` (an ``ApiError``, so daemon work loops retry next cycle).
* **Watch re-establishment**: after reconnect every watch resumes from
  its last-delivered bus sequence number.  When the server still holds
  that suffix, the missed events replay — no relist, no duplicates.
* **Relist fallback**: when the server answers 410-Gone (backlog
  outgrown, or a restarted server with a new epoch), the client
  re-lists and reconciles against its shadow cache, synthesizing
  exactly the ADDED/MODIFIED/DELETED deltas the handlers missed — so
  informer caches never silently diverge and never see duplicates.
  Every such resync increments ``volcano_bus_relists_total``.
* **Bookmarks** advance the resume point through quiet periods, keeping
  the post-reconnect replay window small.

Remote admission: ``register_admission`` makes this connection the
webhook endpoint for a (kind, operation) — the server forwards objects
here for review before committing them (the webhook deployment of the
reference's cmd/admission binary).
"""

from __future__ import annotations

import queue
import random
import socket
import sys
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from volcano_tpu import trace
from volcano_tpu.bus import protocol
from volcano_tpu.bus.protocol import BusError, BusTimeoutError
from volcano_tpu.client.apiserver import (
    ADDED,
    AdmissionError,
    ApiError,
    DELETED,
    MODIFIED,
)
from volcano_tpu.metrics import metrics
from volcano_tpu.utils.logging import get_logger

log = get_logger(__name__)

WatchHandler = Callable[[str, Optional[object], Optional[object]], None]


def _obj_key(data: dict) -> str:
    meta = data.get("metadata", {})
    return f"{meta.get('namespace', 'default')}/{meta.get('name', '')}"


class _WatchState:
    """Client-side informer state for one kind: the shadow cache the
    relist reconcile diffs against, the resume cursor, and the local
    handler fan-out."""

    def __init__(self, kind: str, watch_id: int):
        self.kind = kind
        self.watch_id = watch_id
        #: (handler, wants_initial) — wants_initial governs whether the
        #: FIRST sync's snapshot is delivered (the in-process
        #: ``send_initial`` contract); later relist deltas go to all
        self.handlers: List[Tuple[WatchHandler, bool]] = []
        #: key → wire dict of the last object version delivered
        self.shadow: Dict[str, dict] = {}
        self.epoch: Optional[str] = None
        self.last_seq: Optional[int] = None
        #: first reconcile done — its snapshot is "initial", not a delta
        self.synced = False
        #: torn down after the last handler left; a handler added to a
        #: defunct state is re-routed through a fresh watch
        self.defunct = False


class RemoteAPIServer:
    """Network client to a ``vtpu-apiserver`` bus.

    ``address`` is ``tcp://host:port`` (or a bare ``host:port``).
    Construction does not block on the dial — the connection manager
    establishes it in the background; use ``wait_ready()`` to gate
    startup on bus availability."""

    def __init__(
        self,
        address: str,
        timeout: float = 10.0,
        reconnect_min: float = 0.05,
        reconnect_max: float = 2.0,
    ):
        #: ``address`` may be a comma-separated endpoint LIST
        #: (``tcp://a,tcp://b,...``) — the replicated-apiserver form:
        #: the client dials entries in order until one answers and
        #: rotates across them on connection loss, so a dead replica
        #: never strands a daemon.  Reads/watches are served wherever
        #: we land (followers included); writes are proxied server-side
        #: to the leader.
        self.endpoints = [
            f"tcp://{h}:{p}" for h, p in protocol.parse_bus_endpoints(address)
        ]
        self._endpoint_idx = 0
        self.host, self.tcp_port = protocol.parse_bus_url(self.endpoints[0])
        self.address = self.endpoints[0]
        self.timeout = timeout
        self.reconnect_min = reconnect_min
        self.reconnect_max = reconnect_max

        self._sock: Optional[socket.socket] = None
        self._send_lock = threading.Lock()
        self._closed = False
        self._connected = threading.Event()
        self._ever_connected = False

        self._req_id = 0  # guarded-by: self._id_lock
        self._watch_id = 0  # guarded-by: self._id_lock
        self._id_lock = threading.Lock()
        #: req_id → {"event", "result", "error"}
        self._pending: Dict[int, dict] = {}  # guarded-by: self._pending_lock
        self._pending_lock = threading.Lock()

        self._watch_lock = threading.Lock()
        self._watches: Dict[str, _WatchState] = {}  # guarded-by: self._watch_lock
        self._by_watch_id: Dict[int, _WatchState] = {}  # guarded-by: self._watch_lock

        #: (kind, operation) → [hook]; replayed to the server on connect
        self._admission: Dict[Tuple[str, str], List] = {}

        #: set once a server rejects the v2 ``commit_batch`` op — the
        #: old-peer fallback (per-object binds) for skewed apiservers
        self._no_commit_batch = False
        #: set once a server rejects the v3 ``watch_batch`` op — watches
        #: then (re-)establish via plain ``watch`` and receive one
        #: T_WATCH_EVENT frame per object, exactly the old behavior
        self._no_watch_batch = False
        #: set once a server rejects the v4 ``cas_bind`` op — spillover
        #: binds then degrade to the get + CAS-update equivalent
        self._no_cas_bind = False
        #: set once a server rejects the v6 ``txn_commit`` op — atomic
        #: multi-object transactions then ABORT (reported unsupported),
        #: never replay per-object: a pre-v6 peer cannot apply half a
        #: gang atomically, so the gang broker degrades to the honest
        #: pre-v6 refusal mode instead
        self._no_txn_commit = False
        #: set once a server rejects the v5 ``bus_status`` op — status
        #: queries then answer a degraded ``role: unknown`` payload
        self._no_bus_status = False
        #: set once a server rejects the v8 ``bus_hello`` op — the
        #: connection (and every reconnect after it) then stays on JSON
        #: framing, exactly the pre-v8 wire format
        self._no_bus_hello = False
        #: negotiated body codec for the CURRENT connection — reset to
        #: JSON on every (re)dial, flipped to binary only when the
        #: server's hello answer says so.  Frames are stamped per frame,
        #: so a stale value can never misdecode anything.
        self.codec = protocol.CODEC_JSON
        #: this client must sit on the LEADER (set by
        #: register_admission: webhook reviews are forwarded by the
        #: server that runs the store transaction, which is always the
        #: leader) — on connect to a follower it redials at the
        #: follower-reported leader address
        self._must_lead = False
        #: monotonic stamp of the last leader-hint redial — one hint
        #: mid-election must not turn into a redial storm
        self._last_hint_redial = 0.0

        self._ctl: "queue.Queue[tuple]" = queue.Queue()
        self._dispatch_q: "queue.Queue[Optional[tuple]]" = queue.Queue()
        self._admit_q: "queue.Queue[Optional[tuple]]" = queue.Queue()

        self._conn_thread = threading.Thread(
            target=self._conn_loop, name="vtpu-bus-conn", daemon=True
        )
        self._dispatch_thread = threading.Thread(
            target=self._dispatch_loop, name="vtpu-bus-dispatch", daemon=True
        )
        self._admit_thread = threading.Thread(
            target=self._admit_loop, name="vtpu-bus-admit", daemon=True
        )
        self._conn_thread.start()
        self._dispatch_thread.start()
        self._admit_thread.start()

    # ---- connection management ----

    def wait_ready(self, timeout: float = 30.0) -> bool:
        """Block until the bus is reachable (daemon startup gate)."""
        return self._connected.wait(timeout)

    def _current_endpoint(self) -> Tuple[str, int]:
        url = self.endpoints[self._endpoint_idx % len(self.endpoints)]
        self.address = url
        return protocol.parse_bus_url(url)

    def _dial(self) -> socket.socket:
        """One transport attempt at the current endpoint: the same-host
        shm ring first when enabled (``local_up --multiproc``), TCP
        otherwise — and TCP as the silent fallback whenever the ring
        attach fails for ANY reason (no listener, no directory, no
        fd-passing).  Both return socket-shaped objects carrying the
        identical frame stream."""
        host, port = self._current_endpoint()
        from volcano_tpu.bus import shm

        if shm.shm_enabled() and host in ("127.0.0.1", "localhost", "::1"):
            try:
                return shm.connect(port, timeout=self.timeout)
            except (OSError, ValueError, ConnectionError) as e:
                log.debug("bus shm attach failed (%s); dialing TCP", e)
        return socket.create_connection((host, port), timeout=self.timeout)

    def _conn_loop(self) -> None:
        backoff = self.reconnect_min
        while not self._closed:
            try:
                sock = self._dial()
            except OSError:
                # rotate to the next replica before backing off — a
                # dead endpoint must not serialize the whole list
                # behind its own backoff ladder
                self._endpoint_idx += 1
                jitter = random.uniform(0, backoff * 0.25)
                time.sleep(backoff + jitter)
                backoff = min(backoff * 2, self.reconnect_max)
                continue
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            backoff = self.reconnect_min
            self.codec = protocol.CODEC_JSON  # until the hello says otherwise
            self._sock = sock
            reader = threading.Thread(
                target=self._read_loop, args=(sock,),
                name="vtpu-bus-reader", daemon=True,
            )
            reader.start()
            if self._ever_connected:
                metrics.register_bus_reconnect()
                log.info("bus %s reconnected", self.address)
            self._ever_connected = True
            self._connected.set()
            try:
                self._negotiate_codec()
            except (ApiError, OSError):
                # negotiation must never cost the connection: any
                # failure here leaves the codec on JSON and the session
                # proceeds (a true transport loss surfaces through the
                # reader thread's disconnect signal regardless)
                self.codec = protocol.CODEC_JSON
            if self._must_lead and not self._leader_check():
                # connected to a follower while this client must sit on
                # the leader (admission endpoint): redial at the leader
                self._connected.clear()
                self._teardown_socket(sock)
                self._fail_pending(BusError("redialing at the bus leader"))
                time.sleep(min(0.2, self.reconnect_max))
                continue
            self._resync_session()
            # serve control messages until the reader reports loss
            while not self._closed:
                item = self._ctl.get()
                if item[0] == "disconnect":
                    break
                if item[0] == "redial":
                    break  # e.g. leader moved — reconnect at the hint
                if item[0] == "resync":
                    self._resync_session()
                if item[0] == "unsubscribe":
                    try:
                        self._call({"op": "unwatch", "watch_id": item[1]})
                    except (ApiError, OSError):
                        pass  # a dead connection drops the sub anyway
                if item[0] == "stop":
                    return
            self._connected.clear()
            self._teardown_socket(sock)
            self._fail_pending(BusError("bus connection lost"))

    def _negotiate_codec(self) -> None:
        """VBUS v8 codec negotiation — the FIRST exchange on every
        fresh connection (before the leader check and the session
        resync, so both ride the negotiated codec).  The hello itself
        always goes as a JSON frame; the reply is decoded by its frame
        stamp, so there is no ordering race with the server's codec
        flip.  Degrades to JSON — never errors — on ANY non-binary
        answer: a pre-v8 server answers ``unknown bus op`` (degrade
        PERMANENTLY per connection lifetime, like every capability
        flag), a msgpack-less build never offers binary at all, and an
        explicit ``codec: json`` answer is honored as-is.  Every
        degradation increments ``volcano_bus_codec_fallbacks_total``."""
        if self._no_bus_hello or not protocol.HAS_BINARY:
            return
        try:
            resp = self._call({
                "op": "bus_hello",
                "codecs": [protocol.CODEC_BINARY, protocol.CODEC_JSON],
            })
        except BusError:
            raise  # transport failure — NOT a capability signal
        except ApiError as e:
            if "unknown bus op" not in str(e):
                raise
            log.warning(
                "bus %s does not speak bus_hello (old peer); JSON framing",
                self.address,
            )
            self._no_bus_hello = True
            metrics.register_bus_codec_fallback()
            return
        if resp.get("codec") == protocol.CODEC_BINARY:
            self.codec = protocol.CODEC_BINARY
        else:
            self.codec = protocol.CODEC_JSON
            metrics.register_bus_codec_fallback()

    def _leader_check(self) -> bool:
        """True when the connected peer can host this client (leader,
        standalone, or a pre-v5 server).  On a follower: point the
        endpoint cursor at the reported leader and return False."""
        try:
            status = self.bus_status()
        except (ApiError, OSError):
            return True  # can't tell — stay; calls will surface errors
        if status.get("role") != "follower":
            return True
        leader = status.get("leader")
        if not leader:
            return False  # election in progress — retry shortly
        if leader not in self.endpoints:
            self.endpoints.append(leader)
        self._endpoint_idx = self.endpoints.index(leader)
        log.info("bus %s is a follower; redialing at leader %s",
                 self.address, leader)
        return False

    def _resync_session(self) -> None:
        """After (re)connect: re-register admission endpoints, then
        re-establish every watch with resume-or-relist.  Each item is
        attempted independently, and ANY failure schedules a full retry
        — the whole resync is idempotent (re-registration dedups
        server-side; a re-established watch resumes from last_seq and
        replayed events dedup by sequence number), and a watch left
        un-established would freeze its informer cache silently."""
        failed = False
        for kind, operation in list(self._admission):
            try:
                self._call({"op": "register_admission", "kind": kind,
                            "operation": operation})
            except (ApiError, OSError) as e:
                log.error("bus admission re-register %s/%s failed: %s",
                          kind, operation, e)
                if "not leader" in str(e):
                    # this peer became a follower — redial at the leader
                    self._ctl.put(("redial",))
                    return
                failed = True
        with self._watch_lock:
            states = list(self._watches.values())
        for state in states:
            try:
                self._establish_watch(state)
            except (ApiError, OSError) as e:
                log.error("bus watch %s re-establish failed: %s",
                          state.kind, e)
                failed = True
        if failed and not self._closed:
            def _retry():
                time.sleep(min(self.reconnect_max, 0.5))
                if not self._closed and self._connected.is_set():
                    self._ctl.put(("resync",))

            threading.Thread(target=_retry, name="vtpu-bus-resync-retry",
                             daemon=True).start()

    def _teardown_socket(self, sock: socket.socket) -> None:
        if self._sock is sock:
            self._sock = None
        try:
            sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            sock.close()
        except OSError:
            pass

    def _fail_pending(self, error: Exception) -> None:
        with self._pending_lock:
            pending = list(self._pending.values())
            self._pending.clear()
        for waiter in pending:
            waiter["error"] = error
            waiter["event"].set()

    def _read_loop(self, sock: socket.socket) -> None:
        while not self._closed:
            try:
                mtype, corr_id, payload = protocol.recv_frame(sock)
            except (ConnectionError, OSError, ValueError):
                if self._sock is sock:
                    self._ctl.put(("disconnect",))
                return
            if mtype in (protocol.T_RESP, protocol.T_PONG):
                self._resolve(corr_id, payload, None)
            elif mtype == protocol.T_ERROR:
                self._resolve(corr_id, None, payload)
            elif mtype == protocol.T_WATCH_EVENT:
                state = self._watch_state(corr_id)
                if state is not None:
                    self._dispatch_q.put(("event", state, payload))
            elif mtype == protocol.T_WATCH_BATCH:
                # coalesced frame (protocol v3): unbatch in wire order —
                # each entry carries its own watch id, and the dispatch
                # queue preserves ordering exactly as per-object frames
                # would have
                for entry in payload.get("events", ()):
                    state = self._watch_state(int(entry.get("watch_id", -1)))
                    if state is not None:
                        self._dispatch_q.put(("event", state, entry))
            elif mtype == protocol.T_BOOKMARK:
                state = self._watch_state(corr_id)
                if state is not None:
                    self._dispatch_q.put(("bookmark", state, payload))
            elif mtype == protocol.T_ADMIT_REQ:
                self._admit_q.put((corr_id, payload))

    def _watch_state(self, watch_id: int) -> Optional[_WatchState]:
        # the reader thread races watch()/unwatch teardown on other
        # threads — the bare dict read was the lock lint's catch
        with self._watch_lock:
            return self._by_watch_id.get(watch_id)

    def _resolve(self, req_id: int, result, error) -> None:
        with self._pending_lock:
            waiter = self._pending.pop(req_id, None)
        if waiter is None:
            return
        on_reply = waiter.get("on_reply")
        if on_reply is not None and result is not None:
            # runs on the READER thread, before any later frame is
            # processed — work enqueued here (a watch snapshot's
            # reconcile) is ordered against subsequent watch events
            # exactly as the wire ordered them
            try:
                on_reply(result)
            except Exception as e:  # noqa: BLE001
                log.error("bus reply hook failed: %s", e)
        waiter["result"] = result
        waiter["error_payload"] = error
        waiter["event"].set()

    # ---- request plumbing ----

    def _next_id(self) -> int:
        with self._id_lock:
            self._req_id += 1
            return self._req_id

    def _call(self, payload: dict, timeout: Optional[float] = None,
              mtype: int = protocol.T_REQ, on_reply=None) -> dict:
        if self._closed:
            raise BusError("bus client closed")
        timeout = timeout if timeout is not None else self.timeout
        method = payload.get("op", "ping")
        client_span = None
        if mtype == protocol.T_REQ:
            # cross-process correlation: stamp the scheduling-cycle id on
            # the request frame so server-side records (trace events, op
            # logs) can be joined back to the cycle that caused them.
            # Old servers ignore the key.
            cycle = trace.current_cycle()
            if cycle >= 0 and "cycle" not in payload:
                payload["cycle"] = cycle
            # flight-recorder span context rides the same payload slot
            # discipline (obs/spans.py): old servers ignore the key —
            # no new op, no version bump.  None when the recorder is
            # off or no span is open, so the default path stamps
            # nothing.
            from volcano_tpu import obs

            span_ctx = obs.current_wire()
            if span_ctx is not None and "span" not in payload:
                # client half of the paired bus span: same name as the
                # server's adopted ``bus:<op>`` span, linked parent →
                # child across the wire.  The pair is what
                # obs/collect.py's clock-skew estimator keys on (RTT
                # midpoints), and its duration is the client-PERCEIVED
                # rpc time — which a server-side bus.delay fault
                # inflates, making slow hops tail-keepable anomalies.
                client_span = obs.span(
                    "bus:" + method, cat="bus",
                    args={"peer": self.address},
                )
                client_span.__enter__()
                payload["span"] = obs.current_wire() or span_ctx
        start = time.perf_counter()
        try:
            return self._call_framed(payload, timeout, mtype, method,
                                     start, on_reply)
        finally:
            if client_span is not None:
                client_span.__exit__(*sys.exc_info())

    def _call_framed(self, payload: dict, timeout: float, mtype: int,
                     method: str, start: float, on_reply) -> dict:
        if not self._connected.wait(timeout):
            metrics.observe_bus_request(method, time.perf_counter() - start,
                                        "disconnected")
            raise BusError(f"bus {self.address} unreachable")
        from volcano_tpu import faults

        fp = faults.get_plane()
        if fp.enabled and mtype == protocol.T_REQ and fp.should("bus.client_drop"):
            # the request frame never reaches the wire: callers see the
            # same BusError a mid-send connection loss produces, and the
            # daemon work loops retry next cycle
            metrics.observe_bus_request(method, time.perf_counter() - start,
                                        "disconnected")
            raise BusError("fault-injected: request frame lost")
        req_id = self._next_id()
        waiter = {"event": threading.Event(), "result": None,
                  "error": None, "error_payload": None, "on_reply": on_reply}
        with self._pending_lock:
            self._pending[req_id] = waiter
        try:
            sock = self._sock
            if sock is None:
                raise BusError("bus connection lost")
            with self._send_lock:
                protocol.send_frame(sock, mtype, req_id, payload,
                                    codec=self.codec)
        except (OSError, BusError) as e:
            with self._pending_lock:
                self._pending.pop(req_id, None)
            metrics.observe_bus_request(method, time.perf_counter() - start,
                                        "disconnected")
            raise BusError(f"bus send failed: {e}") from e
        if not waiter["event"].wait(timeout):
            with self._pending_lock:
                self._pending.pop(req_id, None)
            metrics.observe_bus_request(method, time.perf_counter() - start,
                                        "timeout")
            raise BusTimeoutError(f"bus call {method!r} timed out after {timeout}s")
        if waiter["error"] is not None:
            metrics.observe_bus_request(method, time.perf_counter() - start,
                                        "disconnected")
            raise waiter["error"]
        if waiter["error_payload"] is not None:
            metrics.observe_bus_request(method, time.perf_counter() - start, "error")
            leader = waiter["error_payload"].get("leader")
            if leader:
                # leader-hint redial: a "not leader" answer NAMES the
                # current leader — steer the endpoint cursor there and
                # reconnect directly instead of rotating the list
                # blindly (each blind rotation costs a dial + probe;
                # the hint collapses the failover/proxy tail to one
                # reconnect).  Debounced: one hint per second at most.
                self._note_leader(leader)
            protocol.raise_error(waiter["error_payload"])
        metrics.observe_bus_request(method, time.perf_counter() - start, "ok")
        return waiter["result"]

    def _note_leader(self, leader: str) -> None:
        """Point the endpoint cursor at a hinted leader so the NEXT
        dial — a reconnect after a failure, or a ``_must_lead``
        leader-chase — goes straight there instead of rotating the
        list blindly.  Mirrors ``_leader_check``'s cursor discipline
        (benign races: worst case the list briefly holds a duplicate
        entry).

        Only a ``_must_lead`` client redials IMMEDIATELY: for everyone
        else the hinted write already failed typed and the caller's
        retry flows through the live connection once the proxy heals.
        Tearing down a healthy follower connection on every hint was
        worse than blind rotation — mid-failover the hint names the
        JUST-DEAD leader (the follower's stale view), and the
        pointless redial both pays a dead dial and forces the watch
        resume onto whatever epoch the reconnect lands on (the
        zero-relist failover pin caught exactly that churn)."""
        if leader not in self.endpoints:
            self.endpoints.append(leader)
        self._endpoint_idx = self.endpoints.index(leader)
        now = time.monotonic()
        if (
            self._must_lead
            and leader != self.address
            and now - self._last_hint_redial >= 1.0
        ):
            self._last_hint_redial = now
            self._ctl.put(("redial",))

    def _send_noreply(self, mtype: int, corr_id: int, payload: dict) -> None:
        sock = self._sock
        if sock is None:
            return
        try:
            with self._send_lock:
                protocol.send_frame(sock, mtype, corr_id, payload,
                                    codec=self.codec)
        except OSError:
            pass

    # ---- the APIServer surface ----

    def health(self) -> bool:
        try:
            self._call({}, mtype=protocol.T_PING)
            return True
        except (BusError, OSError):
            return False

    def bus_status(self) -> dict:
        """Bus durability/replication status (protocol v5): role, leader
        identity, term, WAL/snapshot stats, follower lag — the payload
        ``vtctl bus status`` renders.  A pre-v5 server answers ``unknown
        bus op``; the client then degrades PERMANENTLY (per connection
        lifetime) to a ``role: unknown`` payload — status is
        observability, never correctness."""
        if not self._no_bus_status:
            try:
                return self._call({"op": "bus_status"})
            except BusError:
                raise  # transport failure — NOT a capability signal
            except ApiError as e:
                if "unknown bus op" not in str(e):
                    raise
                log.warning(
                    "bus %s does not speak bus_status (old peer)",
                    self.address,
                )
                self._no_bus_status = True
        return {"role": "unknown", "persistent": False}

    def _membership_call(self, op: str, url: str, verb: str) -> dict:
        """Shared driver for the VBUS v7 membership ops.  Routed to the
        leader (a follower proxies).  A pre-v7 server answers ``unknown
        bus op``: dynamic membership then fails with a typed error — no
        fallback CAN exist, an old peer has no membership log to record
        the change in (version skew costs the elastic feature, never
        group safety)."""
        try:
            # the leader may wait for a joiner's catch-up (or probe the
            # shrunk group's reachability) before logging the config
            # record — give it room beyond the default per-call budget
            return self._call({"op": op, "url": url},
                              timeout=max(self.timeout, 30.0))
        except BusError:
            raise  # transport failure — NOT a capability signal
        except ApiError as e:
            if "unknown bus op" not in str(e):
                raise
            raise ApiError(
                "bus does not support dynamic membership (pre-v7 "
                f"peer) — {verb} refused"
            ) from e

    def bus_add_replica(self, url: str) -> dict:
        """Admit one new replica to the replication group (protocol v7;
        ``vtctl bus add-replica``)."""
        return self._membership_call("bus_add_replica", url,
                                     "add-replica")

    def bus_remove_replica(self, url: str) -> dict:
        """Retire one replica from the replication group (protocol v7;
        ``vtctl bus remove-replica``)."""
        return self._membership_call("bus_remove_replica", url,
                                     "remove-replica")

    def create(self, obj):
        resp = self._call({"op": "create", "object": protocol.encode_obj(obj)})
        return protocol.decode_obj(resp["object"])

    def update(self, obj, expected_rv: Optional[int] = None):
        resp = self._call({
            "op": "update", "object": protocol.encode_obj(obj),
            "expected_rv": expected_rv,
        })
        return protocol.decode_obj(resp["object"])

    def compare_and_update(self, obj, expected_rv: int):
        return self.update(obj, expected_rv=expected_rv)

    def update_status(self, obj):
        resp = self._call({"op": "update_status",
                           "object": protocol.encode_obj(obj)})
        return protocol.decode_obj(resp["object"])

    def get(self, kind: str, namespace: str, name: str):
        resp = self._call({"op": "get", "kind": kind,
                           "namespace": namespace, "name": name})
        return protocol.decode_obj(resp["object"])

    def list(self, kind: str, namespace: Optional[str] = None) -> List:
        resp = self._call({"op": "list", "kind": kind, "namespace": namespace})
        return [protocol.decode_obj(d) for d in resp["objects"]]

    def delete(self, kind: str, namespace: str, name: str):
        resp = self._call({"op": "delete", "kind": kind,
                           "namespace": namespace, "name": name})
        return protocol.decode_obj(resp["object"])

    def commit_batch(self, binds=(), evicts=(), events=(), conditions=(),
                     pod_groups=()):
        """Coalesced commit frame (protocol v2): one VBUS request
        carrying N binds + evictions + audit events + status writebacks,
        applied server-side as a single store transaction.  A v1 server
        answers ``unknown bus op`` — the client then degrades PERMANENTLY
        (per connection lifetime) to per-object binds through the shared
        :func:`client.apiserver.apply_commit_batch` semantics, so a
        version-skewed apiserver costs throughput, never correctness."""
        if not self._no_commit_batch:
            try:
                resp = self._call({
                    "op": "commit_batch",
                    "binds": list(binds),
                    "evicts": list(evicts),
                    "events": list(events),
                    "conditions": list(conditions),
                    "pod_groups": [protocol.encode_obj(pg)
                                   for pg in pod_groups],
                })
                return resp["results"]
            except BusError:
                raise  # transport failure — NOT a capability signal
            except ApiError as e:
                if "unknown bus op" not in str(e):
                    raise
                log.warning(
                    "bus %s does not speak commit_batch (old peer); "
                    "falling back to per-object binds", self.address,
                )
                self._no_commit_batch = True
        from volcano_tpu.client.apiserver import apply_commit_batch

        return apply_commit_batch(
            self, binds=binds, evicts=evicts, events=events,
            conditions=conditions, pod_groups=pod_groups,
        )

    def cas_bind(self, namespace: str, name: str, hostname: str,
                 expected_rv=None):
        """Optimistic binding write (protocol v4): one round trip that
        binds the pod iff it is still unbound and its resourceVersion
        matches — the federation spillover primitive.  A pre-v4 server
        answers ``unknown bus op``; the client then degrades PERMANENTLY
        (per connection lifetime) to the get + CAS ``update``
        equivalent.  The at-most-once-bind invariant survives the skew
        unchanged (the conflict is still detected at the store via the
        expected resourceVersion); the one semantic difference is that
        ``update`` runs the server's UPDATE admission chain, which the
        native op skips like any binding subresource — against an old
        server, a Pod-UPDATE webhook can therefore observe (and reject)
        spillover binds.  A rejected bind counts as a spillover error
        and is retried next cycle, never silently dropped."""
        if not self._no_cas_bind:
            try:
                resp = self._call({
                    "op": "cas_bind", "namespace": namespace,
                    "name": name, "hostname": hostname,
                    "expected_rv": expected_rv,
                })
                return protocol.decode_obj(resp["object"])
            except BusError:
                raise  # transport failure — NOT a capability signal
            except ApiError as e:
                if "unknown bus op" not in str(e):
                    raise
                log.warning(
                    "bus %s does not speak cas_bind (old peer); "
                    "falling back to get + CAS update", self.address,
                )
                self._no_cas_bind = True
        from volcano_tpu.client.apiserver import ConflictError

        pod = self.get("Pod", namespace, name)
        if pod is None:
            from volcano_tpu.client.apiserver import NotFoundError

            raise NotFoundError(f"Pod {namespace}/{name} not found")
        if pod.spec.node_name:
            raise ConflictError(
                f"pod {namespace}/{name} already bound to "
                f"{pod.spec.node_name}"
            )
        if (
            expected_rv is not None
            and pod.metadata.resource_version != expected_rv
        ):
            raise ConflictError(
                f"Pod {namespace}/{name} resourceVersion "
                f"{pod.metadata.resource_version} != expected {expected_rv}"
            )
        pod.spec.node_name = hostname
        return self.update(pod, expected_rv=pod.metadata.resource_version)

    def txn_commit(self, binds=()):
        """Atomic multi-``cas_bind`` transaction (protocol v6): N
        conditional binds checked and applied all-or-nothing in one
        server-side store lock hold — the cross-shard gang-assembly
        primitive.  Returns the ``{committed, results, objects}`` shape
        of :meth:`client.apiserver.APIServer.txn_commit`.

        A pre-v6 server answers ``unknown bus op``; the client then
        degrades PERMANENTLY (per connection lifetime) to an ABORT —
        ``committed: False`` with every item marked unsupported and
        ``reason: "unsupported"`` — and NEVER to a per-object replay: a
        sequence of single binds against an old peer could crash or
        conflict halfway and strand a partial gang, which is exactly
        the state the transaction exists to forbid.  Version skew costs
        the cross-shard gang feature, never the no-partial-gang
        invariant (the caller stays in the pre-v6 refusal mode)."""
        binds = list(binds)
        if not self._no_txn_commit:
            try:
                resp = self._call({"op": "txn_commit", "binds": binds})
                return {
                    "committed": resp["committed"],
                    "results": resp["results"],
                    "objects": [
                        protocol.decode_obj(d)
                        for d in resp.get("objects", ())
                    ],
                }
            except BusError:
                raise  # transport failure — NOT a capability signal
            except ApiError as e:
                if "unknown bus op" not in str(e):
                    raise
                log.warning(
                    "bus %s does not speak txn_commit (old peer); "
                    "atomic multi-object transactions abort — no "
                    "per-object fallback can be atomic", self.address,
                )
                self._no_txn_commit = True
        return {
            "committed": False,
            "results": [
                "unsupported: pre-v6 bus cannot apply an atomic "
                "multi-object transaction"
            ] * len(binds),
            "objects": [],
            "reason": "unsupported",
        }

    def record_event(
        self,
        namespace: str,
        involved: dict,
        type_: str,
        reason: str,
        message: str,
    ):
        """Event recorder over the bus — the same aggregate-by-
        (object, type, reason) correlator the in-process clients use
        (client.clients.record_event_via), so SchedulerCache audit
        Events flow when the cache's client is a bare RemoteAPIServer
        rather than a SchedulerClient wrapper."""
        from volcano_tpu.client.clients import record_event_via

        return record_event_via(self, namespace, involved, type_,
                                reason, message)

    def register_admission(self, kind: str, operation: str, hook) -> None:
        """Make this client the webhook endpoint for (kind, operation).
        Hooks run locally when the server forwards a review; the
        registration survives reconnects."""
        key = (kind, operation)
        first = key not in self._admission
        self._admission.setdefault(key, []).append(hook)
        #: reviews are forwarded by the leader — from now on this client
        #: chases the leader across reconnects (replicated apiservers)
        self._must_lead = True
        if first and self._connected.is_set():
            try:
                self._call({"op": "register_admission", "kind": kind,
                            "operation": operation})
            except (ApiError, OSError) as e:
                # the connection may survive the failed call (a stalled
                # server times the request out without dropping TCP), so
                # waiting for the connect-time resync is not enough —
                # an unregistered webhook fails OPEN on the server side
                log.error("bus admission register %s/%s failed: %s",
                          kind, operation, e)
                if "not leader" in str(e):
                    # we sit on a follower: break the connection so the
                    # reconnect (with _must_lead set) lands on the
                    # leader, where the resync replays the registration
                    self._ctl.put(("redial",))
                else:
                    self._ctl.put(("resync",))

    def watch(self, kind: str, handler: WatchHandler,
              send_initial: bool = True) -> None:
        """Same contract as the in-process ``APIServer.watch``: register
        a handler; with ``send_initial`` it first receives ADDED for
        every existing object (served from the shadow cache when the
        stream is already up)."""
        with self._watch_lock:
            state = self._watches.get(kind)
            fresh = state is None
            if fresh:
                with self._id_lock:
                    self._watch_id += 1
                    wid = self._watch_id
                state = _WatchState(kind, wid)
                self._watches[kind] = state
                self._by_watch_id[state.watch_id] = state
        # handler registration goes through the dispatch queue so its
        # initial snapshot and subsequent events form one ordered stream
        self._dispatch_q.put(("add_handler", state, (handler, send_initial)))
        if fresh and self._connected.is_set():
            try:
                self._establish_watch(state)
            except (ApiError, OSError) as e:
                # the connection manager owns recovery: a resync pass
                # re-establishes every watch (idempotent), so a blip
                # here cannot leave this informer silently frozen
                log.error("bus watch %s establish failed: %s", kind, e)
                self._ctl.put(("resync",))
        # when not connected, the connect-time resync establishes it

    def unwatch(self, kind: str, handler: WatchHandler) -> None:
        with self._watch_lock:
            state = self._watches.get(kind)
        if state is not None:
            self._dispatch_q.put(("remove_handler", state, handler))

    def close(self) -> None:
        self._closed = True
        self._connected.clear()
        self._ctl.put(("stop",))
        sock = self._sock
        if sock is not None:
            self._teardown_socket(sock)
        self._fail_pending(BusError("bus client closed"))
        self._dispatch_q.put(None)
        self._admit_q.put(None)

    # ---- watch internals ----

    def _establish_watch(self, state: _WatchState) -> None:
        def accept(resp: dict) -> None:
            # Reader-thread hook: the snapshot's reconcile MUST be
            # enqueued before any live event frame that follows the
            # watch response on the wire — enqueueing from the calling
            # thread instead would let a racing DELETED event be
            # overwritten by the older snapshot (a resurrected object
            # in every informer cache, with last_seq regressed).
            if resp.get("resumed"):
                state.epoch = resp["epoch"]
                if "initial" in resp:
                    self._dispatch_q.put(
                        ("reconcile", state, (resp["initial"], resp["seq"]))
                    )

        def establish(base: dict) -> dict:
            """One watch request, preferring the v3 coalesced-delivery
            op.  A server that answers ``unknown bus op`` for
            ``watch_batch`` is an old peer — degrade PERMANENTLY (per
            connection lifetime) to the per-object ``watch`` op; skew
            costs fan-out throughput, never correctness."""
            if not self._no_watch_batch:
                try:
                    return self._call(
                        {"op": "watch_batch", **base}, on_reply=accept
                    )
                except BusError:
                    raise  # transport failure — NOT a capability signal
                except ApiError as e:
                    if "unknown bus op" not in str(e):
                        raise
                    log.warning(
                        "bus %s does not speak watch_batch (old peer); "
                        "per-object watch frames", self.address,
                    )
                    self._no_watch_batch = True
            return self._call({"op": "watch", **base}, on_reply=accept)

        base = {"kind": state.kind, "watch_id": state.watch_id}
        if state.epoch is not None and state.last_seq is not None:
            base["epoch"] = state.epoch
            base["resume_seq"] = state.last_seq
        resp = establish(base)
        if not resp.get("resumed"):
            # 410 Gone — relist: fresh watch returns an atomic snapshot
            # the dispatch thread reconciles against the shadow cache
            metrics.register_bus_relist(state.kind)
            log.info("bus watch %s: resume rejected (410); relisting",
                     state.kind)
            establish({"kind": state.kind, "watch_id": state.watch_id})

    def _dispatch_loop(self) -> None:
        while True:
            item = self._dispatch_q.get()
            if item is None:
                return
            op, state, payload = item
            try:
                if op == "event":
                    self._apply_event(state, payload)
                elif op == "bookmark":
                    if state.last_seq is None or payload["seq"] > state.last_seq:
                        state.last_seq = payload["seq"]
                    metrics.update_bus_watch_lag(time.time() - payload["ts"])
                elif op == "reconcile":
                    self._reconcile(state, *payload)
                elif op == "add_handler":
                    handler, send_initial = payload
                    if state.defunct:
                        # raced a teardown of the last handler — register
                        # through the public path so a fresh watch state
                        # (and server subscription) is established
                        self.watch(state.kind, handler, send_initial)
                        continue
                    state.handlers.append((handler, send_initial))
                    if send_initial and state.synced:
                        for data in list(state.shadow.values()):
                            self._fire(state, [(handler, True)], ADDED, None,
                                       protocol.decode_obj(data))
                elif op == "remove_handler":
                    state.handlers = [
                        (h, init) for h, init in state.handlers if h != payload
                    ]
                    if not state.handlers and not state.defunct:
                        # nobody listens: fully detach, like the
                        # in-process unwatch — drop the client state and
                        # stop the server-side stream (otherwise every
                        # mutation of this kind keeps flowing over TCP
                        # into a shadow cache nobody reads)
                        state.defunct = True
                        with self._watch_lock:
                            if self._watches.get(state.kind) is state:
                                del self._watches[state.kind]
                            self._by_watch_id.pop(state.watch_id, None)
                        self._ctl.put(("unsubscribe", state.watch_id))
            except Exception as e:  # noqa: BLE001 — keep the stream alive
                log.error("bus dispatch %s/%s failed: %s", op, state.kind, e)

    def _apply_event(self, state: _WatchState, entry: dict) -> None:
        if state.last_seq is not None and entry["seq"] <= state.last_seq:
            return  # replay overlap — already delivered
        event = entry["event"]
        old_d, new_d = entry["old"], entry["new"]
        key = _obj_key(new_d if new_d is not None else old_d)
        if event == DELETED:
            state.shadow.pop(key, None)
        else:
            state.shadow[key] = new_d
        state.last_seq = entry["seq"]
        metrics.register_bus_watch_event(state.kind)
        metrics.update_bus_watch_lag(time.time() - entry["ts"])
        self._fire(state, state.handlers, event,
                   protocol.decode_obj(old_d), protocol.decode_obj(new_d))

    def _reconcile(self, state: _WatchState, initial: List[dict],
                   seq: int) -> None:
        """The informer Replace(): diff the fresh list against the shadow
        cache and synthesize exactly the missed deltas — no duplicates,
        no gaps.  The very first sync is the "initial" snapshot, which
        only ``send_initial`` handlers asked for; every later reconcile
        is a relist whose deltas all handlers need."""
        first_sync = not state.synced
        state.synced = True
        add_targets = (
            [(h, init) for h, init in state.handlers if init]
            if first_sync else state.handlers
        )
        fresh = {_obj_key(d): d for d in initial}
        for key, new_d in fresh.items():
            old_d = state.shadow.get(key)
            if old_d is None:
                self._fire(state, add_targets, ADDED, None,
                           protocol.decode_obj(new_d))
            elif (old_d.get("metadata", {}).get("resourceVersion")
                  != new_d.get("metadata", {}).get("resourceVersion")):
                self._fire(state, state.handlers, MODIFIED,
                           protocol.decode_obj(old_d),
                           protocol.decode_obj(new_d))
        for key, old_d in list(state.shadow.items()):
            if key not in fresh:
                self._fire(state, state.handlers, DELETED,
                           protocol.decode_obj(old_d), None)
        state.shadow = fresh
        state.last_seq = seq

    def _fire(self, state: _WatchState, handlers, event, old, new) -> None:
        for handler, _wants_initial in list(handlers):
            try:
                handler(event, old, new)
            except Exception as e:  # noqa: BLE001 — a bad handler must not
                # kill the shared dispatch thread
                log.error("watch handler for %s failed on %s: %s",
                          state.kind, event, e)

    # ---- remote admission reviews ----

    def _admit_loop(self) -> None:
        while True:
            item = self._admit_q.get()
            if item is None:
                return
            review_id, payload = item
            kind, operation = payload["kind"], payload["operation"]
            hooks = list(self._admission.get((kind, operation), []))
            try:
                from volcano_tpu import obs

                obj = protocol.decode_obj(payload["object"])
                meta = getattr(obj, "metadata", None)
                with obs.adopt(
                    payload.get("span"), "admission:review", cat="admission",
                    args={
                        "kind": kind, "operation": operation,
                        **({"pod": f"{meta.namespace}/{meta.name}"}
                           if kind == "Pod" and meta is not None else {}),
                    },
                ):
                    for hook in hooks:
                        obj = hook(operation, obj) or obj
                resp = {"allowed": True, "object": protocol.encode_obj(obj)}
            except AdmissionError as e:
                resp = {"allowed": False, "message": str(e)}
            except Exception as e:  # noqa: BLE001 — deny, don't crash
                log.error("admission hook %s/%s crashed: %s", kind, operation, e)
                resp = {"allowed": False, "message": f"webhook error: {e}"}
            self._send_noreply(protocol.T_ADMIT_RESP, review_id, resp)
