"""Single-leader log shipping between apiserver replicas (ROADMAP 4b).

The durability layer (``bus/wal.py``) makes one ``vtpu-apiserver``
crash-safe; this module makes the bus *highly available*: N replicas,
one leader taking every write, followers applying the leader's WAL
records to their own durable stores and serving reads/watches locally.

Protocol (VBUS v5 request ops, follower → leader, pull-based):

* ``repl_append`` — long-poll for records after ``(seq, chain)``.  The
  leader verifies the follower's position against its retained record
  window by comparing the CRC chain value (each record's chain is
  ``crc32(record_bytes, prev_chain)``); a mismatch or an out-of-window
  cursor answers ``snapshot_needed`` instead of shipping a divergent
  suffix.  The request's ``after`` doubles as a cumulative ack.
* ``repl_snapshot`` — full store snapshot for bootstrap or resync.
* ``repl_commit`` — explicit ack after applying a batch: the follower
  reports its applied seq, the leader recomputes the commit point and
  returns it.  This is what makes quorum acks prompt instead of
  waiting for the next poll cycle.

Commit rule: a write is acknowledged only after the leader's WAL fsync
AND, with ``replica_count >= 2``, after a majority of replicas
(leader included) hold the record — ``commit_seq`` is the quorum-th
highest applied seq.  Watch notifications are withheld until the
commit point everywhere (leader and followers), so no watcher —
local or remote — ever observes an event a failover could roll back.
That is exactly what lets a client's watch cursor survive leader death:
committed seqs exist on a majority, the promotion rule picks the
most-advanced reachable survivor, and the epoch is replication-group-
wide, so ``resume_seq`` validates against the new leader and
``bus_relists_total`` stays flat.

Election: membership is the static ``--replicas`` endpoint list.  A
follower that loses its leader (pull failure persisting past the lease
TTL) probes every peer's ``bus_status``; it promotes itself only when
a majority of replicas is reachable and it is the most advanced —
ordered by ``(term, applied seq, -index)`` — otherwise it follows
whoever is.  Promotion bumps the persisted term; a deposed leader
rejoining sees the higher term and demotes.  No partition-tolerant
consensus is claimed (see the README's honest-gaps entry): below a
majority the group refuses promotion and writes stall rather than
risk acknowledged-write loss.

Write routing: a follower's BusServer proxies write ops (create /
update / update_status / delete / cas_bind / commit_batch / get) to
the leader over the manager's client connection — clients connected to
a follower keep working through it, while watches and lists are served
from the follower's local store.

Fault points: ``repl.drop`` (a shipment batch is dropped on the
leader — the follower re-pulls), ``repl.lag`` (injected apply latency
on the follower), ``bus.leader_kill`` (crash-stop the leader mid-
commit — wired through ``PersistentAPIServer.kill_hook``).
"""

from __future__ import annotations

import socket
import threading
import time
from typing import Dict, List, Optional

from volcano_tpu.bus import protocol
from volcano_tpu.bus.protocol import BusError
from volcano_tpu.bus.wal import PersistentAPIServer
from volcano_tpu.client.apiserver import ApiError
from volcano_tpu.metrics import metrics
from volcano_tpu.utils.logging import get_logger

log = get_logger(__name__)

#: records the leader retains in memory for follower catch-up; a
#: follower further behind than this re-syncs via repl_snapshot
_RETAIN = 4096

#: per-pull shipment cap (frames stay bounded like _WATCH_BATCH_MAX)
_PULL_MAX = 256


def quorum_of(replica_count: int) -> int:
    """Majority including the leader; 1 when the group is a singleton."""
    return replica_count // 2 + 1 if replica_count >= 2 else 1


def candidate_rank(term: int, seq: int, index: int):
    """Election ordering: most advanced by (term, applied seq), lowest
    index on ties.  A named function rather than an inline tuple so the
    interleaving explorer's election model (analysis/explore.py) ranks
    with the PRODUCTION comparator — the model cannot drift from the
    implementation."""
    return (term, seq, -index)


def leader_rank(term: int, commit_seq: int, index: int):
    """Dual-leader resolution ordering: a higher term always wins; an
    EQUAL term resolves by COMMIT seq first (only one same-term leader
    can hold a quorum, and deposing it by mere index would erase
    majority-committed writes — the rolling-kill soak's catch), index
    second.  Shared with the explorer like :func:`candidate_rank`."""
    return (term, commit_seq, -index)


class ReplicationCoordinator:
    """Leader-side record outbox + quorum tracking.

    ``leader_append`` is called by the store's commit path (under the
    store lock); ``pull``/``ack`` are called from bus request-handler
    threads serving followers and touch only this object's condition
    lock — the store lock is never needed here, so a leader parked in
    ``wait_commit`` cannot deadlock the acks that will release it."""

    def __init__(self, replica_count: int, identity: str,
                 base_seq: int, base_chain: int,
                 commit_timeout: float = 10.0):
        self.replica_count = replica_count
        self.identity = identity
        self.commit_timeout = commit_timeout
        self._cv = threading.Condition()
        #: retained tail: {"seq", "term", "chain", "payload", "ts"} —
        #: seq is the LAST event seq the record produced
        self._records: List[dict] = []  # guarded-by: self._cv
        self._base_seq = base_seq  # guarded-by: self._cv
        self._base_chain = base_chain  # guarded-by: self._cv
        self._last_seq = base_seq  # guarded-by: self._cv
        self._last_ts = 0.0  # guarded-by: self._cv
        self._commit_seq = base_seq  # guarded-by: self._cv
        #: follower id → {"acked": seq, "seen": monotonic ts}
        self._followers: Dict[str, dict] = {}  # guarded-by: self._cv
        #: set by shutdown(): in-flight commit waits abort immediately
        #: (a stopping or deposed leader must not park writers — and
        #: must not park its own store lock — for the full timeout)
        self._dead = False  # guarded-by: self._cv
        #: late-commit notify hook (store.flush_committed).  Invoked
        #: ONLY from the dedicated flusher thread below — never from an
        #: ack request thread: the hook takes the store lock, and an
        #: ack thread starving behind a stream of committers would
        #: stall the follower waiting on its repl_commit response,
        #: which stalls the quorum, which wedges the leader (observed
        #: as a whole-group stall under loadgen before this existed).
        self._on_commit = None
        self._flusher: Optional[threading.Thread] = None

    def start_flusher(self, on_commit) -> None:
        """Install the late-commit flush hook on its own thread.  The
        normal path needs no flush here — a committing writer delivers
        its own notifications after ``wait_commit`` — so this thread
        only picks up commits whose writer timed out (or follower-side
        gaps), and its lock waits block nobody."""
        self._on_commit = on_commit
        self._flusher = threading.Thread(
            target=self._flush_loop,
            name=f"vtpu-repl-flush-{self.identity}", daemon=True,
        )
        self._flusher.start()

    def _flush_loop(self) -> None:
        last = 0
        while True:
            with self._cv:
                while not self._dead and self._commit_seq <= last:
                    self._cv.wait(1.0)
                if self._dead:
                    return
                commit = self._commit_seq
            self._on_commit(commit)
            last = commit

    # ---- leader write path (store lock held by the caller) ----

    def leader_append(self, seq: int, term: int, chain: int,
                      payload: bytes, ts: float) -> None:
        with self._cv:
            self._records.append({
                "seq": seq, "term": term, "chain": chain,
                "payload": payload, "ts": ts,
            })
            if len(self._records) > _RETAIN:
                dropped = self._records.pop(0)
                self._base_seq = dropped["seq"]
                self._base_chain = dropped["chain"]
            self._last_seq = seq
            self._last_ts = ts
            self._recompute_commit()
            self._cv.notify_all()

    def wait_commit(self, seq: int, timeout: Optional[float] = None) -> bool:
        timeout = self.commit_timeout if timeout is None else timeout
        deadline = time.monotonic() + timeout
        with self._cv:
            while self._commit_seq < seq:
                if self._dead:
                    return False
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cv.wait(remaining)
            return True

    def shutdown(self) -> None:
        """Abort every parked commit wait (leader stopping or deposed)."""
        with self._cv:
            self._dead = True
            self._cv.notify_all()

    def _recompute_commit(self) -> None:
        # requires-lock: self._cv
        acked = sorted(
            [self._last_seq] + [f["acked"] for f in self._followers.values()],
            reverse=True,
        )
        k = quorum_of(self.replica_count)
        if len(acked) >= k:
            new_commit = acked[k - 1]
            if new_commit > self._commit_seq:
                self._commit_seq = new_commit

    # ---- follower-facing ops (request-handler threads) ----

    def ack(self, follower_id: str, acked_seq: int) -> int:
        """Record a follower's applied seq; returns the commit point."""
        with self._cv:
            entry = self._followers.setdefault(
                follower_id, {"acked": 0, "seen": 0.0}
            )
            if acked_seq > entry["acked"]:
                entry["acked"] = acked_seq
            entry["seen"] = time.monotonic()
            self._recompute_commit()
            commit = self._commit_seq
            self._cv.notify_all()  # wakes parked writers AND the flusher
        return commit

    def pull(self, follower_id: str, after_seq: int, after_chain: int,
             wait_s: float, max_records: int = _PULL_MAX) -> dict:
        """One ``repl_append`` long-poll.  The cursor doubles as an ack."""
        from volcano_tpu import faults

        deadline = time.monotonic() + max(0.0, min(wait_s, 30.0))
        with self._cv:
            entry = self._followers.setdefault(
                follower_id, {"acked": 0, "seen": 0.0}
            )
            if after_seq > entry["acked"]:
                entry["acked"] = after_seq
            entry["seen"] = time.monotonic()
            self._recompute_commit()
            self._cv.notify_all()
            # cursor validation against the retained window + CRC chain:
            # behind the window, AHEAD of the leader (a divergent
            # uncommitted suffix from a dead term), or a chain mismatch
            # all mean the follower's log is not a prefix of ours —
            # re-sync via snapshot instead of shipping a wrong suffix
            if after_seq < self._base_seq or after_seq > self._last_seq:
                return {"snapshot_needed": True}
            expected = self._chain_at(after_seq)
            if expected is None or expected != after_chain:
                return {"snapshot_needed": True}
            while self._last_seq <= after_seq:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._cv.wait(remaining)
            records = [
                r for r in self._records if r["seq"] > after_seq
            ][:max_records]
            commit = self._commit_seq
            last_seq = self._last_seq
        fp = faults.get_plane()
        if fp.enabled and records and fp.should("repl.drop"):
            # the shipment is lost on the wire — the follower's next
            # poll re-requests the same suffix (pure retransmission
            # latency, never a gap: the cursor did not advance)
            records = []
        return {
            "records": [
                {"payload": r["payload"].decode(), "seq": r["seq"],
                 "term": r["term"], "chain": r["chain"]}
                for r in records
            ],
            "commit_seq": commit,
            "leader_seq": last_seq,
        }

    def _chain_at(self, seq: int) -> Optional[int]:
        # requires-lock: self._cv
        if seq == self._base_seq:
            return self._base_chain
        for r in self._records:
            if r["seq"] == seq:
                return r["chain"]
        return None

    def commit_seq(self) -> int:
        with self._cv:
            return self._commit_seq

    def follower_lags(self) -> Dict[str, dict]:
        """Per-follower replication lag, entries + ms, derived purely
        from stored state (no call-time clock) so ``vtctl bus status``
        renders byte-identically across backends."""
        with self._cv:
            out = {}
            for fid, f in self._followers.items():
                lag_entries = max(0, self._last_seq - f["acked"])
                lag_ms = 0.0
                if lag_entries:
                    acked_ts = self._base_ts_for(f["acked"])
                    if acked_ts is not None and self._last_ts:
                        lag_ms = round(
                            max(0.0, (self._last_ts - acked_ts) * 1e3), 1
                        )
                out[fid] = {
                    "acked_seq": f["acked"],
                    "lag_entries": lag_entries,
                    "lag_ms": lag_ms,
                }
            return out

    def _base_ts_for(self, acked_seq: int) -> Optional[float]:
        # requires-lock: self._cv
        for r in self._records:
            if r["seq"] > acked_seq:
                return r["ts"]
        return None

    def max_lag_entries(self) -> int:
        with self._cv:
            if not self._followers:
                return 0
            return max(
                max(0, self._last_seq - f["acked"])
                for f in self._followers.values()
            )


def probe_status(url: str, timeout: float = 1.5) -> Optional[dict]:
    """One-shot ``bus_status`` against a bare endpoint — the election
    probe.  Returns None when the peer is unreachable or too old to
    answer (an ``unknown bus op`` peer cannot be a v5 replica).  The
    timeout is generous relative to the probe's cost (~1 RTT + a
    status render): a loaded-but-alive peer that misses the window
    reads as dead, and an election that keeps seeing phantom deaths
    refuses to promote (below-quorum) or promotes spuriously — both
    worse than a slower probe round."""
    try:
        host, port = protocol.parse_bus_url(url)
        with socket.create_connection((host, port), timeout=timeout) as sock:
            sock.settimeout(timeout)
            protocol.send_frame(sock, protocol.T_REQ, 1, {"op": "bus_status"})
            while True:
                mtype, corr_id, payload = protocol.recv_frame(sock)
                if mtype == protocol.T_RESP and corr_id == 1:
                    return payload
                if mtype == protocol.T_ERROR and corr_id == 1:
                    return None
    except (OSError, ValueError, ConnectionError):
        return None


class _RawClient:
    """Sequential request/response client for the pull loop — one
    in-flight call at a time, no reconnect magic (the manager owns
    failure handling and redials)."""

    def __init__(self, url: str, timeout: float = 10.0):
        host, port = protocol.parse_bus_url(url)
        self.sock = socket.create_connection((host, port), timeout=timeout)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.timeout = timeout
        self._req_id = 0

    def call(self, payload: dict, timeout: Optional[float] = None) -> dict:
        self._req_id += 1
        self.sock.settimeout(timeout if timeout is not None else self.timeout)
        protocol.send_frame(self.sock, protocol.T_REQ, self._req_id, payload)
        while True:
            mtype, corr_id, resp = protocol.recv_frame(self.sock)
            if corr_id != self._req_id:
                continue  # stray push frame (bookmark etc.) — not ours
            if mtype == protocol.T_RESP:
                return resp
            if mtype == protocol.T_ERROR:
                protocol.raise_error(resp)

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


class ReplicaManager:
    """Role state machine for one apiserver replica.

    Owns the election loop, the follower pull/apply loop, and the
    leader-side coordinator; the BusServer consults ``is_leader`` to
    route writes and delegates the ``repl_*``/proxy ops here."""

    def __init__(
        self,
        store: PersistentAPIServer,
        endpoints: List[str],
        index: int,
        lease_ttl: float = 2.0,
        identity: Optional[str] = None,
        on_became_leader=None,
    ):
        if not (0 <= index < len(endpoints)):
            raise ValueError(
                f"replica index {index} outside endpoint list "
                f"({len(endpoints)} entries)"
            )
        self.store = store
        self.endpoints = list(endpoints)
        self.index = index
        self.lease_ttl = lease_ttl
        self.identity = identity or f"apiserver-{index}"
        self.replica_count = len(endpoints)
        self.on_became_leader = on_became_leader

        self._lock = threading.Lock()
        self.role = "init"  # guarded-by: self._lock
        self.leader_url: Optional[str] = None  # guarded-by: self._lock
        self.coordinator: Optional[ReplicationCoordinator] = None  # guarded-by: self._lock
        self._proxy_client = None  # guarded-by: self._lock
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        metrics.update_repl_role("init")

    # ---- public surface ----

    @property
    def is_leader(self) -> bool:
        with self._lock:
            return self.role == "leader"

    def start(self) -> "ReplicaManager":
        self._thread = threading.Thread(
            target=self._run, name=f"vtpu-repl-{self.identity}", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        with self._lock:
            client = self._proxy_client
            self._proxy_client = None
            coord = self.coordinator
        if coord is not None:
            coord.shutdown()  # release writers parked on the quorum
        if client is not None:
            client.close()
        if self._thread is not None:
            self._thread.join(timeout=10)

    def proxy(self, payload: dict) -> dict:
        """Forward a write op from this follower to the leader; the
        response payload is relayed verbatim.  The ``proxied`` marker
        caps forwarding at one hop — a stale leader view answers with a
        typed error instead of bouncing the frame around the group."""
        with self._lock:
            client = self._proxy_client
            leader = self.leader_url
            role = self.role
        if client is None or leader is None:
            raise ApiError(
                "no leader elected — write cannot be routed "
                f"(replica {self.identity} is {role})"
            )
        if not client.wait_ready(0.0):
            # the leader link is down (death/election in progress):
            # FAIL FAST instead of parking the caller for the client's
            # full reconnect timeout — the caller's retry lands after
            # promotion replaces this proxy (loadgen's failover drill
            # caught the parked variant blowing the submit budget)
            raise ApiError(
                f"leader {leader} unreachable from {self.identity} — "
                "retry after the election settles"
            )
        fwd = dict(payload)
        fwd["proxied"] = True
        # bounded by the election timescale, not the generic client
        # timeout: a wedged leader should surface to the caller fast
        return client._call(  # noqa: SLF001 — same-package passthrough
            fwd, timeout=min(max(self.lease_ttl * 4, 2.0), 15.0)
        )

    def status(self) -> dict:
        """Replication fields merged into ``bus_status`` payloads."""
        with self._lock:
            out = {
                "role": self.role,
                "identity": self.identity,
                "index": self.index,
                "replicas": self.replica_count,
                "endpoints": list(self.endpoints),
                # a leader IS the group's leader — report its own
                # endpoint, not the (None) url it follows
                "leader": (
                    self.endpoints[self.index] if self.role == "leader"
                    else self.leader_url
                ),
                "quorum": quorum_of(self.replica_count),
            }
            coord = self.coordinator
        if coord is not None:
            out["followers"] = coord.follower_lags()
            out["commit_seq"] = coord.commit_seq()
        return out

    # ---- leader-side op handlers (BusServer delegates here) ----

    def _coordinator_or_raise(self) -> ReplicationCoordinator:
        with self._lock:
            coord = self.coordinator
            if coord is None or self.role != "leader":
                raise ApiError(f"not leader ({self.role})")
            return coord

    def handle_append(self, payload: dict) -> dict:
        coord = self._coordinator_or_raise()
        resp = coord.pull(
            str(payload.get("id", "")),
            int(payload.get("after", 0)),
            int(payload.get("chain", 0)),
            float(payload.get("wait_s", 0.0)),
            int(payload.get("max", _PULL_MAX)),
        )
        resp["term"] = self.store.term
        resp["epoch"] = self.store.epoch
        return resp

    def handle_snapshot(self, payload: dict) -> dict:
        coord = self._coordinator_or_raise()
        snap = self.store.dump_snapshot()
        return {"snapshot": snap, "commit_seq": coord.commit_seq()}

    def handle_commit(self, payload: dict) -> dict:
        coord = self._coordinator_or_raise()
        commit = coord.ack(
            str(payload.get("id", "")), int(payload.get("applied", 0))
        )
        return {"commit_seq": commit, "leader_seq": self.store.event_seq}

    # ---- the role loop ----

    def _run(self) -> None:
        while not self._stop.is_set():
            with self._lock:
                role = self.role
            try:
                if role == "leader":
                    self._lead_tick()
                    self._stop.wait(self.lease_ttl / 2)
                else:
                    self._follow()
            except Exception as e:  # noqa: BLE001 — the loop must survive
                log.error("replica %s loop error: %s", self.identity, e)
                self._stop.wait(0.2)

    def _lead_tick(self) -> None:
        """Leader heartbeat: watch for a competing leader.  A higher
        term always wins (a deposed incarnation rejoining must step
        down, not split the brain).  An EQUAL term — two candidates
        that raced the same election — resolves by COMMIT seq first,
        index second: with three replicas only one same-term leader can
        hold a commit quorum, so the higher-commit leader is the one
        whose acknowledgements a majority actually stores — deposing it
        by mere index would erase majority-committed writes (the
        rolling-kill soak caught exactly that).  The loser's own writes
        are stalled-unacked (its quorum is gone), so ITS stepdown is
        loss-free."""
        with self._lock:
            coord = self.coordinator
        my_commit = coord.commit_seq() if coord is not None else 0
        mine = leader_rank(self.store.term, my_commit, self.index)
        for i, url in enumerate(self.endpoints):
            if i == self.index:
                continue
            st = probe_status(url)
            if st is None or st.get("role") != "leader":
                continue
            peer = leader_rank(
                int(st.get("term", 0)),
                int(st.get("commit_seq", 0)),
                int(st.get("index", len(self.endpoints))),
            )
            if peer > mine:
                log.error(
                    "replica %s: peer %s leads at (term,commit)=%s over "
                    "ours %s — stepping down",
                    self.identity, url, peer[:2], mine[:2],
                )
                self._become_follower(url)
                return
        with self._lock:
            coord = self.coordinator
        metrics.update_repl_lag(
            coord.max_lag_entries() if coord is not None else 0
        )

    def _become_follower(self, leader_url: Optional[str]) -> None:
        self.store.set_replication(None, read_only=True)
        with self._lock:
            self.role = "follower"
            coord = self.coordinator
            self.coordinator = None
            self._set_leader_locked(leader_url)
        if coord is not None:
            coord.shutdown()  # a deposed leader's parked writers abort
        metrics.update_repl_role("follower")

    def _set_leader_locked(self, leader_url: Optional[str]) -> None:
        # requires-lock: self._lock
        if leader_url == self.leader_url and self._proxy_client is not None:
            return
        old = self._proxy_client
        self._proxy_client = None
        self.leader_url = leader_url
        if old is not None:
            old.close()
        if leader_url is not None:
            from volcano_tpu.bus.remote import RemoteAPIServer

            self._proxy_client = RemoteAPIServer(leader_url, timeout=15.0)

    def _promote(self, term: int) -> None:
        self.store.set_term(term)
        coord = ReplicationCoordinator(
            self.replica_count, self.identity,
            base_seq=self.store.event_seq, base_chain=self.store.chain,
        )
        coord.start_flusher(self.store.flush_committed)
        # order matters: the store must see the coordinator before the
        # role flips to leader (the instant ``is_leader`` goes true the
        # BusServer routes writes locally, and an un-replicated write
        # acked without quorum would be exactly the loss this exists to
        # prevent); the store-lock-atomic install also serializes the
        # transition against in-flight transactions
        self.store.set_replication(coord, read_only=False)
        with self._lock:
            self.coordinator = coord
            self.role = "leader"
            self._set_leader_locked(None)
        metrics.update_repl_role("leader")
        log.info("replica %s promoted to leader (term %d, seq %d)",
                 self.identity, term, self.store.event_seq)
        if self.on_became_leader is not None:
            threading.Thread(
                target=self.on_became_leader,
                name=f"vtpu-repl-onlead-{self.identity}", daemon=True,
            ).start()

    def _elect(self) -> Optional[str]:
        """Probe the group; return the leader url to follow, or None
        after promoting ourselves.  Promotion requires a reachable
        majority and being the most advanced — ``(term, seq, -index)``
        — among it."""
        statuses: Dict[str, dict] = {}
        for i, url in enumerate(self.endpoints):
            if i == self.index:
                continue
            st = probe_status(url)
            if st is not None:
                statuses[url] = st
        # an existing leader wins immediately (highest (term, commit)
        # first, lowest index on ties — _lead_tick's exact tie-break,
        # so a racing dual-leadership resolves to the same winner from
        # every observer's seat)
        leaders = [
            leader_rank(
                int(st.get("term", 0)), int(st.get("commit_seq", 0)),
                int(st.get("index", len(self.endpoints))),
            ) + (url,)
            for url, st in statuses.items() if st.get("role") == "leader"
        ]
        if leaders:
            leaders.sort(reverse=True)
            return leaders[0][3]
        reachable = len(statuses) + 1  # + self
        if reachable < quorum_of(self.replica_count):
            log.warning(
                "replica %s: only %d/%d replicas reachable — refusing "
                "promotion below quorum", self.identity, reachable,
                self.replica_count,
            )
            return None
        mine = candidate_rank(self.store.term, self.store.event_seq,
                              self.index)
        best_peer = max(
            (
                candidate_rank(
                    int(st.get("term", 0)), int(st.get("seq", 0)),
                    int(st.get("index", len(self.endpoints))),
                )
                for st in statuses.values()
            ),
            default=None,
        )
        if best_peer is None or mine >= best_peer:
            if self.index > 0:
                # deterministic stagger: tied candidates promote
                # lowest-index first.  A probe snapshot can miss a peer
                # mid-promotion (two candidates racing the same
                # election), so the better-ranked replica gets a head
                # start proportional to rank, and we re-check for a
                # winner before claiming the term ourselves.
                self._stop.wait(min(self.lease_ttl * 0.25, 0.3) * self.index)
                if self._stop.is_set():
                    return None
                for i, url in enumerate(self.endpoints):
                    if i == self.index:
                        continue
                    st = probe_status(url)
                    if st is not None and st.get("role") == "leader":
                        return url
            max_term = max(
                [self.store.term]
                + [int(st.get("term", 0)) for st in statuses.values()]
            )
            self._promote(max_term + 1)
            return None
        return None  # a more advanced peer exists; let it promote

    def _follow(self) -> None:
        """One follower episode: find the leader, attach, pull until
        the stream breaks, then re-elect.  Leader death is detected by
        pull failure persisting past the lease TTL."""
        self.store.set_replication(None, read_only=True)
        metrics.update_repl_role("follower")
        leader = self._elect()
        if leader is None:
            if self.is_leader:
                return
            self._stop.wait(min(0.2, self.lease_ttl / 4))
            return
        self._become_follower(leader)
        raw: Optional[_RawClient] = None
        failing_since: Optional[float] = None
        try:
            raw = _RawClient(leader, timeout=max(10.0, self.lease_ttl * 3))
            while not self._stop.is_set():
                # every leader interaction shares the same failure
                # budget: transient blips redial inside the TTL window,
                # persistent failure past the TTL declares the leader
                # dead and re-elects.  (An early build let a failed
                # repl_commit crash the episode straight into an
                # election — a slow-but-alive leader then got deposed
                # by its own followers under load.)
                try:
                    resp = raw.call({
                        "op": "repl_append", "id": self.identity,
                        "after": self.store.event_seq,
                        "chain": self.store.chain,
                        "wait_s": self.lease_ttl / 2, "max": _PULL_MAX,
                    })
                    if resp.get("snapshot_needed"):
                        snap = raw.call(
                            {"op": "repl_snapshot"},
                            timeout=max(30.0, self.lease_ttl * 10),
                        )["snapshot"]
                        self.store.adopt_epoch(snap.get("epoch", ""))
                        self.store.install_snapshot(snap)
                        metrics.register_bus_recovery("snapshot")
                        failing_since = None
                        continue
                    records = resp.get("records", ())
                    commit = int(resp.get("commit_seq", 0))
                    if records:
                        self._apply_records(records)
                        ack = raw.call({
                            "op": "repl_commit", "id": self.identity,
                            "applied": self.store.event_seq,
                        })
                        commit = max(commit, int(ack.get("commit_seq", 0)))
                    failing_since = None
                except (BusError, ApiError, OSError, ConnectionError) as e:
                    now = time.monotonic()
                    if failing_since is None:
                        failing_since = now
                    if now - failing_since >= self.lease_ttl:
                        log.error(
                            "replica %s: leader %s unreachable past the "
                            "lease TTL (%s) — re-electing",
                            self.identity, leader, e,
                        )
                        return
                    # redial inside the TTL window (transient blip)
                    try:
                        raw.close()
                        raw = _RawClient(
                            leader, timeout=max(10.0, self.lease_ttl * 3)
                        )
                    except OSError:
                        self._stop.wait(min(0.1, self.lease_ttl / 8))
                    continue
                self.store.adopt_epoch(resp.get("epoch", ""))
                if int(resp.get("term", 0)) > self.store.term:
                    self.store.set_term(int(resp["term"]))
                self.store.flush_committed(commit)
                metrics.update_repl_lag(
                    max(0, int(resp.get("leader_seq", 0))
                        - self.store.event_seq)
                )
        finally:
            if raw is not None:
                raw.close()

    def _apply_records(self, records) -> None:
        from volcano_tpu import faults

        fp = faults.get_plane()
        last = len(records) - 1
        for i, rec in enumerate(records):
            if fp.enabled and fp.should("repl.lag"):
                time.sleep(fp.param_ms("repl.lag") / 1e3)
            # one fsync per shipped batch, not per record — the leader
            # already holds every record durable, so batch-tail fsync
            # loses nothing a leader failure wouldn't re-ship
            self.store.apply_replica_record(
                rec["payload"].encode(), sync=(i == last)
            )
