"""Single-leader log shipping between apiserver replicas (ROADMAP 4b).

The durability layer (``bus/wal.py``) makes one ``vtpu-apiserver``
crash-safe; this module makes the bus *highly available*: N replicas,
one leader taking every write, followers applying the leader's WAL
records to their own durable stores and serving reads/watches locally.

Protocol (VBUS v5 request ops, follower → leader, pull-based):

* ``repl_append`` — long-poll for records after ``(seq, chain)``.  The
  leader verifies the follower's position against its retained record
  window by comparing the CRC chain value (each record's chain is
  ``crc32(record_bytes, prev_chain)``); a mismatch or an out-of-window
  cursor answers ``snapshot_needed`` instead of shipping a divergent
  suffix.  The request's ``after`` doubles as a cumulative ack.
* ``repl_snapshot`` — full store snapshot for bootstrap or resync.
* ``repl_commit`` — explicit ack after applying a batch: the follower
  reports its applied seq, the leader recomputes the commit point and
  returns it.  This is what makes quorum acks prompt instead of
  waiting for the next poll cycle.

Commit rule: a write is acknowledged only after the leader's WAL fsync
AND, with ``replica_count >= 2``, after a majority of replicas
(leader included) hold the record — ``commit_seq`` is the quorum-th
highest applied seq.  Watch notifications are withheld until the
commit point everywhere (leader and followers), so no watcher —
local or remote — ever observes an event a failover could roll back.
That is exactly what lets a client's watch cursor survive leader death:
committed seqs exist on a majority, the promotion rule picks the
most-advanced reachable survivor, and the epoch is replication-group-
wide, so ``resume_seq`` validates against the new leader and
``bus_relists_total`` stays flat.

Election: membership is the static ``--replicas`` endpoint list.  A
follower that loses its leader (pull failure persisting past the lease
TTL) probes every peer's ``bus_status``; it promotes itself only when
a majority of replicas is reachable and it is the most advanced —
ordered by ``(term, applied seq, -index)`` — otherwise it follows
whoever is.  Promotion bumps the persisted term; a deposed leader
rejoining sees the higher term and demotes.  No partition-tolerant
consensus is claimed (see the README's honest-gaps entry): below a
majority the group refuses promotion and writes stall rather than
risk acknowledged-write loss.

Write routing: a follower's BusServer proxies write ops (create /
update / update_status / delete / cas_bind / commit_batch / get) to
the leader over the manager's client connection — clients connected to
a follower keep working through it, while watches and lists are served
from the follower's local store.

Fault points: ``repl.drop`` (a shipment batch is dropped on the
leader — the follower re-pulls), ``repl.lag`` (injected apply latency
on the follower), ``bus.leader_kill`` (crash-stop the leader mid-
commit — wired through ``PersistentAPIServer.kill_hook``).
"""

from __future__ import annotations

import base64
import socket
import threading
import time
from typing import Dict, List, Optional

from volcano_tpu.bus import protocol
from volcano_tpu.bus.protocol import BusError, NotLeaderError
from volcano_tpu.bus.wal import PersistentAPIServer
from volcano_tpu.client.apiserver import ApiError
from volcano_tpu.metrics import metrics
from volcano_tpu.utils.logging import get_logger

log = get_logger(__name__)

#: records the leader retains in memory for follower catch-up; a
#: follower further behind than this re-syncs via repl_snapshot
_RETAIN = 4096

#: per-pull shipment cap (frames stay bounded like _WATCH_BATCH_MAX)
_PULL_MAX = 256


def _ship_record(r: dict, codec: str) -> dict:
    """One record of a ``repl_append`` response.  The CRC chain covers
    the canonical payload BYTES, so the follower must store a
    byte-identical copy: binary conns carry the raw bytes verbatim
    (msgpack bin — the zero-copy path); JSON conns carry the exact
    source string for JSON payloads (the v7 wire shape, so old
    followers keep working) and base64 for msgpack payloads, which
    JSON cannot hold losslessly."""
    out = {"seq": r["seq"], "term": r["term"], "chain": r["chain"]}
    payload = r["payload"]
    if codec == protocol.CODEC_BINARY:
        out["payload"] = payload
    elif payload[:1] == b"{":
        out["payload"] = payload.decode()
    else:
        out["payload"] = base64.b64encode(payload).decode()
        out["b64"] = True
    return out


def _shipped_payload(rec: dict) -> bytes:
    """Inverse of :func:`_ship_record` — the exact leader bytes."""
    payload = rec["payload"]
    if isinstance(payload, (bytes, bytearray)):
        return bytes(payload)
    if rec.get("b64"):
        return base64.b64decode(payload)
    return payload.encode()


def quorum_of(replica_count: int) -> int:
    """Majority including the leader; 1 when the group is a singleton."""
    return replica_count // 2 + 1 if replica_count >= 2 else 1


def proxy_timeout(op: str, lease_ttl: float) -> float:
    """Per-hop budget for a follower forwarding ``op`` to the leader.
    Ordinary writes are bounded by the election timescale, not the
    generic client timeout — a wedged leader should surface to the
    caller fast.  The v7 membership ops are the exception: the leader
    legitimately runs them for tens of seconds (learner catch-up wait,
    config-commit quorum wait), and a 4s hop cap made a proxied
    ``vtctl bus add-replica`` time out while the change went on to
    COMMIT at the leader — the operator's retry then read "already in
    flight"/"already a member" as a hard failure.  Matches the remote
    client's own 30s membership budget."""
    if op in ("bus_add_replica", "bus_remove_replica"):
        return 30.0
    return min(max(lease_ttl * 4, 2.0), 15.0)


def candidate_rank(term: int, seq: int, index: int):
    """Election ordering: most advanced by (term, applied seq), lowest
    index on ties.  A named function rather than an inline tuple so the
    interleaving explorer's election model (analysis/explore.py) ranks
    with the PRODUCTION comparator — the model cannot drift from the
    implementation."""
    return (term, seq, -index)


def leader_rank(term: int, commit_seq: int, index: int):
    """Dual-leader resolution ordering: a higher term always wins; an
    EQUAL term resolves by COMMIT seq first (only one same-term leader
    can hold a quorum, and deposing it by mere index would erase
    majority-committed writes — the rolling-kill soak's catch), index
    second.  Shared with the explorer like :func:`candidate_rank`."""
    return (term, commit_seq, -index)


class ReplicationCoordinator:
    """Leader-side record outbox + quorum tracking.

    ``leader_append`` is called by the store's commit path (under the
    store lock); ``pull``/``ack`` are called from bus request-handler
    threads serving followers and touch only this object's condition
    lock — the store lock is never needed here, so a leader parked in
    ``wait_commit`` cannot deadlock the acks that will release it."""

    def __init__(self, replica_count: int, identity: str,
                 base_seq: int, base_chain: int,
                 commit_timeout: float = 10.0):
        self.replica_count = replica_count  # guarded-by: self._cv
        self.identity = identity
        self.commit_timeout = commit_timeout
        self._cv = threading.Condition()
        #: voter endpoint urls, or None for a static group where every
        #: attached follower votes.  With dynamic membership a catching-
        #: up joiner attaches and pulls BEFORE it is admitted — its acks
        #: must not substitute for a voter's in the quorum count, or a
        #: leader + learner could "commit" a record no voting majority
        #: holds (exactly the acked-write loss a failover then realizes)
        self._voters: Optional[set] = None  # guarded-by: self._cv
        #: retained tail: {"seq", "term", "chain", "payload", "ts",
        #: "config"} — seq is the LAST event seq the record produced
        self._records: List[dict] = []  # guarded-by: self._cv
        self._base_seq = base_seq  # guarded-by: self._cv
        self._base_chain = base_chain  # guarded-by: self._cv
        self._last_seq = base_seq  # guarded-by: self._cv
        self._last_ts = 0.0  # guarded-by: self._cv
        self._commit_seq = base_seq  # guarded-by: self._cv
        #: follower id → {"acked": seq, "seen": monotonic ts}
        self._followers: Dict[str, dict] = {}  # guarded-by: self._cv
        #: set by shutdown(): in-flight commit waits abort immediately
        #: (a stopping or deposed leader must not park writers — and
        #: must not park its own store lock — for the full timeout)
        self._dead = False  # guarded-by: self._cv
        #: late-commit notify hook (store.flush_committed).  Invoked
        #: ONLY from the dedicated flusher thread below — never from an
        #: ack request thread: the hook takes the store lock, and an
        #: ack thread starving behind a stream of committers would
        #: stall the follower waiting on its repl_commit response,
        #: which stalls the quorum, which wedges the leader (observed
        #: as a whole-group stall under loadgen before this existed).
        self._on_commit = None
        self._flusher: Optional[threading.Thread] = None

    def start_flusher(self, on_commit) -> None:
        """Install the late-commit flush hook on its own thread.  The
        normal path needs no flush here — a committing writer delivers
        its own notifications after ``wait_commit`` — so this thread
        only picks up commits whose writer timed out (or follower-side
        gaps), and its lock waits block nobody."""
        self._on_commit = on_commit
        self._flusher = threading.Thread(
            target=self._flush_loop,
            name=f"vtpu-repl-flush-{self.identity}", daemon=True,
        )
        self._flusher.start()

    def _flush_loop(self) -> None:
        last = 0
        while True:
            with self._cv:
                while not self._dead and self._commit_seq <= last:
                    self._cv.wait(1.0)
                if self._dead:
                    return
                commit = self._commit_seq
            self._on_commit(commit)
            last = commit

    # ---- leader write path (store lock held by the caller) ----

    def leader_append(self, seq: int, term: int, chain: int,
                      payload: bytes, ts: float,
                      config: bool = False) -> None:
        with self._cv:
            self._records.append({
                "seq": seq, "term": term, "chain": chain,
                "payload": payload, "ts": ts, "config": config,
            })
            if len(self._records) > _RETAIN:
                dropped = self._records.pop(0)
                self._base_seq = dropped["seq"]
                self._base_chain = dropped["chain"]
            self._last_seq = seq
            self._last_ts = ts
            self._recompute_commit()
            self._cv.notify_all()

    def commit_seq(self) -> int:
        """The current quorum commit point (the membership latch's
        resolution read)."""
        with self._cv:
            return self._commit_seq

    def wait_commit(self, seq: int, timeout: Optional[float] = None) -> bool:
        timeout = self.commit_timeout if timeout is None else timeout
        deadline = time.monotonic() + timeout
        with self._cv:
            while self._commit_seq < seq:
                if self._dead:
                    return False
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cv.wait(remaining)
            return True

    def shutdown(self) -> None:
        """Abort every parked commit wait (leader stopping or deposed)."""
        with self._cv:
            self._dead = True
            self._cv.notify_all()

    def set_group(self, replica_count: int, voter_urls) -> None:
        """Adopt a membership config: the quorum divisor AND the voter
        filter change together, atomically under the condition lock —
        a commit recomputed between the two could count a learner (or
        a just-removed member) against the new divisor."""
        with self._cv:
            self.replica_count = replica_count
            self._voters = set(voter_urls) if voter_urls is not None else None
            self._recompute_commit()
            self._cv.notify_all()

    def _recompute_commit(self) -> None:
        # requires-lock: self._cv
        acked = sorted(
            [self._last_seq]
            + [
                f["acked"] for f in self._followers.values()
                # voter filter: only a follower whose KNOWN url is a
                # known non-member (a learner catching up, a removed
                # member still pulling) is excluded.  A follower that
                # never reported a url — a pre-v7 peer mid rolling
                # upgrade — VOTES: every v7 joiner always sends its
                # url, so url-less can only be old peers, and
                # excluding them would wedge the quorum for the whole
                # upgrade ("version skew costs a feature, never
                # correctness")
                if (
                    self._voters is None
                    or not f.get("url", "")
                    or f["url"] in self._voters
                )
            ],
            reverse=True,
        )
        k = quorum_of(self.replica_count)
        if len(acked) >= k:
            new_commit = acked[k - 1]
            if new_commit > self._commit_seq:
                self._commit_seq = new_commit

    # ---- follower-facing ops (request-handler threads) ----

    def _follower_entry(self, follower_id: str, url: str) -> dict:
        # requires-lock: self._cv
        entry = self._followers.setdefault(
            follower_id,
            {"acked": 0, "seen": 0.0, "url": "",
             "codec": protocol.CODEC_JSON},
        )
        if url:
            entry["url"] = url
        return entry

    def ack(self, follower_id: str, acked_seq: int, url: str = "") -> int:
        """Record a follower's applied seq; returns the commit point."""
        with self._cv:
            entry = self._follower_entry(follower_id, url)
            if acked_seq > entry["acked"]:
                entry["acked"] = acked_seq
            entry["seen"] = time.monotonic()
            self._recompute_commit()
            commit = self._commit_seq
            self._cv.notify_all()  # wakes parked writers AND the flusher
        return commit

    def catch_up_lag(self, url: str) -> Optional[int]:
        """A joiner's replication deficit in entries, or None when no
        attached follower reports that url — the add-replica catch-up
        gate reads it (a new replica bootstraps via ``repl_snapshot``
        and must close the gap BEFORE it counts toward quorum)."""
        with self._cv:
            for f in self._followers.values():
                if f.get("url") == url:
                    return max(0, self._last_seq - f["acked"])
            return None

    def pull(self, follower_id: str, after_seq: int, after_chain: int,
             wait_s: float, max_records: int = _PULL_MAX,
             url: str = "",
             codec: str = protocol.CODEC_JSON) -> dict:
        """One ``repl_append`` long-poll.  The cursor doubles as an ack."""
        from volcano_tpu import faults

        deadline = time.monotonic() + max(0.0, min(wait_s, 30.0))
        with self._cv:
            entry = self._follower_entry(follower_id, url)
            entry["codec"] = codec
            if after_seq > entry["acked"]:
                entry["acked"] = after_seq
            entry["seen"] = time.monotonic()
            self._recompute_commit()
            self._cv.notify_all()
            # cursor validation against the retained window + CRC chain:
            # behind the window, AHEAD of the leader (a divergent
            # uncommitted suffix from a dead term), or a chain mismatch
            # all mean the follower's log is not a prefix of ours —
            # re-sync via snapshot instead of shipping a wrong suffix
            if after_seq < self._base_seq or after_seq > self._last_seq:
                return {"snapshot_needed": True}
            expected = self._chain_at(after_seq)
            if expected is None or expected != after_chain:
                return {"snapshot_needed": True}
            while self._last_seq <= after_seq:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._cv.wait(remaining)
            records = [
                r for r in self._records if r["seq"] > after_seq
            ][:max_records]
            commit = self._commit_seq
            last_seq = self._last_seq
        fp = faults.get_plane()
        if fp.enabled and records and fp.should("repl.drop"):
            # the shipment is lost on the wire — the follower's next
            # poll re-requests the same suffix (pure retransmission
            # latency, never a gap: the cursor did not advance)
            records = []
        if (
            fp.enabled and records
            and any(r.get("config") for r in records)
            and fp.should("repl.config_drop")
        ):
            # the membership-change twin of repl.drop: a shipment
            # carrying a CONFIG record is lost — the chaos drills'
            # window for killing a leader whose config change some
            # followers hold and others do not
            records = []
        return {
            "records": [_ship_record(r, codec) for r in records],
            "commit_seq": commit,
            "leader_seq": last_seq,
        }

    def _chain_at(self, seq: int) -> Optional[int]:
        # requires-lock: self._cv
        if seq == self._base_seq:
            return self._base_chain
        for r in self._records:
            if r["seq"] == seq:
                return r["chain"]
        return None

    def commit_seq(self) -> int:
        with self._cv:
            return self._commit_seq

    def follower_lags(self) -> Dict[str, dict]:
        """Per-follower replication lag, entries + ms, derived purely
        from stored state (no call-time clock) so ``vtctl bus status``
        renders byte-identically across backends."""
        with self._cv:
            out = {}
            for fid, f in self._followers.items():
                lag_entries = max(0, self._last_seq - f["acked"])
                lag_ms = 0.0
                if lag_entries:
                    acked_ts = self._base_ts_for(f["acked"])
                    if acked_ts is not None and self._last_ts:
                        lag_ms = round(
                            max(0.0, (self._last_ts - acked_ts) * 1e3), 1
                        )
                out[fid] = {
                    "acked_seq": f["acked"],
                    "lag_entries": lag_entries,
                    "lag_ms": lag_ms,
                    "codec": f.get("codec", protocol.CODEC_JSON),
                }
            return out

    def _base_ts_for(self, acked_seq: int) -> Optional[float]:
        # requires-lock: self._cv
        for r in self._records:
            if r["seq"] > acked_seq:
                return r["ts"]
        return None

    def max_lag_entries(self) -> int:
        with self._cv:
            if not self._followers:
                return 0
            return max(
                max(0, self._last_seq - f["acked"])
                for f in self._followers.values()
            )

    def quorum_health(self, ttl: float) -> dict:
        """Leader-side health for ``/healthz``: live voters (seen within
        2×ttl, leader included), the quorum bar, and the worst live
        voter's lag in entries — the two degraded conditions
        (``below-quorum``, ``replica-lagging``) read straight off it."""
        with self._cv:
            now = time.monotonic()
            live = 1  # self
            max_lag = 0
            for f in self._followers.values():
                # learners/removed are not the quorum's health; an
                # url-less entry is a pre-v7 voter and counts — the
                # commit rule's exact filter
                if (
                    self._voters is not None
                    and f.get("url", "")
                    and f["url"] not in self._voters
                ):
                    continue
                if now - f["seen"] > ttl * 2:
                    continue
                live += 1
                max_lag = max(max_lag, self._last_seq - f["acked"])
            return {
                "live": live,
                "quorum": quorum_of(self.replica_count),
                "max_lag": max(max_lag, 0),
            }


def probe_status(url: str, timeout: float = 1.5) -> Optional[dict]:
    """One-shot ``bus_status`` against a bare endpoint — the election
    probe.  Returns None when the peer is unreachable or too old to
    answer (an ``unknown bus op`` peer cannot be a v5 replica).  The
    timeout is generous relative to the probe's cost (~1 RTT + a
    status render): a loaded-but-alive peer that misses the window
    reads as dead, and an election that keeps seeing phantom deaths
    refuses to promote (below-quorum) or promotes spuriously — both
    worse than a slower probe round."""
    try:
        host, port = protocol.parse_bus_url(url)
        with socket.create_connection((host, port), timeout=timeout) as sock:
            sock.settimeout(timeout)
            protocol.send_frame(sock, protocol.T_REQ, 1, {"op": "bus_status"})
            while True:
                mtype, corr_id, payload = protocol.recv_frame(sock)
                if mtype == protocol.T_RESP and corr_id == 1:
                    return payload
                if mtype == protocol.T_ERROR and corr_id == 1:
                    return None
    except (OSError, ValueError, ConnectionError):
        return None


def request_prevote(url: str, term: int, seq: int, index: int,
                    timeout: float = 1.5) -> bool:
    """One-shot ``repl_prevote`` against a peer: would it support this
    candidate's promotion?  ANY failure — unreachable, timeout, typed
    error, or a pre-v7 peer answering ``unknown bus op`` — counts as a
    DENIAL: pre-vote exists to stop spurious term bumps, so the safe
    degradation is fewer promotions, never more."""
    try:
        host, port = protocol.parse_bus_url(url)
        with socket.create_connection((host, port), timeout=timeout) as sock:
            sock.settimeout(timeout)
            protocol.send_frame(sock, protocol.T_REQ, 1, {
                "op": "repl_prevote",
                "term": term, "seq": seq, "index": index,
            })
            while True:
                mtype, corr_id, payload = protocol.recv_frame(sock)
                if mtype == protocol.T_RESP and corr_id == 1:
                    return bool(payload.get("granted"))
                if mtype == protocol.T_ERROR and corr_id == 1:
                    return False
    except (OSError, ValueError, ConnectionError):
        return False


class _UncommittedChange(ApiError):
    """A membership record was appended but its commit wait timed out.
    Carries the record's seq so the single-change latch stays held
    (tagged) instead of clearing — the record is in the log and will
    commit or be superseded; a second change must not stack on it."""

    def __init__(self, seq: int, message: str):
        super().__init__(message)
        self.seq = seq


class _RawClient:
    """Sequential request/response client for the pull loop — one
    in-flight call at a time, no reconnect magic (the manager owns
    failure handling and redials)."""

    def __init__(self, url: str, timeout: float = 10.0):
        host, port = protocol.parse_bus_url(url)
        self.sock = socket.create_connection((host, port), timeout=timeout)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.timeout = timeout
        self._req_id = 0
        self.codec = protocol.CODEC_JSON
        self._negotiate_codec()

    def _negotiate_codec(self) -> None:
        """Same ladder discipline as ``RemoteAPIServer``: offer binary,
        and on ANY non-binary answer — a v7 leader rejecting the op, a
        JSON-pinned leader, a connection blip mid-hello — degrade to
        JSON rather than error.  A blip leaves the socket for the pull
        loop's failure budget to judge."""
        if not protocol.HAS_BINARY:
            return
        try:
            resp = self.call({
                "op": "bus_hello",
                "codecs": [protocol.CODEC_BINARY, protocol.CODEC_JSON],
            })
        except ApiError as e:
            if not isinstance(e, BusError) and "unknown bus op" in str(e):
                metrics.register_bus_codec_fallback()
            return
        except OSError:
            return
        if resp.get("codec") == protocol.CODEC_BINARY:
            self.codec = protocol.CODEC_BINARY
        else:
            metrics.register_bus_codec_fallback()

    def call(self, payload: dict, timeout: Optional[float] = None) -> dict:
        self._req_id += 1
        self.sock.settimeout(timeout if timeout is not None else self.timeout)
        protocol.send_frame(self.sock, protocol.T_REQ, self._req_id, payload,
                            codec=self.codec)
        while True:
            mtype, corr_id, resp = protocol.recv_frame(self.sock)
            if corr_id != self._req_id:
                continue  # stray push frame (bookmark etc.) — not ours
            if mtype == protocol.T_RESP:
                return resp
            if mtype == protocol.T_ERROR:
                protocol.raise_error(resp)

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


class ReplicaManager:
    """Role state machine for one apiserver replica.

    Owns the election loop, the follower pull/apply loop, and the
    leader-side coordinator; the BusServer consults ``is_leader`` to
    route writes and delegates the ``repl_*``/proxy ops here."""

    def __init__(
        self,
        store: PersistentAPIServer,
        endpoints: List[str],
        index: int,
        lease_ttl: float = 2.0,
        identity: Optional[str] = None,
        on_became_leader=None,
    ):
        if not (0 <= index < len(endpoints)):
            raise ValueError(
                f"replica index {index} outside endpoint list "
                f"({len(endpoints)} entries)"
            )
        self.store = store
        self.endpoints = list(endpoints)
        self.index = index
        self.lease_ttl = lease_ttl
        self.identity = identity or f"apiserver-{index}"
        #: this replica's own bus endpoint — the STABLE identity under
        #: dynamic membership (index is just the position in the current
        #: config and moves as members come and go)
        self.url = self.endpoints[index]
        self.replica_count = len(endpoints)
        self.on_became_leader = on_became_leader

        self._lock = threading.Lock()
        self.role = "init"  # guarded-by: self._lock
        self.leader_url: Optional[str] = None  # guarded-by: self._lock
        self.coordinator: Optional[ReplicationCoordinator] = None  # guarded-by: self._lock
        self._proxy_client = None  # guarded-by: self._lock
        #: peers this replica cannot reach — the deterministic partition
        #: seam (tests call block_peer/unblock_peer; the chaos drills'
        #: seeded ``bus.partition`` fault point drops calls on top)
        self._blocked: set = set()  # guarded-by: self._lock
        #: monotonic stamp of the last PROVEN leader contact (a pull or
        #: commit round-trip that succeeded) — what a pre-vote grant is
        #: judged against: a peer that heard its leader within the TTL
        #: denies, so a partitioned rejoiner cannot scare up a term bump
        #: while the group is healthy
        self._leader_heard = 0.0  # guarded-by: self._lock
        #: single-change discipline: an in-flight add/remove refuses a
        #: second change until its config record commits
        self._change_inflight: Optional[str] = None  # guarded-by: self._lock
        #: seq of a change whose record was APPENDED but whose commit
        #: wait timed out — the latch stays held past the request (the
        #: record is in the log and WILL commit or be superseded by an
        #: elected log; a second change stacked on the uncommitted base
        #: is exactly what single-change membership forbids).  A later
        #: _begin_change resolves it against the commit point.
        self._change_pending_seq: Optional[int] = None  # guarded-by: self._lock
        #: epoch of the last membership config this manager adopted
        self._adopted_epoch = -1  # guarded-by: self._lock
        #: True once a config CONTAINING this replica was adopted —
        #: distinguishes "removed from the group" (stand down) from
        #: "never admitted yet" (keep following as a learner: that IS
        #: the add-replica catch-up phase)
        self._was_member = False  # guarded-by: self._lock
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        metrics.update_repl_role("init")

    # ---- public surface ----

    @property
    def is_leader(self) -> bool:
        with self._lock:
            return self.role == "leader"

    # ---- the partition seam ----

    def block_peer(self, url: str) -> None:
        """Deterministically partition this replica from ``url`` (every
        probe / pre-vote / pull toward it fails like a dropped link).
        The test seam behind the pre-vote partition-and-rejoin pin; the
        seeded ``bus.partition`` fault point layers probabilistic drops
        on top for chaos drills."""
        with self._lock:
            self._blocked.add(url)

    def unblock_peer(self, url: str) -> None:
        with self._lock:
            self._blocked.discard(url)

    def _link_ok(self, url: str) -> bool:
        from volcano_tpu import faults

        with self._lock:
            if url in self._blocked:
                return False
        fp = faults.get_plane()
        return not (fp.enabled and fp.should("bus.partition"))

    def _probe(self, url: str) -> Optional[dict]:
        """``probe_status`` through the partition seam."""
        if not self._link_ok(url):
            return None
        return probe_status(url)

    def start(self) -> "ReplicaManager":
        self._thread = threading.Thread(
            target=self._run, name=f"vtpu-repl-{self.identity}", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        with self._lock:
            client = self._proxy_client
            self._proxy_client = None
            coord = self.coordinator
        if coord is not None:
            coord.shutdown()  # release writers parked on the quorum
        if client is not None:
            client.close()
        if self._thread is not None:
            self._thread.join(timeout=10)

    def proxy(self, payload: dict) -> dict:
        """Forward a write op from this follower to the leader; the
        response payload is relayed verbatim.  The ``proxied`` marker
        caps forwarding at one hop — a stale leader view answers with a
        typed error instead of bouncing the frame around the group."""
        with self._lock:
            client = self._proxy_client
            leader = self.leader_url
            role = self.role
        if client is None or leader is None:
            raise NotLeaderError(
                "no leader elected — write cannot be routed "
                f"(replica {self.identity} is {role})"
            )
        if not client.wait_ready(0.0):
            # the leader link is down (death/election in progress):
            # FAIL FAST instead of parking the caller for the client's
            # full reconnect timeout — the caller's retry lands after
            # promotion replaces this proxy (loadgen's failover drill
            # caught the parked variant blowing the submit budget).
            # The hint names the leader WE know: the caller may well
            # reach it even though this follower's link is down.
            raise NotLeaderError(
                f"leader {leader} unreachable from {self.identity} — "
                "retry after the election settles, or dial the leader",
                leader=leader,
            )
        fwd = dict(payload)
        fwd["proxied"] = True
        return client._call(  # noqa: SLF001 — same-package passthrough
            fwd, timeout=proxy_timeout(str(payload.get("op", "")),
                                       self.lease_ttl)
        )

    def status(self) -> dict:
        """Replication fields merged into ``bus_status`` payloads."""
        with self._lock:
            out = {
                "role": self.role,
                "identity": self.identity,
                "index": self.index,
                "replicas": self.replica_count,
                "endpoints": list(self.endpoints),
                # a leader IS the group's leader — report its own
                # endpoint, not the (None) url it follows
                "leader": (
                    self.endpoints[self.index] if self.role == "leader"
                    else self.leader_url
                ),
                "quorum": quorum_of(self.replica_count),
            }
            coord = self.coordinator
        if coord is not None:
            out["followers"] = coord.follower_lags()
            out["commit_seq"] = coord.commit_seq()
        return out

    # ---- leader-side op handlers (BusServer delegates here) ----

    def _coordinator_or_raise(self) -> ReplicationCoordinator:
        with self._lock:
            coord = self.coordinator
            if coord is None or self.role != "leader":
                raise ApiError(f"not leader ({self.role})")
            return coord

    def handle_append(self, payload: dict,
                      codec: str = protocol.CODEC_JSON) -> dict:
        coord = self._coordinator_or_raise()
        resp = coord.pull(
            str(payload.get("id", "")),
            int(payload.get("after", 0)),
            int(payload.get("chain", 0)),
            float(payload.get("wait_s", 0.0)),
            int(payload.get("max", _PULL_MAX)),
            url=str(payload.get("url", "")),
            codec=codec,
        )
        resp["term"] = self.store.term
        resp["epoch"] = self.store.epoch
        return resp

    def handle_snapshot(self, payload: dict) -> dict:
        coord = self._coordinator_or_raise()
        snap = self.store.dump_snapshot()
        return {"snapshot": snap, "commit_seq": coord.commit_seq()}

    def handle_commit(self, payload: dict) -> dict:
        coord = self._coordinator_or_raise()
        commit = coord.ack(
            str(payload.get("id", "")), int(payload.get("applied", 0)),
            url=str(payload.get("url", "")),
        )
        return {"commit_seq": commit, "leader_seq": self.store.event_seq}

    def handle_prevote(self, payload: dict) -> dict:
        """Answer a candidate's pre-vote probe (VBUS v7).  Granted only
        when (a) this replica is not itself the leader, (b) it has NOT
        proven leader contact within the lease TTL, and (c) the
        candidate's log is at least as advanced — so a healthy group
        denies a partitioned rejoiner unanimously and the stable
        leader's term never moves.  Grants are stateless probes (no
        persisted vote): pre-vote prevents spurious term bumps, the
        real election's rank ordering still decides the winner."""
        with self._lock:
            role = self.role
            heard = (
                time.monotonic() - self._leader_heard
            ) < self.lease_ttl
        cand = candidate_rank(
            int(payload.get("term", 0)), int(payload.get("seq", 0)),
            int(payload.get("index", 0)),
        )
        mine = candidate_rank(self.store.term, self.store.event_seq,
                              self.index)
        granted = role != "leader" and not heard and cand >= mine
        return {"granted": granted, "term": self.store.term, "role": role}

    # ---- dynamic membership (leader-side ops, request threads) ----

    def _begin_change(self, what: str) -> None:
        with self._lock:
            if (
                self._change_inflight is not None
                and self._change_pending_seq is not None
            ):
                # a previous change appended its record but its commit
                # wait timed out — resolve against the commit point
                # now: committed since ⇒ the latch clears and this
                # change proceeds on the new base; still uncommitted ⇒
                # refuse (stacking a second change on an uncommitted
                # config is what single-change membership forbids)
                coord = self.coordinator
                if (
                    coord is not None
                    and coord.commit_seq() >= self._change_pending_seq
                ):
                    self._change_inflight = None
                    self._change_pending_seq = None
            if self._change_inflight is not None:
                raise ApiError(
                    f"membership change already in flight "
                    f"({self._change_inflight}) — one change at a time "
                    "(the single-server degenerate case of joint "
                    "consensus; a second change is refused until the "
                    "first commits)"
                )
            self._change_inflight = what

    def _end_change(self, pending_seq: Optional[int] = None) -> None:
        """Release the latch — unless ``pending_seq`` names a record
        still awaiting its commit, in which case the latch stays held
        (tagged with the seq) until a later ``_begin_change`` proves
        the commit point passed it."""
        with self._lock:
            if pending_seq is not None:
                self._change_pending_seq = pending_seq
                return
            self._change_inflight = None
            self._change_pending_seq = None

    def add_replica(self, url: str, catch_up_timeout: float = 10.0,
                    max_lag: int = 16) -> dict:
        """Admit ONE new replica.  The joiner must already be running
        (started with ``--replicas <old list>,<itself>``): it attaches
        as a non-voting learner, bootstraps through the existing
        ``repl_snapshot`` path, and only once its replication lag has
        closed to ``max_lag`` entries is the membership record logged —
        so a slow bootstrap can never stall the write quorum it is
        about to join."""
        url = url.strip()
        protocol.parse_bus_url(url)  # validate before touching state
        coord = self._coordinator_or_raise()
        self._begin_change(f"add {url}")
        try:
            cfg = self.store.membership_config() or {
                "epoch": 0, "endpoints": list(self.endpoints),
            }
            endpoints = [str(u) for u in cfg.get("endpoints", ())]
            if url in endpoints:
                raise ApiError(f"{url} is already a member")
            deadline = time.monotonic() + catch_up_timeout
            while True:
                lag = coord.catch_up_lag(url)
                if lag is not None and lag <= max_lag:
                    break
                if time.monotonic() >= deadline or self._stop.is_set():
                    raise ApiError(
                        f"new replica {url} never caught up "
                        f"(lag: {'not attached' if lag is None else lag})"
                        " — start it with --replicas listing the whole "
                        "new group (itself last) and retry"
                    )
                time.sleep(0.1)
            new_cfg = {
                "epoch": int(cfg.get("epoch", 0)) + 1,
                "endpoints": endpoints + [url],
            }
            result = self._commit_config(coord, new_cfg, f"add {url}")
        except _UncommittedChange as e:
            # appended but not committed: the latch stays HELD, tagged
            # with the record's seq — a later change request resolves
            # it against the commit point instead of stacking
            self._end_change(pending_seq=e.seq)
            raise
        except BaseException:
            self._end_change()
            raise
        self._end_change()
        return result

    def remove_replica(self, url: str) -> dict:
        """Retire ONE replica.  Refused when the remaining group could
        not commit (a reachable majority of the NEW config is required
        up front — shrinking must never wedge the quorum), and refused
        for the leader itself (kill it and let the group elect first;
        leadership transfer is honestly not implemented)."""
        url = url.strip()
        coord = self._coordinator_or_raise()
        if url == self.url:
            raise ApiError(
                "cannot remove the current leader — remove a follower, "
                "or kill this leader and remove it after the election"
            )
        self._begin_change(f"remove {url}")
        try:
            cfg = self.store.membership_config() or {
                "epoch": 0, "endpoints": list(self.endpoints),
            }
            endpoints = [str(u) for u in cfg.get("endpoints", ())]
            if url not in endpoints:
                raise ApiError(f"{url} is not a member")
            remaining = [u for u in endpoints if u != url]
            reachable = 1  # self
            for u in remaining:
                if u != self.url and self._probe(u) is not None:
                    reachable += 1
            if reachable < quorum_of(len(remaining)):
                raise ApiError(
                    f"removal refused: only {reachable}/{len(remaining)} "
                    "of the remaining group reachable — the shrunk "
                    "group could not commit a write (grow reachability "
                    "first, never the other way)"
                )
            new_cfg = {
                "epoch": int(cfg.get("epoch", 0)) + 1,
                "endpoints": remaining,
            }
            result = self._commit_config(coord, new_cfg, f"remove {url}")
        except _UncommittedChange as e:
            # same latch discipline as add_replica: appended-but-
            # uncommitted keeps the latch held, tagged with the seq
            self._end_change(pending_seq=e.seq)
            raise
        except BaseException:
            self._end_change()
            raise
        self._end_change()
        return result

    def _commit_config(self, coord: ReplicationCoordinator, cfg: dict,
                       what: str) -> dict:
        """Log one membership record and wait for its commit.  The
        config takes effect at APPEND (coordinator re-counts quorum
        under the new membership immediately), which is what keeps the
        one-change-at-a-time case safe: old and new majorities overlap,
        so two leaders of adjacent configs can never both commit."""
        from volcano_tpu import obs

        if obs.enabled():
            with obs.span("repl:membership", cat="repl",
                          args={"change": what,
                                "epoch": int(cfg.get("epoch", 0))}):
                return self._commit_config_inner(coord, cfg, what)
        return self._commit_config_inner(coord, cfg, what)

    def _commit_config_inner(self, coord: ReplicationCoordinator,
                             cfg: dict, what: str) -> dict:
        seq = self.store.log_membership(cfg)
        self._adopt_config(cfg)
        coord.set_group(len(cfg["endpoints"]), cfg["endpoints"])
        committed = coord.wait_commit(seq)
        if not committed:
            raise _UncommittedChange(
                seq,
                f"membership change ({what}) appended at seq {seq} but "
                "not yet committed — it completes when a quorum of the "
                "new config acks, or a newer elected log supersedes it; "
                "further changes are refused until it does",
            )
        log.info("replica %s: membership %s committed (epoch %d: %s)",
                 self.identity, what, cfg["epoch"], cfg["endpoints"])
        return {
            "committed": True, "seq": seq,
            "epoch": cfg["epoch"], "endpoints": list(cfg["endpoints"]),
        }

    def _adopt_config(self, cfg: dict) -> None:
        """Point this manager at a membership config (endpoints, own
        index, replica count).  Caller has verified self.url ∈ cfg."""
        with self._lock:
            self.endpoints = [str(u) for u in cfg["endpoints"]]
            self.index = self.endpoints.index(self.url)
            self.replica_count = len(self.endpoints)
            self._adopted_epoch = int(cfg.get("epoch", 0))

    def _adopt_membership(self) -> None:
        """Role-loop half of membership adoption: reconcile with the
        store's config (authoritative once seeded; ``--replicas`` only
        bootstraps).  A replica finding itself dropped from a config it
        was once part of stands down to ``removed``; one that was NEVER
        admitted keeps following as a learner (that is the catch-up
        phase ``add_replica`` gates on)."""
        cfg = self.store.membership_config()
        if cfg is None:
            return
        epoch = int(cfg.get("epoch", 0))
        with self._lock:
            if epoch <= self._adopted_epoch:
                return
            was_member = self._was_member
        endpoints = [str(u) for u in cfg.get("endpoints", ())]
        if not endpoints:
            return
        if self.url not in endpoints:
            with self._lock:
                self._adopted_epoch = epoch
            metrics.update_membership_epoch(epoch)
            if was_member:
                log.warning(
                    "replica %s (%s) removed at membership epoch %d — "
                    "standing down (restart the daemon to re-admit it)",
                    self.identity, self.url, epoch,
                )
                self._become_follower(None)
                with self._lock:
                    self.role = "removed"
                metrics.update_repl_role("removed")
            return
        self._adopt_config(cfg)
        with self._lock:
            self._was_member = True
            coord = self.coordinator
            if self.role == "removed":
                self.role = "init"  # re-admitted: rejoin via election
        metrics.update_membership_epoch(epoch)
        if coord is not None:
            coord.set_group(len(endpoints), endpoints)

    def _note_shipped_config(self) -> bool:
        """Reconcile membership after applying shipped state — WAL
        records or an installed snapshot, the same rule either way.
        A config listing this replica marks it admitted (recorded here,
        not just in ``_run``'s between-episode adoption pass: a
        follower that never leaves its first episode could otherwise
        not tell "removed" from "never admitted").  Returns True when a
        config dropped this replica from a group it was once part of —
        the caller ends the follow episode and ``_run``'s adoption pass
        stands it down to role ``removed``."""
        cfg = self.store.membership_config()
        if cfg is None:
            return False
        if self.url in cfg.get("endpoints", ()):
            with self._lock:
                self._was_member = True
            return False
        with self._lock:
            was_member = self._was_member
        if was_member:
            log.warning(
                "replica %s: shipped membership config no longer "
                "lists %s — leaving the follow loop",
                self.identity, self.url,
            )
            return True
        return False

    # ---- the role loop ----

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self._adopt_membership()
            except Exception as e:  # noqa: BLE001 — the loop must survive
                log.error("replica %s membership adoption error: %s",
                          self.identity, e)
            with self._lock:
                role = self.role
            try:
                if role == "removed":
                    # stood down: stay alive for reads/status, never
                    # pull or elect (a restart re-enters as a learner)
                    self._stop.wait(self.lease_ttl)
                elif role == "leader":
                    self._lead_tick()
                    self._stop.wait(self.lease_ttl / 2)
                else:
                    self._follow()
            except Exception as e:  # noqa: BLE001 — the loop must survive
                log.error("replica %s loop error: %s", self.identity, e)
                self._stop.wait(0.2)

    def _lead_tick(self) -> None:
        """Leader heartbeat: watch for a competing leader.  A higher
        term always wins (a deposed incarnation rejoining must step
        down, not split the brain).  An EQUAL term — two candidates
        that raced the same election — resolves by COMMIT seq first,
        index second: with three replicas only one same-term leader can
        hold a commit quorum, so the higher-commit leader is the one
        whose acknowledgements a majority actually stores — deposing it
        by mere index would erase majority-committed writes (the
        rolling-kill soak caught exactly that).  The loser's own writes
        are stalled-unacked (its quorum is gone), so ITS stepdown is
        loss-free."""
        with self._lock:
            coord = self.coordinator
        my_commit = coord.commit_seq() if coord is not None else 0
        mine = leader_rank(self.store.term, my_commit, self.index)
        for i, url in enumerate(self.endpoints):
            if i == self.index:
                continue
            st = self._probe(url)
            if st is None or st.get("role") != "leader":
                continue
            peer = leader_rank(
                int(st.get("term", 0)),
                int(st.get("commit_seq", 0)),
                int(st.get("index", len(self.endpoints))),
            )
            if peer > mine:
                log.error(
                    "replica %s: peer %s leads at (term,commit)=%s over "
                    "ours %s — stepping down",
                    self.identity, url, peer[:2], mine[:2],
                )
                self._become_follower(url)
                return
        with self._lock:
            coord = self.coordinator
        metrics.update_repl_lag(
            coord.max_lag_entries() if coord is not None else 0
        )

    def _become_follower(self, leader_url: Optional[str]) -> None:
        self.store.set_replication(None, read_only=True)
        with self._lock:
            self.role = "follower"
            coord = self.coordinator
            self.coordinator = None
            self._set_leader_locked(leader_url)
        if coord is not None:
            coord.shutdown()  # a deposed leader's parked writers abort
        metrics.update_repl_role("follower")

    def _set_leader_locked(self, leader_url: Optional[str]) -> None:
        # requires-lock: self._lock
        if leader_url == self.leader_url and self._proxy_client is not None:
            return
        old = self._proxy_client
        self._proxy_client = None
        self.leader_url = leader_url
        if old is not None:
            old.close()
        if leader_url is not None:
            from volcano_tpu.bus.remote import RemoteAPIServer

            self._proxy_client = RemoteAPIServer(leader_url, timeout=15.0)

    def _promote(self, term: int) -> None:
        self.store.set_term(term)
        coord = ReplicationCoordinator(
            self.replica_count, self.identity,
            base_seq=self.store.event_seq, base_chain=self.store.chain,
        )
        coord.start_flusher(self.store.flush_committed)
        # order matters: the store must see the coordinator before the
        # role flips to leader (the instant ``is_leader`` goes true the
        # BusServer routes writes locally, and an un-replicated write
        # acked without quorum would be exactly the loss this exists to
        # prevent); the store-lock-atomic install also serializes the
        # transition against in-flight transactions
        if self.store.membership_config() is not None:
            # a dynamic group: quorum counts VOTERS (the adopted
            # config), not whatever happens to be pulling
            coord.set_group(self.replica_count, list(self.endpoints))
        self.store.set_replication(coord, read_only=False)
        with self._lock:
            self.coordinator = coord
            self.role = "leader"
            self._was_member = True
            self._set_leader_locked(None)
        metrics.update_repl_role("leader")
        log.info("replica %s promoted to leader (term %d, seq %d)",
                 self.identity, term, self.store.event_seq)
        if self.store.membership_config() is None:
            # the group's FIRST leader seeds the membership config into
            # the log (one record, epoch 1, the static --replicas list)
            # so every later change is a replicated delta against a
            # recorded base — no quorum wait here: followers may not
            # have attached yet, and the record commits when they do
            try:
                self.store.log_membership({
                    "epoch": 1, "endpoints": list(self.endpoints),
                })
                coord.set_group(self.replica_count, list(self.endpoints))
                with self._lock:
                    self._adopted_epoch = 1
            except ApiError as e:
                log.error("membership seed failed (will stay static "
                          "until a change is requested): %s", e)
        if self.on_became_leader is not None:
            threading.Thread(
                target=self.on_became_leader,
                name=f"vtpu-repl-onlead-{self.identity}", daemon=True,
            ).start()

    def _elect(self) -> Optional[str]:
        """Probe the group; return the leader url to follow, or None
        after promoting ourselves.  Promotion requires a reachable
        majority and being the most advanced — ``(term, seq, -index)``
        — among it."""
        cfg = self.store.membership_config()
        if cfg is not None and self.url not in cfg.get("endpoints", ()):
            # this replica's own log says it is NOT a voting member
            # (a learner awaiting admission, or a removed replica
            # restarted with its stale --replicas list).  It must
            # never promote: a non-member winning an election — its
            # stale endpoint list can still see a probe majority —
            # would be a zombie leader outside the committed config.
            # Keep following; add-replica is the only way back in.
            log.info(
                "replica %s (%s): not in membership epoch %s — "
                "following only, never electing",
                self.identity, self.url, cfg.get("epoch"),
            )
            return None
        statuses: Dict[str, dict] = {}
        for i, url in enumerate(self.endpoints):
            if i == self.index:
                continue
            st = self._probe(url)
            if st is not None:
                statuses[url] = st
        # an existing leader wins immediately (highest (term, commit)
        # first, lowest index on ties — _lead_tick's exact tie-break,
        # so a racing dual-leadership resolves to the same winner from
        # every observer's seat)
        leaders = [
            leader_rank(
                int(st.get("term", 0)), int(st.get("commit_seq", 0)),
                int(st.get("index", len(self.endpoints))),
            ) + (url,)
            for url, st in statuses.items() if st.get("role") == "leader"
        ]
        if leaders:
            leaders.sort(reverse=True)
            return leaders[0][3]
        reachable = len(statuses) + 1  # + self
        if reachable < quorum_of(self.replica_count):
            log.warning(
                "replica %s: only %d/%d replicas reachable — refusing "
                "promotion below quorum", self.identity, reachable,
                self.replica_count,
            )
            return None
        mine = candidate_rank(self.store.term, self.store.event_seq,
                              self.index)
        best_peer = max(
            (
                candidate_rank(
                    int(st.get("term", 0)), int(st.get("seq", 0)),
                    int(st.get("index", len(self.endpoints))),
                )
                for st in statuses.values()
            ),
            default=None,
        )
        if best_peer is None or mine >= best_peer:
            if self.index > 0:
                # deterministic stagger: tied candidates promote
                # lowest-index first.  A probe snapshot can miss a peer
                # mid-promotion (two candidates racing the same
                # election), so the better-ranked replica gets a head
                # start proportional to rank, and we re-check for a
                # winner before claiming the term ourselves.
                self._stop.wait(min(self.lease_ttl * 0.25, 0.3) * self.index)
                if self._stop.is_set():
                    return None
                for i, url in enumerate(self.endpoints):
                    if i == self.index:
                        continue
                    st = self._probe(url)
                    if st is not None and st.get("role") == "leader":
                        return url
            # PRE-VOTE (the Raft §9.6 discipline): before touching the
            # term, ask every reachable peer whether it would support
            # this promotion.  A peer that heard from a live leader
            # within its TTL denies — so a rejoiner partitioned from
            # the leader but not from the followers (the asymmetric
            # case the majority floor above cannot catch) probes,
            # collects denials, and goes back to retrying WITHOUT
            # incrementing the term or deposing anyone.  Grants must
            # reach a majority counting ourselves; denials and
            # unreachable peers are equivalent (safety over liveness).
            grants = 1  # self
            for url in statuses:
                if not self._link_ok(url):
                    continue
                if request_prevote(
                    url, self.store.term, self.store.event_seq, self.index
                ):
                    grants += 1
            if grants < quorum_of(self.replica_count):
                log.warning(
                    "replica %s: pre-vote denied (%d/%d grants) — a live "
                    "leader is visible to the group; not promoting",
                    self.identity, grants, quorum_of(self.replica_count),
                )
                return None
            max_term = max(
                [self.store.term]
                + [int(st.get("term", 0)) for st in statuses.values()]
            )
            self._promote(max_term + 1)
            return None
        return None  # a more advanced peer exists; let it promote

    def _follow(self) -> None:
        """One follower episode: find the leader, attach, pull until
        the stream breaks, then re-elect.  Leader death is detected by
        pull failure persisting past the lease TTL."""
        self.store.set_replication(None, read_only=True)
        metrics.update_repl_role("follower")
        leader = self._elect()
        if leader is None:
            if self.is_leader:
                return
            self._stop.wait(min(0.2, self.lease_ttl / 4))
            return
        self._become_follower(leader)
        raw: Optional[_RawClient] = None
        failing_since: Optional[float] = None
        try:
            raw = _RawClient(leader, timeout=max(10.0, self.lease_ttl * 3))
            while not self._stop.is_set():
                # every leader interaction shares the same failure
                # budget: transient blips redial inside the TTL window,
                # persistent failure past the TTL declares the leader
                # dead and re-elects.  (An early build let a failed
                # repl_commit crash the episode straight into an
                # election — a slow-but-alive leader then got deposed
                # by its own followers under load.)
                try:
                    if not self._link_ok(leader):
                        # the partition seam: the link to the leader is
                        # down — burn the same failure budget a dropped
                        # TCP connection would
                        raise BusError("partitioned from leader")
                    resp = raw.call({
                        "op": "repl_append", "id": self.identity,
                        "url": self.url,
                        "after": self.store.event_seq,
                        "chain": self.store.chain,
                        "wait_s": self.lease_ttl / 2, "max": _PULL_MAX,
                    })
                    with self._lock:
                        # proven leader contact — what pre-vote denials
                        # are judged against
                        self._leader_heard = time.monotonic()
                    if resp.get("snapshot_needed"):
                        snap = raw.call(
                            {"op": "repl_snapshot"},
                            timeout=max(30.0, self.lease_ttl * 10),
                        )["snapshot"]
                        self.store.adopt_epoch(snap.get("epoch", ""))
                        self.store.install_snapshot(snap)
                        metrics.register_bus_recovery("snapshot")
                        failing_since = None
                        if self._note_shipped_config():
                            # a removal can arrive VIA SNAPSHOT too (a
                            # down member removed while its log
                            # diverged): on a write-idle group the
                            # records branch would never run again, so
                            # the stand-down must happen here
                            return
                        continue
                    records = resp.get("records", ())
                    commit = int(resp.get("commit_seq", 0))
                    if records:
                        self._apply_records(records)
                        ack = raw.call({
                            "op": "repl_commit", "id": self.identity,
                            "url": self.url,
                            "applied": self.store.event_seq,
                        })
                        commit = max(commit, int(ack.get("commit_seq", 0)))
                        if self._note_shipped_config():
                            return
                    failing_since = None
                except (BusError, ApiError, OSError, ConnectionError) as e:
                    now = time.monotonic()
                    if failing_since is None:
                        failing_since = now
                    if now - failing_since >= self.lease_ttl:
                        log.error(
                            "replica %s: leader %s unreachable past the "
                            "lease TTL (%s) — re-electing",
                            self.identity, leader, e,
                        )
                        # the leader is PROVABLY lost: clear the
                        # recorded view so proxies answer "no leader
                        # elected" and /healthz degrades to
                        # below-quorum while the election runs —
                        # keeping the dead url made the follower
                        # answer "ok" while every write stalled
                        with self._lock:
                            self._set_leader_locked(None)
                        return
                    # redial inside the TTL window (transient blip)
                    try:
                        raw.close()
                        raw = _RawClient(
                            leader, timeout=max(10.0, self.lease_ttl * 3)
                        )
                    except OSError:
                        self._stop.wait(min(0.1, self.lease_ttl / 8))
                    continue
                self.store.adopt_epoch(resp.get("epoch", ""))
                if int(resp.get("term", 0)) > self.store.term:
                    self.store.set_term(int(resp["term"]))
                self.store.flush_committed(commit)
                metrics.update_repl_lag(
                    max(0, int(resp.get("leader_seq", 0))
                        - self.store.event_seq)
                )
        finally:
            if raw is not None:
                raw.close()

    def _apply_records(self, records) -> None:
        from volcano_tpu import faults

        fp = faults.get_plane()
        last = len(records) - 1
        for i, rec in enumerate(records):
            if fp.enabled and fp.should("repl.lag"):
                time.sleep(fp.param_ms("repl.lag") / 1e3)
            # one fsync per shipped batch, not per record — the leader
            # already holds every record durable, so batch-tail fsync
            # loses nothing a leader failure wouldn't re-ship
            self.store.apply_replica_record(
                _shipped_payload(rec), sync=(i == last)
            )
