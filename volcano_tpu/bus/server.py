"""``BusServer`` — the network face of the in-process API server.

Wraps a ``client.apiserver.APIServer`` store behind the frame protocol
in ``bus.protocol`` over TCP, turning the single-process object store
into the deployable bus the reference architecture meets at
(cmd/scheduler/main.go:46, cmd/admission/app/server.go:37-99):

* **CRUD + list** proxy straight to the wrapped store, so semantics
  (optimistic concurrency, owner-reference cascade, admission chain)
  are exactly the in-process ones.
* **Watch streams**: every store mutation is stamped with a global bus
  sequence number and retained in a bounded backlog.  A watch request
  carrying ``(epoch, resume_seq)`` replays the missed suffix when the
  backlog still covers it; otherwise the server answers
  ``resumed: false`` — the 410-Gone "relist required" of the k8s
  watch API — and the client re-lists.  Periodic bookmarks advance the
  client's resume point through quiet periods.
* **Remote admission**: a connection may register as the webhook for a
  (kind, operation); the server forwards CREATE/UPDATE objects to it as
  admission-review frames and waits for the verdict before touching the
  store — the out-of-process equivalent of the reference's webhook
  configurations.  Reviews run *before* the store transaction (exactly
  the k8s ordering), so a webhook that calls back into the bus cannot
  deadlock on the store lock.

Event fan-out happens under the store lock (the store's own ``_notify``
discipline), which gives every subscriber one total order; delivery is
decoupled through per-connection outbound queues so a slow or dead peer
can never stall the store — it overflows its queue and is disconnected,
after which it resyncs via resume-or-relist.
"""

from __future__ import annotations

import queue
import socket
import threading
import time
import uuid
from typing import Dict, List, Optional, Tuple

from volcano_tpu import trace
from volcano_tpu.bus import protocol
from volcano_tpu.client.apiserver import AdmissionError, ApiError, APIServer
from volcano_tpu.metrics import metrics
from volcano_tpu.utils.logging import get_logger

log = get_logger(__name__)

#: outbound frames buffered per connection before the peer is declared
#: too slow and disconnected (it will resync via resume-or-relist)
_OUTBOUND_DEPTH = 16384

#: watch events coalesced into one T_WATCH_BATCH frame at most — bounds
#: frame size so a relist-scale replay cannot produce one giant payload
_WATCH_BATCH_MAX = 512


class _CachedPayload:
    """A frame body serialized at most once per EVENT, not once per
    subscriber — the serde hot path under multi-scheduler watch fan-out
    (with N federated schedulers every store mutation fans out N ways,
    and re-running ``json.dumps`` per subscriber made encode cost scale
    O(subscribers)).  The correlation id lives in the frame header, so
    the cached body bytes are shared verbatim; the watch_batch coalescer
    splices the per-watch id into the cached bytes instead of re-
    encoding (see ``_Conn.write_loop``).  Each codec caches its own
    bytes: a mixed fleet (binary schedulers + a JSON-only dashboard)
    costs one encode per codec per event, never one per subscriber.
    Lazily computed on the first writer that ships it; the
    unsynchronized benign race can at worst serialize twice."""

    __slots__ = ("obj", "_raw", "_raw_bin")

    def __init__(self, obj: dict):
        self.obj = obj
        self._raw: Optional[bytes] = None
        self._raw_bin: Optional[bytes] = None

    def raw(self) -> bytes:
        body = self._raw
        if body is None:
            body = protocol.encode_payload(self.obj)
            self._raw = body
        return body

    def raw_bin(self) -> bytes:
        body = self._raw_bin
        if body is None:
            body = protocol.encode_payload(self.obj, protocol.CODEC_BINARY)
            self._raw_bin = body
        return body

    def raw_for(self, codec: str) -> bytes:
        return self.raw_bin() if codec == protocol.CODEC_BINARY else self.raw()


def _splice_watch_id(body: bytes, watch_id: int) -> bytes:
    """``{"seq":...}`` → ``{"watch_id":N,"seq":...}`` by byte surgery —
    the batch entry a v3 client decodes as ``dict(entry, watch_id=N)``,
    without re-serializing the (shared, cached) entry body."""
    return b'{"watch_id":' + str(watch_id).encode() + b"," + body[1:]


def _splice_watch_id_bin(body: bytes, watch_id: int) -> bytes:
    """The msgpack twin of :func:`_splice_watch_id`: prepend a
    ``watch_id`` key to a cached map body by bumping the map-header
    count and splicing the packed pair in front of the existing
    entries — the entry body itself stays the shared cached bytes."""
    import msgpack

    marker = body[0]
    pair = b"\xa8watch_id" + msgpack.packb(watch_id)
    if 0x80 <= marker < 0x8F:
        # fixmap with room for one more pair
        return bytes((marker + 1,)) + pair + body[1:]
    if marker == 0x8F:
        # fixmap at capacity: promote to map16
        return b"\xde\x00\x10" + pair + body[1:]
    if marker == 0xDE:
        count = int.from_bytes(body[1:3], "big")
        return b"\xde" + (count + 1).to_bytes(2, "big") + pair + body[3:]
    # map32 or a non-map body: fall back to decode/re-encode
    entry = msgpack.unpackb(body, raw=False)
    entry["watch_id"] = watch_id
    return msgpack.packb(entry, use_bin_type=True)


def _batch_body_bin(parts: List[bytes]) -> bytes:
    """Assemble ``{"events": [...]}`` in msgpack from pre-spliced entry
    bodies — the binary equivalent of the JSON join below, still zero
    re-encode.  ``len(parts) <= _WATCH_BATCH_MAX < 65536``."""
    n = len(parts)
    head = bytes((0x90 | n,)) if n < 16 else b"\xdc" + n.to_bytes(2, "big")
    return b"\x81\xa6events" + head + b"".join(parts)


class _Conn:
    """One accepted connection: a reader (request handler) thread plus a
    writer thread draining the outbound queue, so watch pushes and
    admission reviews never block the store-side notifier."""

    def __init__(self, sock: socket.socket, peer):
        self.sock = sock
        self.peer = peer
        #: (mtype, corr_id, dict-or-_CachedPayload) frames, None = stop
        self.outbound: "queue.Queue[Optional[Tuple[int, int, object]]]" = queue.Queue(
            maxsize=_OUTBOUND_DEPTH
        )
        self.closed = False
        #: the peer established its watches via the v3 ``watch_batch``
        #: op: consecutive T_WATCH_EVENT frames may coalesce into one
        #: T_WATCH_BATCH frame on the writer thread below.  Set before
        #: the first watch response is pushed, read only by the writer —
        #: a plain flag, no lock needed.
        self.batch_watch = False
        #: negotiated body codec (protocol v8 ``bus_hello``).  Every
        #: connection starts JSON — the pre-v8 wire format — and flips
        #: to binary only when the peer asked for it; frames are
        #: self-describing (stamped per frame), so the flip has no
        #: ordering hazard with in-flight responses.
        self.codec = protocol.CODEC_JSON
        #: watch_id → kind, for cleanup on close
        self.watches: Dict[int, str] = {}
        #: review_id → waiter, resolved by T_ADMIT_RESP frames
        self.reviews: Dict[int, dict] = {}
        self._lock = threading.Lock()

    def push(self, mtype: int, corr_id: int, payload: dict) -> bool:
        """Enqueue a frame; returns False (and kills the connection) when
        the peer is too slow to keep up."""
        if self.closed:
            return False
        try:
            self.outbound.put_nowait((mtype, corr_id, payload))
            return True
        except queue.Full:
            log.error("bus peer %s overflowed its outbound queue; disconnecting", self.peer)
            self.kill()
            return False

    def kill(self) -> None:
        with self._lock:
            if self.closed:
                return
            self.closed = True
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass
        # unblock the writer thread and fail pending admission reviews
        try:
            self.outbound.put_nowait(None)
        except queue.Full:
            pass
        for waiter in list(self.reviews.values()):
            waiter["result"] = None
            waiter["event"].set()
        self.reviews.clear()

    def _send(self, mtype: int, corr_id: int, payload) -> bool:
        """Send one wire frame (with the bus.delay injection point);
        False kills the connection.  ``payload`` is a dict or a
        :class:`_CachedPayload` whose bytes are shared across
        subscribers."""
        from volcano_tpu import faults

        fp = faults.get_plane()
        if fp.enabled and fp.should("bus.delay"):
            # latency injection lives on the writer thread, NOT the
            # store-side notifier — a slow wire must never stall the
            # store (the decoupling this queue exists for)
            time.sleep(fp.param_ms("bus.delay") / 1e3)
        codec = self.codec
        try:
            if isinstance(payload, _CachedPayload):
                body = payload.raw_for(codec)
            else:
                body = protocol.encode_payload(payload, codec)
            protocol.send_frame_raw(self.sock, mtype, corr_id, body, codec)
            metrics.observe_bus_frame_bytes(codec, len(body))
            return True
        except (OSError, ValueError):
            self.kill()
            return False

    def _send_raw(self, mtype: int, corr_id: int, body: bytes) -> bool:
        """Pre-assembled body variant of :meth:`_send` (the watch-batch
        splice path); the body is already in this connection's codec.
        Same delay injection and failure semantics."""
        from volcano_tpu import faults

        fp = faults.get_plane()
        if fp.enabled and fp.should("bus.delay"):
            time.sleep(fp.param_ms("bus.delay") / 1e3)
        try:
            protocol.send_frame_raw(self.sock, mtype, corr_id, body,
                                    self.codec)
            metrics.observe_bus_frame_bytes(self.codec, len(body))
            return True
        except (OSError, ValueError):
            self.kill()
            return False

    def write_loop(self) -> None:
        while True:
            item = self.outbound.get()
            if item is None or self.closed:
                return
            mtype, corr_id, payload = item
            if not (self.batch_watch and mtype == protocol.T_WATCH_EVENT):
                if not self._send(mtype, corr_id, payload):
                    return
                continue
            # watch-frame coalescing (protocol v3): a commit_batch
            # transaction lands N notifications on this queue in one
            # burst before this thread wakes — drain the consecutive
            # watch events greedily and ship ONE T_WATCH_BATCH frame.
            # Each entry carries its watch id (the correlation-id slot
            # holds only one); entry payloads are shared with the server
            # backlog and other connections, so the id is SPLICED into
            # each entry's cached bytes — the entry body itself is
            # serialized once per event cluster-wide, not once per
            # subscriber (the serde hot path).  A non-watch frame
            # (response, bookmark, admission review) is an ordering
            # barrier: it flushes the batch and is sent right after, in
            # queue order.
            batch = [(corr_id, payload)]
            tail = None
            drained_stop = False
            while len(batch) < _WATCH_BATCH_MAX:
                try:
                    nxt = self.outbound.get_nowait()
                except queue.Empty:
                    break
                if nxt is None:
                    drained_stop = True
                    break
                if nxt[0] != protocol.T_WATCH_EVENT:
                    tail = nxt
                    break
                batch.append((nxt[1], nxt[2]))
            if len(batch) == 1:
                ok = self._send(mtype, corr_id, payload)
            else:
                metrics.observe_watch_batch(len(batch))
                binary = self.codec == protocol.CODEC_BINARY
                splice = _splice_watch_id_bin if binary else _splice_watch_id
                parts = []
                for wid, p in batch:
                    body = (
                        p.raw_for(self.codec) if isinstance(p, _CachedPayload)
                        else protocol.encode_payload(p, self.codec)
                    )
                    parts.append(splice(body, wid))
                ok = self._send_raw(
                    protocol.T_WATCH_BATCH, 0,
                    _batch_body_bin(parts) if binary
                    else b'{"events":[' + b",".join(parts) + b"]}",
                )
            if not ok:
                return
            if tail is not None and not self._send(*tail):
                return
            if drained_stop or self.closed:
                return


class BusServer:
    """Serve an ``APIServer`` store over TCP.  ``port=0`` binds an
    ephemeral port (read it back from ``.port`` after ``start()``)."""

    def __init__(
        self,
        api: APIServer,
        host: str = "127.0.0.1",
        port: int = 0,
        backlog_size: int = 4096,
        bookmark_interval: float = 2.0,
        admission_timeout: float = 10.0,
        replica=None,
    ):
        self.api = api
        self.host = host
        self._port = port
        self.backlog_size = backlog_size
        self.bookmark_interval = bookmark_interval
        self.admission_timeout = admission_timeout
        #: replication role manager (bus/replication.py): routes write
        #: ops to the leader while this replica follows, and serves the
        #: repl_* log-shipping ops while it leads.  None = standalone.
        self.replica = replica
        #: epoch: identifies the resume-token space.  A volatile store
        #: mints a fresh one per incarnation (a resume token from
        #: another incarnation can never be judged against our sequence
        #: numbers → relist-required); a persistent store carries its
        #: epoch in the data-dir meta — shared across restarts AND
        #: across replicas — so surviving cursors resume instead.
        self._own_epoch = uuid.uuid4().hex
        #: durable stores restore the sequence + backlog at start();
        #: afterwards the central watchers keep _seq in lockstep with
        #: the store's committed event stream (see _make_central_watcher)
        self._persistent = hasattr(api, "current_event_seq")
        self._seq = 0  # guarded-by: self.api.locked()
        #: retained watch entries (cached-payload wrappers, shared with
        #: every subscriber queue)
        self._backlog: List[_CachedPayload] = []  # guarded-by: self.api.locked()
        #: kind → [(conn, watch_id)] live subscriptions
        self._subs: Dict[str, List[Tuple[_Conn, int]]] = {}  # guarded-by: self.api.locked()
        #: (kind, operation) → [conn] remote admission registrations;
        #: guarded by _admission_lock — a reconnecting webhook races its
        #: old connection's cleanup, and an unguarded prune-empty-key
        #: could strand the fresh registration on an orphaned list
        self._admission: Dict[Tuple[str, str], List[_Conn]] = {}  # guarded-by: self._admission_lock
        self._admission_lock = threading.Lock()
        self._review_id = 0  # guarded-by: self._review_lock
        self._review_lock = threading.Lock()
        self._central_watchers: List[Tuple[str, object]] = []
        self._listener: Optional[socket.socket] = None
        #: same-host shared-memory ring listener (bus/shm.py), opened
        #: next to the TCP listener when VTPU_BUS_SHM is set; None when
        #: the transport is off or could not come up (TCP still serves)
        self._shm_listener = None
        self._threads: List[threading.Thread] = []
        self._conns: List[_Conn] = []  # guarded-by: self._conns_lock
        self._conns_lock = threading.Lock()
        self._stop = threading.Event()

    # ---- lifecycle ----

    @property
    def epoch(self) -> str:
        return getattr(self.api, "epoch", "") or self._own_epoch

    @property
    def port(self) -> int:
        assert self._listener is not None, "server not started"
        return self._listener.getsockname()[1]

    def start(self) -> "BusServer":
        # bind first, subscribe after: a failed bind must not leave
        # central watchers attached (a retried start() would then record
        # every store mutation twice, duplicating all watch streams)
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        # a restarted server re-binding its fixed port (the kill-and-
        # resume scenario) can race not-yet-reaped sockets of the
        # previous incarnation — retry briefly instead of crashing
        deadline = time.monotonic() + 5.0
        while True:
            try:
                self._listener.bind((self.host, self._port))
                break
            except OSError:
                if self._port == 0 or time.monotonic() >= deadline:
                    raise
                time.sleep(0.05)
        self._listener.listen(64)
        if self._persistent:
            # recovery restores the resume surface: the store's durable
            # event seq and recent-event ring become this incarnation's
            # sequence + backlog, so clients whose cursor survived the
            # restart resume instead of relisting (the 410-storm fix)
            with self.api.locked():
                self._seq = self.api.event_seq
                self._backlog = [
                    _CachedPayload(e) for e in self.api.recent_events()
                ][-self.backlog_size:]
        for kind in protocol.KINDS:
            handler = self._make_central_watcher(kind)
            self.api.watch(kind, handler, send_initial=False)
            self._central_watchers.append((kind, handler))
        accept = threading.Thread(
            target=self._accept_loop, name="vtpu-bus-accept", daemon=True
        )
        bookmark = threading.Thread(
            target=self._bookmark_loop, name="vtpu-bus-bookmark", daemon=True
        )
        self._threads = [accept, bookmark]
        accept.start()
        bookmark.start()
        from volcano_tpu.bus import shm

        if shm.shm_enabled():
            # same-host ring transport: rendezvous derived from the TCP
            # port, so clients need no extra discovery.  Failure to come
            # up is never fatal — TCP serves regardless.
            try:
                self._shm_listener = shm.ShmListener(self.port).start(
                    self._adopt_conn)
                log.info("bus shm rings at %s", self._shm_listener.dir)
            except Exception as e:  # noqa: BLE001 — transport is optional
                log.warning("bus shm listener unavailable (%s); TCP only", e)
                self._shm_listener = None
        log.info("bus serving on %s:%d (epoch %s)", self.host, self.port, self.epoch[:8])
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._shm_listener is not None:
            try:
                self._shm_listener.stop()
            except OSError:
                pass
            self._shm_listener = None
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
            self._listener = None
        with self._conns_lock:
            conns = list(self._conns)
        for conn in conns:
            conn.kill()
        # detach the central watchers so a restarted server on the same
        # store does not leave this incarnation's handlers firing forever
        for kind, handler in self._central_watchers:
            self.api.unwatch(kind, handler)
        self._central_watchers = []

    @property
    def running(self) -> bool:
        return self._listener is not None and not self._stop.is_set()

    # ---- event backlog + fan-out (runs under the store lock) ----

    def _make_central_watcher(self, kind: str):
        from volcano_tpu import faults

        def on_event(event, old, new):
            # requires-lock: self.api.locked()
            # (store watchers fire under the store lock — the
            # _notify discipline documented on APIServer.locked)
            if self._persistent:
                # lockstep with the durable stream: the persistent
                # store stamps each committed event's seq just before
                # flushing its notification (wal.py), so bus sequence
                # numbers survive restarts and match across replicas
                self._seq = self.api.current_event_seq
            else:
                self._seq += 1
            entry = _CachedPayload({
                "seq": self._seq,
                "kind": kind,
                "event": event,
                "old": protocol.encode_obj(old),
                "new": protocol.encode_obj(new),
                "ts": time.time(),
            })
            self._backlog.append(entry)
            if len(self._backlog) > self.backlog_size:
                del self._backlog[: len(self._backlog) - self.backlog_size]
            fp = faults.get_plane()
            for conn, watch_id in list(self._subs.get(kind, [])):
                if fp.enabled and fp.should("bus.drop_event"):
                    # a watch frame only "drops" when its pipe breaks —
                    # kill the subscriber's connection instead of
                    # silently skipping the push (a skipped frame with a
                    # live stream would be an UNRECOVERABLE gap: the
                    # client's next event advances last_seq past it).
                    # The reconnect resumes from last_seq and replays
                    # this entry from the backlog.
                    conn.kill()
                    continue
                # the SAME cached payload goes to every subscriber —
                # its body serializes once, on the first writer thread
                # that ships it (the multi-scheduler fan-out hot path)
                conn.push(protocol.T_WATCH_EVENT, watch_id, entry)

        return on_event

    def _bookmark_loop(self) -> None:
        while not self._stop.wait(self.bookmark_interval):
            with self.api.locked():
                payload = _CachedPayload(
                    {"seq": self._seq, "ts": time.time()}
                )
                for subs in self._subs.values():
                    for conn, watch_id in subs:
                        conn.push(protocol.T_BOOKMARK, watch_id, payload)

    # ---- connections ----

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            listener = self._listener  # stop() may null it concurrently
            if listener is None:
                return
            try:
                sock, peer = listener.accept()
            except OSError:
                return
            if self._stop.is_set():
                # accepted in the same instant stop() closed the
                # listener — drop it so no client talks to a dead server
                try:
                    sock.close()
                except OSError:
                    pass
                return
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            self._adopt_conn(sock, peer)

    def _adopt_conn(self, sock, peer) -> None:
        """Register a transport-agnostic connection (TCP accept or shm
        attach) and start its writer + handler threads."""
        conn = _Conn(sock, peer)
        with self._conns_lock:
            self._conns.append(conn)
        self._update_codec_gauge()
        threading.Thread(
            target=conn.write_loop, name="vtpu-bus-writer", daemon=True
        ).start()
        threading.Thread(
            target=self._serve_conn, args=(conn,),
            name="vtpu-bus-handler", daemon=True,
        ).start()

    def _serve_conn(self, conn: _Conn) -> None:
        try:
            while not conn.closed:
                try:
                    mtype, corr_id, payload = protocol.recv_frame(conn.sock)
                except (ConnectionError, OSError):
                    return
                except ValueError as e:
                    conn.push(protocol.T_ERROR, 0, protocol.error_payload(
                        protocol.BusError(str(e))))
                    return
                if mtype == protocol.T_PING:
                    conn.push(protocol.T_PONG, corr_id, {})
                elif mtype == protocol.T_ADMIT_RESP:
                    waiter = conn.reviews.pop(corr_id, None)
                    if waiter is not None:
                        waiter["result"] = payload
                        waiter["event"].set()
                elif mtype == protocol.T_REQ:
                    # one thread per request, NOT inline: a create whose
                    # admission reviewer lives on THIS connection blocks
                    # waiting for a T_ADMIT_RESP that only this reader
                    # can receive — and a reviewer's own read-back calls
                    # must be servable while another request is parked
                    # in a review.  Ordering is preserved where it
                    # matters: each RemoteAPIServer caller thread is
                    # synchronous, so its requests never overlap.
                    threading.Thread(
                        target=self._handle_request,
                        args=(conn, corr_id, payload),
                        name="vtpu-bus-request", daemon=True,
                    ).start()
                # other types are server→client only; ignore
        finally:
            self._cleanup_conn(conn)

    def _cleanup_conn(self, conn: _Conn) -> None:
        conn.kill()
        with self._conns_lock:
            if conn in self._conns:
                self._conns.remove(conn)
        self._update_codec_gauge()
        with self.api.locked():
            for watch_id, kind in conn.watches.items():
                subs = self._subs.get(kind, [])
                if (conn, watch_id) in subs:
                    subs.remove((conn, watch_id))
            conn.watches.clear()
            self._update_watcher_gauge()
        with self._admission_lock:
            for key, conns in list(self._admission.items()):
                if conn in conns:
                    conns.remove(conn)
                if not conns:
                    self._admission.pop(key, None)

    def _update_watcher_gauge(self) -> None:
        # requires-lock: self.api.locked()
        metrics.update_bus_server_watchers(
            sum(len(s) for s in self._subs.values())
        )

    def _update_codec_gauge(self) -> None:
        with self._conns_lock:
            counts = {protocol.CODEC_JSON: 0, protocol.CODEC_BINARY: 0}
            for c in self._conns:
                counts[c.codec] = counts.get(c.codec, 0) + 1
        for codec, count in counts.items():
            metrics.update_bus_codec_connections(codec, count)

    # ---- request dispatch ----

    def _handle_request(self, conn: _Conn, req_id: int, payload: dict) -> None:
        from volcano_tpu import faults

        op = payload.get("op", "")
        fp = faults.get_plane()
        if fp.enabled and fp.should("bus.disconnect"):
            # server-side partition: the request dies with the
            # connection; the client fails fast with BusError, redials,
            # and its resync re-establishes every watch resume-or-relist
            conn.kill()
            return
        start = time.perf_counter()
        rec = trace.get_recorder()
        if rec.enabled and "cycle" in payload:
            # cross-process correlation: the client stamped the request
            # with its scheduling-cycle id (bus/remote.py) — record it so
            # a pending task can be followed scheduler → bus →
            # controllers by joining on the cycle id
            rec.event(
                "bus:" + op, "bus",
                cycle=payload["cycle"], kind=payload.get("kind"),
            )
        try:
            from volcano_tpu import obs

            # server-side half of the cross-process span: parent is the
            # REMOTE caller's span (payload["span"], stamped by
            # bus/remote.py).  Ops without a context — or with the
            # flight recorder off — cost one enabled() check.
            if obs.enabled() and "span" in payload:
                with obs.adopt(
                    payload["span"], "bus:" + op, cat="bus",
                    args={"kind": payload.get("kind")}
                    if payload.get("kind") else None,
                ):
                    result = self._execute(conn, req_id, payload, op)
            else:
                result = self._execute(conn, req_id, payload, op)
            if result is not None:
                conn.push(protocol.T_RESP, req_id, result)
            metrics.observe_bus_server_request(op, time.perf_counter() - start, "ok")
        except ApiError as e:
            conn.push(protocol.T_ERROR, req_id, protocol.error_payload(e))
            metrics.observe_bus_server_request(op, time.perf_counter() - start, "error")
        except Exception as e:  # noqa: BLE001 — report, keep serving
            log.error("bus request %s failed: %s", op, e)
            conn.push(protocol.T_ERROR, req_id, protocol.error_payload(ApiError(str(e))))
            metrics.observe_bus_server_request(op, time.perf_counter() - start, "error")

    #: ops that mutate (or linearizably read) the store — while this
    #: server is a replication FOLLOWER they are proxied to the leader,
    #: so a client connected anywhere keeps working; watches and lists
    #: stay local (informer-grade staleness, the k8s contract).  ``get``
    #: is routed too: read-modify-CAS loops (leader leases, queue
    #: updates) need their read against the write point or every CAS
    #: would spuriously conflict on follower lag.
    _LEADER_OPS = frozenset({
        "create", "update", "update_status", "delete",
        "cas_bind", "commit_batch", "txn_commit", "get",
        "bus_add_replica", "bus_remove_replica",
    })

    def _execute(self, conn: _Conn, req_id: int, payload: dict, op: str):
        from volcano_tpu.bus.protocol import NotLeaderError

        api = self.api
        replica = self.replica
        if op == "bus_hello":
            # v8 codec negotiation — answered locally by ANY role (the
            # codec is a property of THIS connection, not of the store).
            # The reply rides the freshly negotiated codec; frames are
            # self-describing, so the client decodes it either way.
            offered = payload.get("codecs") or ()
            if protocol.HAS_BINARY and protocol.CODEC_BINARY in offered:
                conn.codec = protocol.CODEC_BINARY
            else:
                conn.codec = protocol.CODEC_JSON
            self._update_codec_gauge()
            return {"codec": conn.codec, "version": protocol.VERSION}
        if replica is not None and not replica.is_leader:
            if op in self._LEADER_OPS:
                if payload.get("proxied"):
                    # one-hop cap: our leader view is stale — tell the
                    # proxying peer instead of bouncing frames around;
                    # the hint carries OUR leader view so the caller's
                    # next dial is direct, not a blind rotation
                    raise NotLeaderError(
                        "not leader (proxied write refused)",
                        leader=replica.leader_url,
                    )
                return replica.proxy(payload)
            if op == "register_admission":
                raise NotLeaderError(
                    "not leader — register_admission must run at the "
                    f"leader ({replica.leader_url or 'unknown'})",
                    leader=replica.leader_url,
                )
        if op == "bus_status":
            from volcano_tpu.bus.wal import bus_status_payload

            return bus_status_payload(api, replica)
        if op == "repl_append":
            if replica is None:
                raise ApiError("replication not enabled")
            # the connection's codec decides HOW record payloads ship
            # (raw bytes on binary connections, text/base64 on JSON) —
            # see ReplicationCoordinator.pull for the byte-verbatim rule
            return replica.handle_append(payload, codec=conn.codec)
        if op == "repl_snapshot":
            if replica is None:
                raise ApiError("replication not enabled")
            return replica.handle_snapshot(payload)
        if op == "repl_commit":
            if replica is None:
                raise ApiError("replication not enabled")
            return replica.handle_commit(payload)
        if op == "repl_prevote":
            if replica is None:
                raise ApiError("replication not enabled")
            # served by ANY role: a pre-vote probe asks "would you
            # support my promotion", which followers (and the leader,
            # who always denies) answer locally
            return replica.handle_prevote(payload)
        if op == "bus_add_replica":
            if replica is None:
                raise ApiError("replication not enabled")
            return replica.add_replica(str(payload.get("url", "")))
        if op == "bus_remove_replica":
            if replica is None:
                raise ApiError("replication not enabled")
            return replica.remove_replica(str(payload.get("url", "")))
        if op == "create":
            obj = protocol.decode_obj(payload["object"])
            obj = self._remote_admission(obj.kind, "CREATE", obj)
            return {"object": protocol.encode_obj(api.create(obj))}
        if op == "update":
            obj = protocol.decode_obj(payload["object"])
            obj = self._remote_admission(obj.kind, "UPDATE", obj)
            return {"object": protocol.encode_obj(
                api.update(obj, expected_rv=payload.get("expected_rv")))}
        if op == "update_status":
            obj = protocol.decode_obj(payload["object"])
            return {"object": protocol.encode_obj(api.update_status(obj))}
        if op == "get":
            obj = api.get(payload["kind"], payload["namespace"], payload["name"])
            return {"object": protocol.encode_obj(obj)}
        if op == "list":
            objs = api.list(payload["kind"], payload.get("namespace"))
            return {"objects": [protocol.encode_obj(o) for o in objs]}
        if op == "delete":
            old = api.delete(payload["kind"], payload["namespace"], payload["name"])
            return {"object": protocol.encode_obj(old)}
        if op == "commit_batch":
            # the coalesced bind/commit frame (protocol v2): N binds +
            # evictions + audit events + status writebacks applied as
            # ONE store transaction with one watch-notification flush —
            # the per-object sections skip admission exactly like the
            # update_status subresource path they are built from
            results = api.commit_batch(
                binds=payload.get("binds", ()),
                evicts=payload.get("evicts", ()),
                events=payload.get("events", ()),
                conditions=payload.get("conditions", ()),
                pod_groups=[
                    protocol.decode_obj(d)
                    for d in payload.get("pod_groups", ())
                ],
            )
            return {"results": results}
        if op == "cas_bind":
            # v4: one optimistic binding write — bind iff still unbound
            # and the resourceVersion matches (the federation spillover
            # primitive; conflicts detected at the store, Omega-style)
            obj = api.cas_bind(
                payload["namespace"], payload["name"], payload["hostname"],
                expected_rv=payload.get("expected_rv"),
            )
            return {"object": protocol.encode_obj(obj)}
        if op == "txn_commit":
            # v6: the atomic multi-cas_bind transaction — every
            # precondition checked before any effect, all binds applied
            # under one store lock hold (a persistent store logs them as
            # ONE WAL record), per-item conflict results on abort.  The
            # cross-shard gang-assembly primitive.
            result = api.txn_commit(payload.get("binds", ()))
            return {
                "committed": result["committed"],
                "results": result["results"],
                "objects": [
                    protocol.encode_obj(o) for o in result.get("objects", ())
                ],
            }
        if op == "watch":
            self._handle_watch(conn, req_id, payload)
            return None  # responses pushed inline for ordering
        if op == "watch_batch":
            # v3: identical watch semantics, but the connection opts into
            # coalesced T_WATCH_BATCH delivery (the writer thread batches
            # consecutive watch frames).  Flag first: the flip must be
            # visible before the establishment pushes any event.
            conn.batch_watch = True
            self._handle_watch(conn, req_id, payload)
            return None
        if op == "unwatch":
            watch_id = int(payload["watch_id"])
            with self.api.locked():
                kind = conn.watches.pop(watch_id, None)
                if kind is not None:
                    subs = self._subs.get(kind, [])
                    subs[:] = [s for s in subs if s != (conn, watch_id)]
                    self._update_watcher_gauge()
            return {"unwatched": kind is not None}
        if op == "register_admission":
            key = (payload["kind"], payload["operation"])
            with self._admission_lock:
                conns = self._admission.setdefault(key, [])
                if conn not in conns:
                    conns.append(conn)
            return {"registered": True}
        raise ApiError(f"unknown bus op {op!r}")

    # ---- watch ----

    def _handle_watch(self, conn: _Conn, req_id: int, payload: dict) -> None:
        """Establish a watch.  Everything happens under the store lock so
        the response, any backlog replay, and the live subscription form
        one gapless, duplicate-free sequence."""
        kind = payload["kind"]
        if kind not in protocol.KINDS:
            raise ApiError(f"unknown kind {kind!r}")
        watch_id = int(payload["watch_id"])
        resume_seq = payload.get("resume_seq")
        from volcano_tpu import faults

        fp = faults.get_plane()
        with self.api.locked():
            if resume_seq is not None:
                oldest_covered = self._seq - len(self._backlog)
                force_relist = fp.enabled and fp.should("bus.force_relist")
                if (
                    force_relist
                    or payload.get("epoch") != self.epoch
                    or resume_seq < oldest_covered
                ):
                    # 410 Gone: this incarnation cannot prove the client
                    # missed nothing — a fresh list is required
                    conn.push(protocol.T_RESP, req_id, {
                        "resumed": False, "epoch": self.epoch, "seq": self._seq,
                    })
                    return
                conn.push(protocol.T_RESP, req_id, {
                    "resumed": True, "epoch": self.epoch, "seq": self._seq,
                })
                for entry in self._backlog:
                    if (
                        entry.obj["seq"] > resume_seq
                        and entry.obj["kind"] == kind
                    ):
                        conn.push(protocol.T_WATCH_EVENT, watch_id, entry)
            else:
                initial = [protocol.encode_obj(o) for o in self.api.list(kind)]
                conn.push(protocol.T_RESP, req_id, {
                    "resumed": True, "epoch": self.epoch, "seq": self._seq,
                    "initial": initial,
                })
            # re-establishment on a live connection replaces the old
            # subscription — a watch id is never subscribed twice
            subs = self._subs.setdefault(kind, [])
            subs[:] = [s for s in subs if s != (conn, watch_id)]
            subs.append((conn, watch_id))
            conn.watches[watch_id] = kind
            self._update_watcher_gauge()

    # ---- remote admission ----

    def _remote_admission(self, kind: str, operation: str, obj):
        """Run registered remote reviews in order, mutating as we go.
        Runs BEFORE the store transaction (k8s webhook ordering) so a
        webhook that reads back through the bus cannot deadlock."""
        with self._admission_lock:
            conns = list(self._admission.get((kind, operation), ()))
        if not conns:
            return obj
        from volcano_tpu import obs

        data = protocol.encode_obj(obj)
        # the review runs in the WEBHOOK daemon's process — forward the
        # span context so its admission:review span parents into this
        # request's trace (old webhook clients ignore the key)
        span_ctx = obs.current_wire()
        for conn in conns:
            if conn.closed:
                continue
            with self._review_lock:
                self._review_id += 1
                review_id = self._review_id
            waiter = {"event": threading.Event(), "result": None}
            conn.reviews[review_id] = waiter
            review = {"kind": kind, "operation": operation, "object": data}
            if span_ctx is not None:
                review["span"] = span_ctx
            if not conn.push(protocol.T_ADMIT_REQ, review_id, review):
                continue
            if not waiter["event"].wait(self.admission_timeout):
                conn.reviews.pop(review_id, None)
                raise AdmissionError(
                    f"admission review for {kind}/{operation} timed out"
                )
            result = waiter["result"]
            if result is None:
                # reviewer died mid-flight — failure-open, like a webhook
                # with failurePolicy: Ignore whose endpoint vanished
                log.error("admission reviewer for %s/%s disconnected mid-review",
                          kind, operation)
                continue
            if not result.get("allowed", False):
                raise AdmissionError(result.get("message") or
                                     "denied by admission webhook")
            if result.get("object") is not None:
                data = result["object"]
        return protocol.decode_obj(data)
