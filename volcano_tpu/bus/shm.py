"""Same-host shared-memory bus transport: mmap'd SPSC byte rings.

When the scheduler, apiserver, and compute sidecar are co-resident (the
``local_up --multiproc`` topology), every bus frame still pays the
loopback TCP stack.  This module carries the IDENTICAL frames — same
header, same negotiated codec, same byte stream — through a pair of
mmap'd single-producer/single-consumer rings (one per direction, the
LMAX-Disruptor/Aeron shape) with an eventfd doorbell, so the framing
and serde layers above are completely unchanged: :class:`ShmSocket`
duck-types the five socket methods the bus actually uses (``sendall`` /
``recv`` / ``settimeout`` / ``setsockopt`` / ``close``), and
``send_frame`` / ``recv_frame`` / ``_Conn`` / ``RemoteAPIServer`` run
over it verbatim.

Ring layout (one file per direction, client-created, same uid):

    offset 0    u32 magic ``VRNG`` + u32 data size
    offset 64   u64 write position (producer-owned cache line)
    offset 128  u64 read position  (consumer-owned cache line)
    offset 4096 data[size]

Positions increase monotonically; the byte at stream position ``p``
lives at ``data[p % size]``, so frames wrap mid-frame freely — the
stream above does exact reads and never sees the seam.  The doorbell is
an eventfd the producer rings after advancing ``write_pos``; the
consumer sleeps in ``select`` on (doorbell, control socket) so a peer
death (control-socket EOF) wakes it immediately.  Where ``os.eventfd``
or fd-passing is unavailable the consumer degrades to an adaptive
spin-then-sleep poll — slower wakeups, same bytes.

Connection setup rides a tiny unix control socket in the ring
directory: the client creates both ring files (c2s, s2c) and both
eventfds, passes the eventfds with ``socket.send_fds``, and names the
ring files in a one-line JSON hello; the server mmaps them and answers
one ack byte.  The control socket then stays open purely as a liveness
signal.  Anything failing anywhere in attach — missing directory, dead
listener, no fd-passing — raises, and the caller falls back to TCP.

Deliberate caveats (documented in the README): same host and same uid
only (the rings are plain files under the shm directory), one ring per
direction per connection, and no in-flight resize.
"""

from __future__ import annotations

import json
import mmap
import os
import select
import socket
import struct
import threading
import time
from typing import Optional, Tuple

_RING_MAGIC = 0x56524E47  # "VRNG"
_MAGIC_OFF = 0
_SIZE_OFF = 4
_WRITE_POS_OFF = 64
_READ_POS_OFF = 128
_DATA_OFF = 4096

_U32 = struct.Struct("<I")
_U64 = struct.Struct("<Q")

#: default data bytes per ring (per direction)
DEFAULT_RING_BYTES = 4 * 1024 * 1024

#: adaptive poll for builds without an eventfd doorbell: spin briefly,
#: then back off to bounded sleeps
_POLL_SPIN = 200
_POLL_SLEEP_S = 0.0005

_HAS_EVENTFD = hasattr(os, "eventfd") and hasattr(socket, "send_fds")


def ring_dir(port: int) -> str:
    """The shm directory a bus endpoint at ``port`` rendezvouses in.

    Derived from the TCP port so the client needs no extra discovery:
    ``$VTPU_BUS_SHM_DIR`` (or ``/dev/shm/vtpu-bus-<uid>``) + the port.
    """
    base = os.environ.get("VTPU_BUS_SHM_DIR") or os.path.join(
        "/dev/shm", f"vtpu-bus-{os.getuid()}")
    return os.path.join(base, str(port))


def shm_enabled() -> bool:
    """Whether the same-host ring transport is switched on at all
    (``VTPU_BUS_SHM=1``, set by ``local_up --multiproc``)."""
    return os.environ.get("VTPU_BUS_SHM", "") not in ("", "0")


def _create_ring_file(path: str, size: int) -> mmap.mmap:
    fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_RDWR, 0o600)
    try:
        os.ftruncate(fd, _DATA_OFF + size)
        mem = mmap.mmap(fd, _DATA_OFF + size)
    finally:
        os.close(fd)
    _U32.pack_into(mem, _MAGIC_OFF, _RING_MAGIC)
    _U32.pack_into(mem, _SIZE_OFF, size)
    return mem


def _open_ring_file(path: str) -> Tuple[mmap.mmap, int]:
    fd = os.open(path, os.O_RDWR)
    try:
        total = os.fstat(fd).st_size
        mem = mmap.mmap(fd, total)
    finally:
        os.close(fd)
    if _U32.unpack_from(mem, _MAGIC_OFF)[0] != _RING_MAGIC:
        mem.close()
        raise ValueError(f"not a VRNG ring file: {path}")
    size = _U32.unpack_from(mem, _SIZE_OFF)[0]
    if total < _DATA_OFF + size:
        mem.close()
        raise ValueError(f"truncated ring file: {path}")
    return mem, size


class _Ring:
    """One direction of the transport.  Exactly one producer and one
    consumer; each owns its position word and only ever reads the
    other's — the SPSC discipline that keeps this lock-free."""

    def __init__(self, mem: mmap.mmap, size: int, doorbell: Optional[int]):
        self.mem = mem
        self.size = size
        self.doorbell = doorbell  # eventfd, or None → polling

    # -- position words (the mmap is the shared truth) --
    @property
    def write_pos(self) -> int:
        return _U64.unpack_from(self.mem, _WRITE_POS_OFF)[0]

    @write_pos.setter
    def write_pos(self, v: int) -> None:
        _U64.pack_into(self.mem, _WRITE_POS_OFF, v)

    @property
    def read_pos(self) -> int:
        return _U64.unpack_from(self.mem, _READ_POS_OFF)[0]

    @read_pos.setter
    def read_pos(self, v: int) -> None:
        _U64.pack_into(self.mem, _READ_POS_OFF, v)

    def ring(self) -> None:
        if self.doorbell is not None:
            try:
                os.eventfd_write(self.doorbell, 1)
            except OSError:
                pass

    def _copy_in(self, pos: int, data: bytes) -> None:
        """Write ``data`` at stream position ``pos`` (may wrap)."""
        idx = pos % self.size
        first = min(len(data), self.size - idx)
        self.mem[_DATA_OFF + idx:_DATA_OFF + idx + first] = data[:first]
        if first < len(data):
            rest = len(data) - first
            self.mem[_DATA_OFF:_DATA_OFF + rest] = data[first:]

    def _copy_out(self, pos: int, n: int) -> bytes:
        """Read ``n`` bytes at stream position ``pos`` (may wrap)."""
        idx = pos % self.size
        first = min(n, self.size - idx)
        out = self.mem[_DATA_OFF + idx:_DATA_OFF + idx + first]
        if first < n:
            out += self.mem[_DATA_OFF:_DATA_OFF + n - first]
        return out

    def close(self) -> None:
        try:
            self.mem.close()
        except (BufferError, ValueError):
            pass
        if self.doorbell is not None:
            try:
                os.close(self.doorbell)
            except OSError:
                pass
            self.doorbell = None


class ShmSocket:
    """A connected shm transport endpoint, duck-typed as a socket.

    ``tx``/``rx`` are the two rings from this endpoint's perspective;
    ``ctl`` is the control unix socket whose EOF means the peer died.
    The bus layers above only ever call ``sendall`` / ``recv`` /
    ``settimeout`` / ``setsockopt`` / ``shutdown`` / ``close``.
    """

    def __init__(self, tx: _Ring, rx: _Ring, ctl: socket.socket,
                 peer: str = "shm"):
        self._tx = tx
        self._rx = rx
        self._ctl = ctl
        self._ctl.setblocking(False)
        self._peer = peer
        self._timeout: Optional[float] = None
        self._closed = False
        self._peer_dead = False
        # one writer/reader thread each on the bus, but close() can race
        # a blocked recv — guard the teardown only
        self._close_lock = threading.Lock()

    # -- socket surface ----------------------------------------------
    def settimeout(self, t: Optional[float]) -> None:
        self._timeout = t

    def gettimeout(self) -> Optional[float]:
        return self._timeout

    def setsockopt(self, *_a, **_kw) -> None:
        """No-op: TCP_NODELAY and friends have no shm equivalent."""

    def getpeername(self):
        return (self._peer, 0)

    def fileno(self) -> int:
        return self._ctl.fileno() if not self._closed else -1

    def _deadline(self) -> Optional[float]:
        return None if self._timeout is None else (
            time.monotonic() + self._timeout)

    def _peer_alive(self) -> bool:
        """Drain the control socket; EOF means the peer is gone."""
        if self._peer_dead or self._closed:
            return False
        try:
            while True:
                chunk = self._ctl.recv(4096)
                if chunk == b"":
                    self._peer_dead = True
                    return False
                # doorbell bytes in the no-eventfd fallback: just drain
        except (BlockingIOError, InterruptedError):
            return True
        except OSError:
            self._peer_dead = True
            return False

    def _wait(self, ring: _Ring, deadline: Optional[float]) -> None:
        """Sleep until the ring MAY have progressed, the peer dies, or
        the deadline passes (socket.timeout)."""
        if deadline is not None and time.monotonic() >= deadline:
            raise socket.timeout("shm ring timed out")
        fds = [self._ctl.fileno()]
        if ring.doorbell is not None:
            fds.append(ring.doorbell)
            budget = None if deadline is None else max(
                0.0, deadline - time.monotonic())
            try:
                ready, _, _ = select.select(fds, [], [], budget)
            except (OSError, ValueError):
                self._peer_dead = True
                return
            if ring.doorbell in ready:
                try:
                    os.eventfd_read(ring.doorbell)
                except OSError:
                    pass
            if self._ctl.fileno() in ready:
                self._peer_alive()
        else:
            time.sleep(_POLL_SLEEP_S)
            self._peer_alive()

    def sendall(self, data: bytes) -> None:
        try:
            self._sendall(data)
        except ValueError:
            # the mmap was torn down by a concurrent close()
            if self._closed:
                raise ConnectionError("shm socket is closed") from None
            raise

    def _sendall(self, data: bytes) -> None:
        if self._closed:
            raise OSError("shm socket is closed")
        view = memoryview(data)
        deadline = self._deadline()
        ring = self._tx
        while len(view):
            if self._closed:
                raise ConnectionError("shm socket is closed")
            free = ring.size - (ring.write_pos - ring.read_pos)
            if free <= 0:
                # backpressure: the ring is full.  The doorbell fd is
                # the consumer's wait channel, so sharing it here could
                # lose a wakeup — a bounded sleep-poll is the honest
                # SPSC answer for the rare full-ring case.
                if not self._peer_alive():
                    raise ConnectionError("shm peer closed")
                if deadline is not None and time.monotonic() >= deadline:
                    raise socket.timeout("shm ring full")
                time.sleep(_POLL_SLEEP_S)
                continue
            n = min(free, len(view))
            w = ring.write_pos
            ring._copy_in(w, bytes(view[:n]))
            ring.write_pos = w + n
            ring.ring()
            if ring.doorbell is None:
                # no eventfd: nudge the peer's select via the ctl socket
                try:
                    self._ctl.send(b"\x00")
                except OSError:
                    pass
            view = view[n:]

    def recv(self, n: int) -> bytes:
        try:
            return self._recv(n)
        except ValueError:
            # the mmap was torn down by a concurrent close()
            if self._closed:
                return b""
            raise

    def _recv(self, n: int) -> bytes:
        if self._closed:
            return b""
        ring = self._rx
        deadline = self._deadline()
        spins = 0
        while True:
            if self._closed:
                return b""
            avail = ring.write_pos - ring.read_pos
            if avail > 0:
                take = min(avail, n)
                r = ring.read_pos
                out = ring._copy_out(r, take)
                # the position store is the release: a producer polling
                # a full ring sees the space as soon as this lands
                ring.read_pos = r + take
                return out
            if not self._peer_alive():
                return b""
            if ring.doorbell is None and spins < _POLL_SPIN:
                spins += 1
                continue
            self._wait(ring, deadline)

    def shutdown(self, _how: int = socket.SHUT_RDWR) -> None:
        try:
            self._ctl.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass

    def close(self) -> None:
        with self._close_lock:
            if self._closed:
                return
            self._closed = True
        # shutdown BEFORE close: a thread blocked in select on this fd
        # pins the open file, so a bare close() would neither wake it
        # nor deliver EOF to the peer until it returned — which it
        # never would.  shutdown() propagates immediately to both.
        try:
            self._ctl.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        # and wake any local waiter parked on a doorbell
        self._tx.ring()
        self._rx.ring()
        try:
            self._ctl.close()
        except OSError:
            pass
        self._tx.close()
        self._rx.close()

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"<ShmSocket peer={self._peer} closed={self._closed}>"


def _make_doorbell() -> Optional[int]:
    if not _HAS_EVENTFD:
        return None
    try:
        return os.eventfd(0, os.EFD_NONBLOCK | os.EFD_CLOEXEC)
    except OSError:  # pragma: no cover - exotic kernels
        return None


def connect(port: int, timeout: Optional[float] = None,
            ring_bytes: int = DEFAULT_RING_BYTES) -> ShmSocket:
    """Attach to the shm listener rendezvousing at TCP ``port``.

    Raises on ANY failure (no directory, no listener, no fd-passing) —
    the caller's contract is to fall back to TCP silently.
    """
    d = ring_dir(port)
    ctl = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    ctl.settimeout(timeout if timeout else 5.0)
    mems = []
    bells = []
    paths = []
    try:
        ctl.connect(os.path.join(d, "ctl.sock"))
        tag = f"{os.getpid()}-{ctl.fileno()}-{time.monotonic_ns()}"
        c2s_path = os.path.join(d, f"c2s-{tag}.ring")
        s2c_path = os.path.join(d, f"s2c-{tag}.ring")
        c2s = _create_ring_file(c2s_path, ring_bytes)
        paths.append(c2s_path)
        mems.append(c2s)
        s2c = _create_ring_file(s2c_path, ring_bytes)
        paths.append(s2c_path)
        mems.append(s2c)
        c2s_bell = _make_doorbell()
        s2c_bell = _make_doorbell()
        bells = [b for b in (c2s_bell, s2c_bell) if b is not None]
        hello = json.dumps({
            "c2s": os.path.basename(c2s_path),
            "s2c": os.path.basename(s2c_path),
            "bells": len(bells),
            "pid": os.getpid(),
        }).encode() + b"\n"
        if bells and _HAS_EVENTFD:
            socket.send_fds(ctl, [hello], bells)
        else:
            ctl.sendall(hello)
        ack = ctl.recv(1)
        if ack != b"+":
            raise ConnectionError(f"shm attach refused: {ack!r}")
        # ring files are mmap'd on both sides now; unlink so a dead
        # process never leaks them on disk
        for p in paths:
            try:
                os.unlink(p)
            except OSError:
                pass
        return ShmSocket(
            _Ring(c2s, ring_bytes, c2s_bell),
            _Ring(s2c, ring_bytes, s2c_bell),
            ctl, peer=f"shm:{port}")
    except BaseException:
        for m in mems:
            try:
                m.close()
            except (BufferError, ValueError):
                pass
        for b in bells:
            try:
                os.close(b)
            except OSError:
                pass
        for p in paths:
            try:
                os.unlink(p)
            except OSError:
                pass
        try:
            ctl.close()
        except OSError:
            pass
        raise


class ShmListener:
    """The server half: a unix control socket in the ring directory that
    turns each attach into a ShmSocket and hands it to ``on_conn``
    (the same ``_serve_conn`` path TCP connections take)."""

    def __init__(self, port: int):
        self.dir = ring_dir(port)
        os.makedirs(self.dir, mode=0o700, exist_ok=True)
        self.ctl_path = os.path.join(self.dir, "ctl.sock")
        try:
            os.unlink(self.ctl_path)
        except OSError:
            pass
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._sock.bind(self.ctl_path)
        self._sock.listen(64)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def serve(self, on_conn) -> None:
        """Accept attaches until stopped; each successful attach calls
        ``on_conn(shm_socket, peer_string)``."""
        while not self._stop.is_set():
            try:
                ctl, _ = self._sock.accept()
            except OSError:
                return
            try:
                sock, peer = self._attach(ctl)
            except Exception:
                try:
                    ctl.close()
                except OSError:
                    pass
                continue
            on_conn(sock, peer)

    def start(self, on_conn) -> "ShmListener":
        self._thread = threading.Thread(
            target=self.serve, args=(on_conn,),
            name="bus-shm-accept", daemon=True)
        self._thread.start()
        return self

    def _attach(self, ctl: socket.socket) -> Tuple[ShmSocket, str]:
        ctl.settimeout(5.0)
        if _HAS_EVENTFD:
            hello_raw, fds, _flags, _addr = socket.recv_fds(ctl, 4096, 2)
        else:  # pragma: no cover - no fd-passing on this build
            hello_raw, fds = ctl.recv(4096), []
        hello = json.loads(hello_raw.decode().strip())
        if len(fds) != int(hello.get("bells", 0)):
            for fd in fds:
                os.close(fd)
            raise ConnectionError("shm attach lost its doorbells")
        c2s_bell = fds[0] if len(fds) == 2 else None
        s2c_bell = fds[1] if len(fds) == 2 else None
        c2s_mem, c2s_size = _open_ring_file(
            os.path.join(self.dir, os.path.basename(hello["c2s"])))
        s2c_mem, s2c_size = _open_ring_file(
            os.path.join(self.dir, os.path.basename(hello["s2c"])))
        ctl.sendall(b"+")
        peer = f"shm:pid-{hello.get('pid', '?')}"
        # server's tx is s2c, rx is c2s
        return ShmSocket(
            _Ring(s2c_mem, s2c_size, s2c_bell),
            _Ring(c2s_mem, c2s_size, c2s_bell),
            ctl, peer=peer), peer

    def stop(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        try:
            os.unlink(self.ctl_path)
        except OSError:
            pass
        if self._thread is not None:
            self._thread.join(timeout=5)
