"""WAL + snapshot durability for the API-server store (ROADMAP item 4a).

The reference leans on etcd for bus durability; this module is the
standalone build's equivalent: a :class:`PersistentAPIServer` whose
every store *transaction* — one ``create``/``update``/``delete``, one
coalesced ``commit_batch``, one ``cas_bind`` — appends exactly one
length-prefixed, CRC-checksummed record to a write-ahead log and
fsyncs it **before** any observer (the requesting client's ack, or any
watch subscriber) sees the effect.

Write-ahead discipline
----------------------

The in-process ``APIServer`` fires watch notifications inline, mid-
transaction, under the store lock.  Here they are *buffered* per
transaction and flushed only after the WAL record is durable (and,
under replication, committed by the follower quorum — see
``bus/replication.py``).  Consequences:

* an acknowledged write can never be lost by a crash — the record hit
  disk before the T_RESP frame left the server;
* a watch subscriber can never observe an event that recovery would
  roll back — notifications trail durability;
* recovery is **physical**: each record carries the encoded watch
  events the transaction produced (old/new object dicts with their
  final resourceVersions), so replay is deterministic re-application
  of state — no admission re-runs, no re-minted timestamps.

Recovery loads the latest snapshot, replays the WAL tail, tolerates a
torn/partial trailing record (truncated to the last whole record), and
— critically — restores the **global bus sequence and watch backlog**:
the snapshot persists the epoch and recent-event ring, so a restarted
``vtpu-apiserver`` hands resuming clients their missed suffix instead
of a cluster-wide 410 relist storm (``bus_relists_total`` is the
canary).

Fault points: ``wal.write_fail`` (append raises, op not acked),
``wal.torn_tail`` (a partial record reaches disk, then the op fails —
the crash-mid-write shape), ``wal.fsync_delay`` (latency injection on
the fsync).
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import os
import struct
import threading
import time
import uuid
import zlib
from typing import Dict, List, Optional, Tuple

from volcano_tpu.client.apiserver import ADDED, ApiError, APIServer, DELETED
from volcano_tpu.metrics import metrics
from volcano_tpu.utils.logging import get_logger

log = get_logger(__name__)

#: per-record framing: u32 payload length + u32 crc32(payload)
_REC_HEADER = struct.Struct("<II")

WAL_FILE = "wal.log"
SNAPSHOT_FILE = "snapshot.json"
META_FILE = "meta.json"


class WalError(ApiError):
    """A WAL append could not be made durable — the op is NOT acked."""


def append_record(f, payload: bytes) -> None:
    """Write one framed record (no fsync — the caller owns durability)."""
    f.write(_REC_HEADER.pack(len(payload), zlib.crc32(payload)) + payload)


def read_records(path: str) -> Tuple[List[bytes], int, bool]:
    """Read every whole, checksum-valid record from a WAL file.

    Returns ``(payloads, valid_prefix_len, torn)``: a torn or corrupt
    tail — short header, short payload, or CRC mismatch — ends the scan
    at the last good record instead of raising (the crash-mid-write
    recovery contract).  ``valid_prefix_len`` is the byte offset the
    file should be truncated to before appending resumes."""
    payloads: List[bytes] = []
    offset = 0
    torn = False
    if not os.path.exists(path):
        return payloads, 0, False
    with open(path, "rb") as f:
        data = f.read()
    n = len(data)
    while offset + _REC_HEADER.size <= n:
        length, crc = _REC_HEADER.unpack_from(data, offset)
        start = offset + _REC_HEADER.size
        end = start + length
        if end > n:
            torn = True
            break
        payload = data[start:end]
        if zlib.crc32(payload) != crc:
            torn = True
            break
        payloads.append(payload)
        offset = end
    if offset != n:
        torn = True
    return payloads, offset, torn


def record_codec() -> Optional[str]:
    """Body codec for NEW WAL records (recovery sniffs per record, so
    reads never need this).  ``VTPU_WAL_CODEC=json`` pins JSON — the
    escape hatch for replicating to a follower too old to decode
    msgpack record bytes; the default (``None``) lets
    ``protocol.encode_record`` pick binary when msgpack is available."""
    from volcano_tpu.bus import protocol

    forced = os.environ.get("VTPU_WAL_CODEC", "").strip().lower()
    if forced in (protocol.CODEC_JSON, protocol.CODEC_BINARY):
        return forced
    return None


def store_digest(api: APIServer) -> str:
    """Canonical content digest of a store: every object of every kind,
    keyed and resourceVersion-stamped — the equality the crash-recovery
    tests pin (recovered store == acknowledged-write prefix)."""
    from volcano_tpu.bus import protocol

    state: Dict[str, Dict[str, dict]] = {}
    with api.locked():
        for kind in sorted(protocol.KINDS):
            objs = api.list(kind)
            if objs:
                state[kind] = {
                    f"{o.metadata.namespace}/{o.metadata.name}": o.to_dict()
                    for o in objs
                }
    blob = json.dumps(state, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()


def bus_status_payload(api, replica=None) -> dict:
    """The ``bus_status`` op's payload, shared by the in-process and
    ``--bus`` backends so ``vtctl bus status`` renders byte-identically
    over both (the ``vtctl shards`` discipline).  Every field is stored
    or derived state — no call-time clocks — so two calls against the
    same quiescent store produce the same bytes."""
    status = (
        api.bus_status() if hasattr(api, "bus_status")
        else {"role": "standalone", "persistent": False}
    )
    if replica is not None:
        status.update(replica.status())
    return status


class PersistentAPIServer(APIServer):
    """The in-process store with WAL + snapshot durability.

    Drop-in for ``APIServer`` everywhere (BusServer, clients, daemons);
    the only behavioral difference is the write-ahead discipline
    documented in the module docstring.  ``data_dir`` holds three
    files: ``meta.json`` (epoch + term, atomic rewrite), ``wal.log``
    (the record stream since the last snapshot), ``snapshot.json``
    (full store + recent-event ring, atomic rewrite, rotated every
    ``snapshot_every`` records)."""

    def __init__(
        self,
        data_dir: str,
        snapshot_every: int = 256,
        backlog_keep: int = 1024,
        fsync: bool = True,
    ):
        super().__init__()
        self.data_dir = data_dir
        self.snapshot_every = snapshot_every
        self.backlog_keep = backlog_keep
        self.fsync = fsync
        os.makedirs(data_dir, exist_ok=True)

        self.epoch = ""  # guarded-by: self._lock
        self.term = 0  # guarded-by: self._lock
        self.event_seq = 0  # guarded-by: self._lock
        #: the seq of the event currently being flushed to watchers —
        #: the bus server's central watcher reads it (under the same
        #: store lock the notification fires under) so bus sequence
        #: numbers stay in lockstep with the durable event stream
        self.current_event_seq = 0  # guarded-by: self._lock
        self.chain = 0  # guarded-by: self._lock
        #: rolling ring of recent encoded events ({seq, kind, event,
        #: old, new, ts}) — persisted into snapshots so a restarted
        #: server still covers resuming clients' cursors
        self._recent: List[dict] = []  # guarded-by: self._lock
        self._txn_depth = 0  # guarded-by: self._lock
        self._txn_events: List[tuple] = []  # guarded-by: self._lock
        #: events applied + logged but not yet quorum-committed (each
        #: item: (seq, kind, event, old, new)); flushed in order by
        #: flush_committed()
        self._pending_notify: List[tuple] = []  # guarded-by: self._lock
        self._records_since_snapshot = 0  # guarded-by: self._lock
        self._snapshot_seq = 0  # guarded-by: self._lock
        self._wal_f = None  # guarded-by: self._lock
        self._wal_size = 0  # guarded-by: self._lock
        self.last_fsync_ts = 0.0  # guarded-by: self._lock
        self.last_fsync_ms = 0.0  # guarded-by: self._lock
        #: replication-group membership config, or None before the
        #: group's first leader seeded it: ``{"epoch": int,
        #: "endpoints": [url, ...]}``.  Lives in the LOG (one
        #: membership record per change, replicated and recovered like
        #: any transaction) so after any crash/partition exactly one
        #: config survives — the one on the most advanced elected log.
        self.membership: Optional[dict] = None  # guarded-by: self._lock
        #: follower guard: public mutating ops are refused while this
        #: store replicates from a leader (writes arrive only through
        #: apply_replica_record / install_snapshot)
        self.read_only = False
        #: leader-side replication coordinator (bus/replication.py);
        #: None = standalone durability, commit == fsync
        self.replicator = None
        #: ``bus.leader_kill`` crash hook (daemon: os._exit(137))
        self.kill_hook = None
        self.recovered = {"snapshot": False, "wal_records": 0, "torn": False}

        with self._lock:
            self._load_meta()
            self._recover()

    # ---- meta (epoch + term) ----

    def _meta_path(self) -> str:
        return os.path.join(self.data_dir, META_FILE)

    def _load_meta(self) -> None:
        # requires-lock: self._lock
        path = self._meta_path()
        if os.path.exists(path):
            with open(path, encoding="utf-8") as f:
                meta = json.load(f)
            self.epoch = meta.get("epoch", "")
            self.term = int(meta.get("term", 0))
        if not self.epoch:
            self.epoch = uuid.uuid4().hex
            self._write_meta()

    def _write_meta(self) -> None:
        # requires-lock: self._lock
        tmp = self._meta_path() + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump({"epoch": self.epoch, "term": self.term}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._meta_path())

    def set_term(self, term: int) -> None:
        """Persist a new replication term (promotion / leader adoption)."""
        with self._lock:
            if term != self.term:
                self.term = term
                self._write_meta()

    def adopt_epoch(self, epoch: str) -> None:
        """A follower bootstrapping from a leader joins the leader's
        resume-token space (epoch is replication-group-wide, so watch
        cursors survive failover between replicas)."""
        with self._lock:
            if epoch and epoch != self.epoch:
                self.epoch = epoch
                self._write_meta()

    # ---- recovery ----

    def _wal_path(self) -> str:
        return os.path.join(self.data_dir, WAL_FILE)

    def _snapshot_path(self) -> str:
        return os.path.join(self.data_dir, SNAPSHOT_FILE)

    def _recover(self) -> None:
        # requires-lock: self._lock
        from volcano_tpu.bus import protocol

        snap_path = self._snapshot_path()
        if os.path.exists(snap_path):
            with open(snap_path, encoding="utf-8") as f:
                snap = json.load(f)
            self._install_state(snap)
            self.recovered["snapshot"] = True
            metrics.register_bus_recovery("snapshot")

        payloads, valid_len, torn = read_records(self._wal_path())
        self.recovered["torn"] = torn
        for payload in payloads:
            # codec sniffed per record: a log written by a JSON build
            # recovers under a binary-default one and vice versa
            rec = protocol.decode_record(payload)
            if rec.get("term", 0) > self.term:
                self.term = rec["term"]
            self._ingest_record(rec, payload, pend_notify=False)
        self.recovered["wal_records"] = len(payloads)
        if payloads:
            metrics.register_bus_recovery("wal_tail")
        if torn:
            log.warning(
                "wal %s had a torn tail; truncated to %d bytes "
                "(%d whole records kept)",
                self._wal_path(), valid_len, len(payloads),
            )
        if self.term:
            self._write_meta()

        # reopen for appends, truncated to the valid prefix so the next
        # record does not land after torn garbage
        self._wal_f = open(self._wal_path(), "ab")
        self._wal_f.truncate(valid_len)
        self._wal_f.seek(valid_len)
        self._wal_size = valid_len
        metrics.update_wal_size(self._wal_size)

    def _install_state(self, snap: dict) -> None:
        # requires-lock: self._lock
        from volcano_tpu.bus import protocol

        self._store.clear()
        self._owned.clear()
        for kind, objs in snap.get("objects", {}).items():
            bucket = self._store.setdefault(kind, {})
            for key, data in objs.items():
                obj = protocol.decode_obj(data)
                bucket[key] = obj
                self._register_owners(obj, key)
        self._rv = int(snap.get("rv", 0))
        self.event_seq = int(snap.get("seq", 0))
        self._snapshot_seq = self.event_seq
        self.chain = int(snap.get("chain", 0))
        if snap.get("epoch"):
            self.epoch = snap["epoch"]
        if int(snap.get("term", 0)) > self.term:
            self.term = int(snap["term"])
        if snap.get("membership") is not None:
            self.membership = dict(snap["membership"])
        self._recent = list(snap.get("backlog", []))

    def _ingest_record(self, rec: dict, payload: bytes,
                       pend_notify: bool) -> None:
        """Apply one logged record's events to the store: the ONE copy
        of the per-record bookkeeping (physical apply, recent ring,
        CRC chain, snapshot counter) shared by recovery replay and the
        follower replication path — the two must never drift or
        recovered and replicated stores diverge."""
        # requires-lock: self._lock
        ts = rec.get("ts", 0.0)
        if "membership" in rec:
            # a membership-config record: no store events, ONE synthetic
            # slot in the event-seq space (so replication cursors move
            # past it and the CRC chain covers it), config applied at
            # APPEND time — the Raft latest-config-in-log rule, which is
            # what makes "exactly one surviving config" hold when a
            # leader dies mid-change: the elected most-advanced log
            # decides, and every replica replays the same record
            self.event_seq += 1
            self.membership = dict(rec["membership"])
            self.chain = zlib.crc32(payload, self.chain)
            self._records_since_snapshot += 1
            metrics.update_membership_epoch(
                int(self.membership.get("epoch", 0))
            )
            return
        for kind, event, old_d, new_d in rec["events"]:
            self.event_seq += 1
            self._apply_event_physical(kind, event, old_d, new_d)
            self._recent.append({
                "seq": self.event_seq, "kind": kind, "event": event,
                "old": old_d, "new": new_d, "ts": ts,
            })
            if pend_notify:
                self._pending_notify.append((
                    self.event_seq, kind, event,
                    self._decode_clone(old_d), self._decode_clone(new_d),
                ))
        del self._recent[: max(0, len(self._recent) - self.backlog_keep)]
        self.chain = zlib.crc32(payload, self.chain)
        self._records_since_snapshot += 1

    def _apply_event_physical(self, kind, event, old_d, new_d) -> None:
        # requires-lock: self._lock
        from volcano_tpu.bus import protocol

        bucket = self._store.setdefault(kind, {})
        if event == DELETED:
            obj = protocol.decode_obj(old_d)
            key = self._key(obj)
            prev = bucket.pop(key, None)
            if prev is not None:
                self._unregister_owners(prev, key)
        else:
            obj = protocol.decode_obj(new_d)
            key = self._key(obj)
            prev = bucket.get(key)
            if prev is not None:
                self._unregister_owners(prev, key)
            bucket[key] = obj
            self._register_owners(obj, key)
            rv = obj.metadata.resource_version or 0
            if rv > self._rv:
                self._rv = rv

    # ---- the write-ahead transaction wrapper ----

    @contextlib.contextmanager
    def _txn(self):
        """One store transaction: buffer the watch notifications the op
        produces, then (outermost level only) append one WAL record and
        fsync, wait for the replication commit, and flush the buffered
        notifications — in that order, so durability precedes every
        observer.

        The quorum wait happens OUTSIDE the store lock: application +
        WAL append are locked (sequencing), but parking the lock until
        followers ack would block every read, watch establishment, and
        — fatally — the ``bus_status`` probes a not-yet-attached
        follower needs to FIND this leader, wedging a fresh-promoted
        leader into a quorum-stall spiral (the loadgen failover drill
        caught it).  The cost is a wider read-uncommitted window on the
        leader, already documented in the known-gaps entry."""
        last_seq = 0
        replicator = None
        demoted = False
        error: Optional[BaseException] = None
        with self._lock:
            self._txn_depth += 1
            try:
                yield
            except BaseException as e:  # noqa: BLE001 — re-raised below,
                # AFTER the commit/flush bookkeeping: an op that raised
                # after earlier nested mutations (defensive — current
                # ops never do) must not strand buffered notifications
                error = e
            finally:
                self._txn_depth -= 1
                if self._txn_depth == 0 and self._txn_events:
                    events = self._txn_events
                    self._txn_events = []
                    last_seq = self._commit_txn(events)
                    # captured UNDER the lock, alongside the append:
                    # role transitions (set_replication) synchronize on
                    # the same lock, so this snapshot is exactly the
                    # regime the record was logged in — reading
                    # self.replicator after release could see a
                    # just-deposed leader's None and ack without quorum
                    replicator = self.replicator
                    demoted = self.read_only
        if last_seq:
            if replicator is not None:
                from volcano_tpu import obs

                _q0 = time.perf_counter()
                if obs.enabled() and obs.current() is not None:
                    # quorum wait parks OUTSIDE the store lock (see
                    # above) — the span shows replication, not fsync,
                    # as the write's tail latency when followers lag
                    with obs.span("repl:quorum_wait", cat="repl",
                                  args={"seq": last_seq}):
                        committed = replicator.wait_commit(last_seq)
                else:
                    committed = replicator.wait_commit(last_seq)
                metrics.observe_repl_quorum_wait(
                    time.perf_counter() - _q0
                )
                self.flush_committed(last_seq if committed
                                     else replicator.commit_seq())
                if error is None and not committed:
                    # durable locally, may commit later (the
                    # coordinator's flusher delivers the parked
                    # notifications then) — but the CALLER is not acked
                    raise ApiError(
                        "replication quorum timeout — write not "
                        "acknowledged"
                    )
            elif demoted:
                # deposed mid-write: the record exists only locally and
                # the follower resync will reconcile it away — nothing
                # is flushed, nothing is acked
                if error is None:
                    raise ApiError(
                        "store demoted to follower mid-write — not "
                        "acknowledged"
                    )
            else:
                self.flush_committed(last_seq)
        if error is not None:
            raise error

    def _notify(self, kind: str, event: str, old, new) -> None:
        # requires-lock: self._lock
        if self._txn_depth > 0:
            self._txn_events.append((kind, event, old, new))
        else:
            super()._notify(kind, event, old, new)

    def _check_writable(self) -> None:
        if self.read_only:
            raise ApiError(
                "store is a replication follower — writes go to the leader"
            )

    def create(self, obj):
        self._check_writable()
        with self._txn():
            return super().create(obj)

    def update(self, obj, expected_rv: Optional[int] = None):
        self._check_writable()
        with self._txn():
            return super().update(obj, expected_rv=expected_rv)

    def update_status(self, obj):
        self._check_writable()
        with self._txn():
            return super().update_status(obj)

    def delete(self, kind: str, namespace: str, name: str):
        self._check_writable()
        with self._txn():
            return super().delete(kind, namespace, name)

    def cas_bind(self, namespace: str, name: str, hostname: str,
                 expected_rv: Optional[int] = None):
        self._check_writable()
        with self._txn():
            return super().cas_bind(namespace, name, hostname,
                                    expected_rv=expected_rv)

    def commit_batch(self, binds=(), evicts=(), events=(), conditions=(),
                     pod_groups=()):
        self._check_writable()
        with self._txn():
            return super().commit_batch(
                binds=binds, evicts=evicts, events=events,
                conditions=conditions, pod_groups=pod_groups,
            )

    def txn_commit(self, binds=()):
        """The atomic multi-``cas_bind`` transaction as ONE WAL record:
        all N bind events buffer through ``_txn`` and land in a single
        fsynced record (the exact atomic ``commit_batch`` path), so
        replication ships the gang as a unit and recovery replays it
        whole or not at all — a crash can never resurrect half a gang.
        An aborted transaction mutates nothing and therefore logs
        nothing."""
        self._check_writable()
        with self._txn():
            return super().txn_commit(binds=binds)

    # ---- membership-config records (bus/replication.py) ----

    def membership_config(self) -> Optional[dict]:
        """The latest membership config applied to this log (None until
        the group's first leader seeds one)."""
        with self._lock:
            return dict(self.membership) if self.membership else None

    def log_membership(self, membership: dict) -> int:
        """Append ONE membership-config WAL record and hand it to the
        replication outbox.  Returns the record's event seq; the CALLER
        (the ReplicaManager, which owns the single-change discipline)
        re-counts the quorum under the new config and waits for the
        commit — appending and waiting are split exactly so the config
        can take effect at append time (``_ingest_record``'s rule).  A
        failed append (``wal.write_fail``) applies nothing."""
        from volcano_tpu.bus import protocol

        with self._lock:
            fp = _get_fault_plane()
            record = {
                "membership": dict(membership),
                "seq0": self.event_seq,
                "term": self.term,
                "ts": time.time(),
            }
            payload = protocol.encode_record(record, codec=record_codec())
            self._append_wal(payload, fp)  # raises WalError → no change
            self.chain = zlib.crc32(payload, self.chain)
            self.event_seq += 1
            self.membership = dict(membership)
            self._records_since_snapshot += 1
            if self.replicator is not None:
                self.replicator.leader_append(
                    self.event_seq, self.term, self.chain, payload,
                    record["ts"], config=True,
                )
            seq = self.event_seq
            if self._records_since_snapshot >= self.snapshot_every:
                self._write_snapshot()
        metrics.update_membership_epoch(int(membership.get("epoch", 0)))
        return seq

    # ---- commit path ----

    def _commit_txn(self, events: List[tuple]) -> int:
        """Append one WAL record for the buffered events and hand it to
        the replication outbox.  Returns the transaction's last event
        seq; the CALLER (outside the lock) waits for the quorum and
        flushes the notifications."""
        # requires-lock: self._lock
        from volcano_tpu import faults
        from volcano_tpu.bus import protocol

        fp = faults.get_plane()
        if fp.enabled and self.kill_hook is not None and fp.should("bus.leader_kill"):
            # the SIGKILL-mid-commit-burst chaos point: the record may
            # or may not have hit disk — exactly the window the
            # recovery contract covers
            log.error("bus.leader_kill fired: apiserver going down hard")
            self.kill_hook()
        ts = time.time()
        encoded = [
            (kind, event, protocol.encode_obj(old), protocol.encode_obj(new))
            for kind, event, old, new in events
        ]
        seq0 = self.event_seq
        record = {
            "events": encoded,
            "rv": self._rv,
            "seq0": seq0,
            "term": self.term,
            "ts": ts,
        }
        payload = protocol.encode_record(record, codec=record_codec())
        try:
            self._append_wal(payload, fp)
        except WalError:
            # the record never became durable, so the op will not be
            # acked — ROLL BACK the in-memory mutations too, or reads
            # (and AlreadyExists-based retries) would observe a write
            # that a restart erases
            self._rollback_events(events)
            raise
        self.chain = zlib.crc32(payload, self.chain)
        last_seq = seq0 + len(encoded)
        self.event_seq = last_seq
        for i, (kind, event, old_d, new_d) in enumerate(encoded):
            seq = seq0 + i + 1
            self._recent.append({
                "seq": seq, "kind": kind, "event": event,
                "old": old_d, "new": new_d, "ts": ts,
            })
            self._pending_notify.append(
                (seq, kind, event, events[i][2], events[i][3])
            )
        del self._recent[: max(0, len(self._recent) - self.backlog_keep)]
        # hand the record to the replication outbox (no-op standalone);
        # the quorum wait happens outside the store lock, in _txn
        if self.replicator is not None:
            self.replicator.leader_append(last_seq, self.term, self.chain,
                                          payload, ts)
        self._records_since_snapshot += 1
        if self._records_since_snapshot >= self.snapshot_every:
            self._write_snapshot()
        return last_seq

    def _rollback_events(self, events: List[tuple]) -> None:
        """Undo a failed transaction's in-memory mutations from its
        buffered events, newest first (the clones carry the exact prior
        state, cascade deletions included)."""
        # requires-lock: self._lock
        for kind, event, old, new in reversed(events):
            bucket = self._store.setdefault(kind, {})
            if event == DELETED:
                key = self._key(old)
                bucket[key] = old
                self._register_owners(old, key)
            elif event == ADDED:
                key = self._key(new)
                cur = bucket.pop(key, None)
                if cur is not None:
                    self._unregister_owners(cur, key)
            else:  # MODIFIED
                key = self._key(new)
                cur = bucket.get(key)
                if cur is not None:
                    self._unregister_owners(cur, key)
                bucket[key] = old
                self._register_owners(old, key)

    def _append_wal(self, payload: bytes, fp) -> None:
        # requires-lock: self._lock
        if fp.enabled and fp.should("wal.write_fail"):
            raise WalError("fault-injected: wal append failed")
        if fp.enabled and fp.should("wal.torn_tail"):
            # crash-mid-write: a partial record reaches disk, the op
            # dies unacked; recovery must truncate this torn tail
            framed = _REC_HEADER.pack(len(payload), zlib.crc32(payload)) + payload
            torn = framed[: max(1, len(framed) // 2)]
            self._wal_f.write(torn)
            self._wal_f.flush()
            os.fsync(self._wal_f.fileno())
            self._wal_size += len(torn)
            raise WalError("fault-injected: torn wal write")
        append_record(self._wal_f, payload)
        self._wal_f.flush()
        if fp.enabled and fp.should("wal.fsync_delay"):
            time.sleep(fp.param_ms("wal.fsync_delay") / 1e3)
        t0 = time.perf_counter()
        if self.fsync:
            os.fsync(self._wal_f.fileno())
        dt = time.perf_counter() - t0
        self.last_fsync_ts = time.time()
        self.last_fsync_ms = round(dt * 1e3, 3)
        metrics.observe_wal_fsync(dt)
        from volcano_tpu import obs

        if obs.enabled() and obs.current() is not None:
            # flight recorder: the durability cost lands in the traced
            # request's waterfall.  Context-gated (and emission is a
            # bounded ring append — obs/channel.py) so telemetry never
            # extends this store-lock hold with I/O.
            obs.complete("wal:fsync", dt, cat="wal",
                         args={"bytes": len(payload)})
        self._wal_size += _REC_HEADER.size + len(payload)
        metrics.update_wal_size(self._wal_size)

    def _flush_pending_locked(self, commit_seq: int) -> None:
        # requires-lock: self._lock
        while self._pending_notify and self._pending_notify[0][0] <= commit_seq:
            seq, kind, event, old, new = self._pending_notify.pop(0)
            self.current_event_seq = seq
            super()._notify(kind, event, old, new)

    def flush_committed(self, commit_seq: int) -> None:
        """Deliver parked notifications up to ``commit_seq`` — the late
        path for transactions whose quorum ack arrived after their
        request timed out, and the follower's apply→commit gap."""
        with self._lock:
            self._flush_pending_locked(commit_seq)

    # ---- snapshot ----

    def _snapshot_state(self) -> dict:
        """The full-state snapshot dict — the ONE shape shared by disk
        rotation and follower bootstrap (``repl_snapshot``), so the two
        recovery sources can never drift field-by-field."""
        # requires-lock: self._lock
        return {
            "epoch": self.epoch,
            "term": self.term,
            "rv": self._rv,
            "seq": self.event_seq,
            "chain": self.chain,
            "membership": (
                dict(self.membership) if self.membership else None
            ),
            "objects": {
                kind: {key: obj.to_dict() for key, obj in bucket.items()}
                for kind, bucket in self._store.items() if bucket
            },
            "backlog": self._recent[-self.backlog_keep:],
        }

    def _write_snapshot(self) -> None:
        # requires-lock: self._lock
        snap = self._snapshot_state()
        tmp = self._snapshot_path() + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(snap, f, separators=(",", ":"))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._snapshot_path())
        # rotate the WAL: records up to here live in the snapshot now
        self._wal_f.close()
        self._wal_f = open(self._wal_path(), "wb")
        self._wal_size = 0
        self._snapshot_seq = self.event_seq
        self._records_since_snapshot = 0
        metrics.update_wal_size(0)

    def snapshot_now(self) -> None:
        """Force a snapshot rotation (tests, graceful shutdown)."""
        with self._lock:
            self._write_snapshot()

    # ---- replication surface (called by bus/replication.py) ----

    def set_replication(self, replicator, read_only: bool) -> None:
        """Atomically install a replication regime.  Runs under the
        store lock so a role transition serializes against in-flight
        transactions: a transaction observes either the old regime
        (its coordinator, later shutdown, refuses the ack) or the new
        one — never a half-applied mix that acks without quorum."""
        with self._lock:
            self.replicator = replicator
            self.read_only = read_only

    def apply_replica_record(self, payload: bytes, sync: bool = True) -> int:
        """Follower path: append the leader's record to the local WAL,
        apply it physically, park its notifications until the commit
        point covers them.  Returns the new applied seq.  ``sync=False``
        defers the fsync to the batch tail (the leader already holds
        the record durable, so a follower crash between appends loses
        nothing a re-pull would not re-ship)."""
        from volcano_tpu.bus import protocol

        with self._lock:
            rec = protocol.decode_record(payload)
            fp = _get_fault_plane()
            if fp.enabled and fp.should("wal.write_fail"):
                raise WalError("fault-injected: wal append failed")
            append_record(self._wal_f, payload)
            self._wal_f.flush()
            if self.fsync and sync:
                t0 = time.perf_counter()
                os.fsync(self._wal_f.fileno())
                dt = time.perf_counter() - t0
                self.last_fsync_ts = time.time()
                self.last_fsync_ms = round(dt * 1e3, 3)
                metrics.observe_wal_fsync(dt)
            self._wal_size += _REC_HEADER.size + len(payload)
            metrics.update_wal_size(self._wal_size)
            if rec.get("term", 0) > self.term:
                self.term = rec["term"]
                self._write_meta()
            self._ingest_record(rec, payload, pend_notify=True)
            if self._records_since_snapshot >= self.snapshot_every:
                self._write_snapshot()
            return self.event_seq

    def _decode_clone(self, data):
        # requires-lock: self._lock
        from volcano_tpu.bus import protocol

        return protocol.decode_obj(data)

    def dump_snapshot(self) -> dict:
        """Full-state snapshot for a (re)joining follower."""
        with self._lock:
            return self._snapshot_state()

    def install_snapshot(self, snap: dict) -> None:
        """Follower resync: replace the whole store with the leader's
        snapshot (bootstrap, or a divergent/lagging log that the
        leader's retained window no longer covers)."""
        with self._lock:
            self._install_state(snap)
            self._pending_notify = []
            self._write_meta()
            self._write_snapshot()

    # ---- status + introspection ----

    def recent_events(self) -> List[dict]:
        """The recovered/live recent-event ring — the bus server seeds
        its watch backlog from this at start so resuming clients get
        their missed suffix from a restarted process."""
        with self._lock:
            return list(self._recent)

    def bus_status(self) -> dict:
        from volcano_tpu.bus import protocol

        with self._lock:
            try:
                snap_size = os.path.getsize(self._snapshot_path())
            except OSError:
                snap_size = 0
            return {
                "role": "leader" if self.replicator is not None
                else ("follower" if self.read_only else "standalone"),
                "persistent": True,
                "epoch": self.epoch,
                "term": self.term,
                "seq": self.event_seq,
                "rv": self._rv,
                "wal_size_bytes": self._wal_size,
                "wal_records": self._records_since_snapshot,
                "snapshot_size_bytes": snap_size,
                "snapshot_seq": self._snapshot_seq,
                "last_fsync_ts": self.last_fsync_ts,
                "last_fsync_ms": self.last_fsync_ms,
                "wal_codec": record_codec() or (
                    protocol.CODEC_BINARY if protocol.HAS_BINARY
                    else protocol.CODEC_JSON
                ),
                **({
                    "membership_epoch": int(self.membership.get("epoch", 0)),
                    "membership": sorted(
                        self.membership.get("endpoints", ())
                    ),
                } if self.membership else {}),
                **({"metrics_address": self.metrics_address}
                   if getattr(self, "metrics_address", "") else {}),
            }

    def close(self) -> None:
        with self._lock:
            if self._wal_f is not None:
                self._wal_f.close()
                self._wal_f = None


def _get_fault_plane():
    from volcano_tpu import faults

    return faults.get_plane()
