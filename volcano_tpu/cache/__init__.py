from volcano_tpu.cache.interface import Binder, Cache, Evictor, StatusUpdater
from volcano_tpu.cache.cache import (
    DefaultBinder,
    DefaultEvictor,
    DefaultStatusUpdater,
    SchedulerCache,
)

__all__ = [
    "Binder",
    "Cache",
    "Evictor",
    "StatusUpdater",
    "DefaultBinder",
    "DefaultEvictor",
    "DefaultStatusUpdater",
    "SchedulerCache",
]
