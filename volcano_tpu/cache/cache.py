"""SchedulerCache — mutex-guarded mirror of cluster state.

Reference: pkg/scheduler/cache/cache.go + event_handlers.go.  Fed by event
handlers (wired to informers in production, called directly in tests —
the reference's own unit-test pattern, allocate_test.go:155-222); produces
deep-copied snapshots; executes bind/evict side effects asynchronously with
an errTasks resync queue.
"""

from __future__ import annotations

import threading
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Dict, List, Optional

from volcano_tpu.api import (
    ClusterInfo,
    JobInfo,
    NodeInfo,
    QueueInfo,
    TaskInfo,
    TaskStatus,
    new_task_info,
)
from volcano_tpu.api.job_info import get_job_id
from volcano_tpu.api.queue_info import NamespaceCollection
from volcano_tpu.apis import core, scheduling, scheme
from volcano_tpu.cache.interface import Binder, Cache, Evictor, StatusUpdater
from volcano_tpu.utils.logging import get_logger

log = get_logger(__name__)


def is_terminated(status: TaskStatus) -> bool:
    return status in (TaskStatus.Succeeded, TaskStatus.Failed)


class DefaultBinder(Binder):
    """POSTs the pod binding through the API client (cache.go:122-134)."""

    def __init__(self, client):
        self.client = client

    def bind(self, task: TaskInfo, hostname: str) -> None:
        self.client.bind_pod(task.namespace, task.name, hostname)


class DefaultEvictor(Evictor):
    """Deletes the pod (cache.go:141-149)."""

    def __init__(self, client):
        self.client = client

    def evict(self, task: TaskInfo) -> None:
        self.client.delete_pod(task.namespace, task.name)


class DefaultStatusUpdater(StatusUpdater):
    """cache.go defaultStatusUpdater."""

    def __init__(self, client):
        self.client = client

    def update_pod_condition(self, task: TaskInfo, reason: str, message: str) -> None:
        self.client.update_pod_condition(task.namespace, task.name, reason, message)

    def update_pod_group(self, pg: scheduling.PodGroup):
        return self.client.update_pod_group(pg)


class SchedulerCache(Cache):
    def __init__(
        self,
        binder: Optional[Binder] = None,
        evictor: Optional[Evictor] = None,
        status_updater: Optional[StatusUpdater] = None,
        scheduler_name: str = "volcano",
        default_queue: str = "default",
        default_priority: int = 0,
        sync_side_effects: bool = True,
        client=None,
    ):
        self._mutex = threading.RLock()
        self.scheduler_name = scheduler_name
        self.default_queue = default_queue
        self.default_priority = default_priority

        self.jobs: Dict[str, JobInfo] = {}
        self.nodes: Dict[str, NodeInfo] = {}
        self.queues: Dict[str, QueueInfo] = {}
        self.priority_classes: Dict[str, core.PriorityClass] = {}
        self.namespace_collections: Dict[str, NamespaceCollection] = {}
        #: PVCs keyed "ns/name" (pvcInformer, cache.go:415-421)
        self.pvcs: Dict[str, core.PersistentVolumeClaim] = {}

        self.client = client
        self.binder = binder or (DefaultBinder(client) if client else None)
        self.evictor = evictor or (DefaultEvictor(client) if client else None)
        self.status_updater = status_updater or (
            DefaultStatusUpdater(client) if client else None
        )

        #: tasks whose async side effects failed; re-synced from API truth
        #: (cache.go:687-709 errTasks workqueue).
        self.err_tasks: List[TaskInfo] = []

        # The reference fires bind/evict in goroutines (cache.go:596-612).
        # sync_side_effects=True (default) keeps them on-thread for
        # deterministic tests and simpler failure semantics.
        self._sync = sync_side_effects
        self._pool: Optional[ThreadPoolExecutor] = None
        self._pending: List[Future] = []

    # ---- lifecycle ----

    def run(self) -> None:
        if not self._sync and self._pool is None:
            self._pool = ThreadPoolExecutor(max_workers=8)
        if self.client is not None:
            self.client.watch(self)

    def wait_for_cache_sync(self) -> bool:
        return True

    def flush(self) -> None:
        """Wait for async side effects (test/shutdown aid)."""
        for f in list(self._pending):
            f.result()
        self._pending.clear()

    def _run_effect(self, fn, *args) -> None:
        if self._sync or self._pool is None:
            fn(*args)
        else:
            self._pending.append(self._pool.submit(fn, *args))

    # ---- event handlers: pods (event_handlers.go:39-254) ----

    def _get_or_create_job(self, ti: TaskInfo) -> Optional[JobInfo]:
        """event_handlers.go:44-58 — only pods carrying a PodGroup
        annotation get a job; others are node-accounting-only."""
        if not ti.job:
            return None
        if ti.job not in self.jobs:
            self.jobs[ti.job] = JobInfo(ti.job)
        return self.jobs[ti.job]

    def _add_task(self, ti: TaskInfo) -> None:
        """event_handlers.go:60-79."""
        job = self._get_or_create_job(ti)
        if job is not None:
            job.add_task_info(ti)
        if ti.node_name:
            if ti.node_name not in self.nodes:
                self.nodes[ti.node_name] = NodeInfo(None)
                self.nodes[ti.node_name].name = ti.node_name
            if not is_terminated(ti.status):
                try:
                    self.nodes[ti.node_name].add_task(ti)
                except ValueError as e:
                    # Transient double-add when our own bind's watch echo races
                    # the in-cache accounting — the reference logs and
                    # keeps the node-held task (event_handlers.go AddPod
                    # error path); state converges on the next update.
                    log.debug("add task to node: %s", e)

    def _delete_task(self, ti: TaskInfo) -> None:
        """event_handlers.go:126-151."""
        if ti.job and ti.job in self.jobs:
            job = self.jobs[ti.job]
            stored = job.tasks.get(ti.uid)
            if stored is not None:
                job.delete_task_info(stored)
        if ti.node_name and ti.node_name in self.nodes:
            node = self.nodes[ti.node_name]
            if ti.uid in node.tasks:
                node.remove_task(ti)

    def add_pod(self, pod: core.Pod) -> None:
        with self._mutex:
            self._add_task(new_task_info(pod))

    def update_pod(self, old_pod: core.Pod, new_pod: core.Pod) -> None:
        with self._mutex:
            self._delete_task(new_task_info(old_pod))
            self._add_task(new_task_info(new_pod))

    def delete_pod(self, pod: core.Pod) -> None:
        with self._mutex:
            self._delete_task(new_task_info(pod))

    # ---- event handlers: nodes (event_handlers.go:255-354) ----

    def add_node(self, node: core.Node) -> None:
        with self._mutex:
            name = node.metadata.name
            if name in self.nodes:
                self.nodes[name].set_node(node)
            else:
                self.nodes[name] = NodeInfo(node)

    def update_node(self, old_node: core.Node, new_node: core.Node) -> None:
        with self._mutex:
            name = new_node.metadata.name
            if name in self.nodes:
                self.nodes[name].set_node(new_node)
            else:
                self.nodes[name] = NodeInfo(new_node)

    def delete_node(self, node: core.Node) -> None:
        with self._mutex:
            self.nodes.pop(node.metadata.name, None)

    # ---- event handlers: podgroups (event_handlers.go:356-581) ----

    def add_pod_group(self, pg: scheduling.PodGroup) -> None:
        with self._mutex:
            job_id = pg.key()
            if job_id not in self.jobs:
                self.jobs[job_id] = JobInfo(job_id)
            self.jobs[job_id].set_pod_group(pg)

    def update_pod_group(self, old_pg, new_pg: scheduling.PodGroup) -> None:
        self.add_pod_group(new_pg)

    def delete_pod_group(self, pg: scheduling.PodGroup) -> None:
        with self._mutex:
            job = self.jobs.get(pg.key())
            if job is not None:
                job.pod_group = None
                # Jobs without scheduling spec drop out of snapshots; GC'd
                # when tasks drain (cleanup worker in the reference).
                if not job.tasks:
                    del self.jobs[pg.key()]

    # ---- dual-version handlers (cache.go:393-424: the v1alpha1
    # informer set converts BOTH old and new through the scheme, then
    # delegates) ----

    def add_pod_group_v1alpha1(self, pg) -> None:
        self.add_pod_group(scheme.pod_group_v1alpha1_to_hub(pg))

    def update_pod_group_v1alpha1(self, old_pg, new_pg) -> None:
        self.update_pod_group(
            scheme.pod_group_v1alpha1_to_hub(old_pg) if old_pg is not None else None,
            scheme.pod_group_v1alpha1_to_hub(new_pg),
        )

    def delete_pod_group_v1alpha1(self, pg) -> None:
        self.delete_pod_group(scheme.pod_group_v1alpha1_to_hub(pg))

    def add_queue_v1alpha1(self, queue) -> None:
        self.add_queue(scheme.queue_v1alpha1_to_hub(queue))

    def update_queue_v1alpha1(self, old_queue, new_queue) -> None:
        self.update_queue(
            scheme.queue_v1alpha1_to_hub(old_queue) if old_queue is not None else None,
            scheme.queue_v1alpha1_to_hub(new_queue),
        )

    def delete_queue_v1alpha1(self, queue) -> None:
        self.delete_queue(scheme.queue_v1alpha1_to_hub(queue))

    # ---- event handlers: queues (event_handlers.go:696-863) ----

    def add_queue(self, queue: scheduling.Queue) -> None:
        with self._mutex:
            qi = QueueInfo(queue)
            self.queues[qi.uid] = qi

    def update_queue(self, old_queue, new_queue: scheduling.Queue) -> None:
        self.add_queue(new_queue)

    def delete_queue(self, queue: scheduling.Queue) -> None:
        with self._mutex:
            self.queues.pop(queue.metadata.name, None)

    # ---- event handlers: priority classes (event_handlers.go:865-958) ----

    def add_priority_class(self, pc: core.PriorityClass) -> None:
        with self._mutex:
            self.priority_classes[pc.metadata.name] = pc
            if pc.global_default:
                self.default_priority = pc.value

    def delete_priority_class(self, pc: core.PriorityClass) -> None:
        with self._mutex:
            self.priority_classes.pop(pc.metadata.name, None)
            if pc.global_default:
                self.default_priority = 0

    # ---- PVC handlers (pvcInformer wiring, cache.go:415-421) ----

    def add_pvc(self, pvc: core.PersistentVolumeClaim) -> None:
        with self._mutex:
            self.pvcs[f"{pvc.metadata.namespace}/{pvc.metadata.name}"] = pvc

    def update_pvc(self, old, new: core.PersistentVolumeClaim) -> None:
        self.add_pvc(new)

    def delete_pvc(self, pvc: core.PersistentVolumeClaim) -> None:
        with self._mutex:
            self.pvcs.pop(f"{pvc.metadata.namespace}/{pvc.metadata.name}", None)

    # ---- event handlers: resource quotas (event_handlers.go:961-1036) ----

    def add_resource_quota(self, namespace: str, quota_name: str, weight: Optional[int]) -> None:
        with self._mutex:
            coll = self.namespace_collections.setdefault(
                namespace, NamespaceCollection(namespace)
            )
            coll.update(quota_name, weight)

    def delete_resource_quota(self, namespace: str, quota_name: str) -> None:
        with self._mutex:
            coll = self.namespace_collections.get(namespace)
            if coll is not None:
                coll.delete(quota_name)

    # ---- snapshot (cache.go:712-790) ----

    def snapshot(self) -> ClusterInfo:
        with self._mutex:
            snapshot = ClusterInfo()

            for node in self.nodes.values():
                if not node.ready():
                    continue
                snapshot.nodes[node.name] = node.clone()

            for queue in self.queues.values():
                snapshot.queues[queue.uid] = queue.clone()

            for key, pvc in self.pvcs.items():
                snapshot.pvcs[key] = pvc.clone()

            for name, coll in self.namespace_collections.items():
                snapshot.namespace_info[name] = coll.snapshot()

            for job in self.jobs.values():
                # No scheduling spec → not schedulable (cache.go:765-770).
                if job.pod_group is None:
                    continue
                if job.queue not in snapshot.queues:
                    continue
                job.priority = self.default_priority
                pri_name = job.pod_group.spec.priority_class_name
                pc = self.priority_classes.get(pri_name)
                if pc is not None:
                    job.priority = pc.value
                snapshot.jobs[job.uid] = job.clone()
                snapshot.jobs[job.uid].priority = job.priority

            return snapshot

    # ---- side effects (cache.go:498-615) ----

    def _find_job_and_task(self, task_info: TaskInfo):
        job = self.jobs.get(task_info.job)
        if job is None:
            raise KeyError(f"failed to find job {task_info.job}")
        task = job.tasks.get(task_info.uid)
        if task is None:
            raise KeyError(
                f"failed to find task in status {task_info.status.name} by id {task_info.uid}"
            )
        return job, task

    def bind(self, task_info: TaskInfo, hostname: str) -> None:
        """cache.go:557-615."""
        with self._mutex:
            job, task = self._find_job_and_task(task_info)
            node = self.nodes.get(hostname)
            if node is None:
                raise KeyError(
                    f"failed to bind task {task.uid} to host {hostname}: host not found"
                )
            job.update_task_status(task, TaskStatus.Binding)
            task.node_name = hostname
            node.add_task(task)

        def effect():
            try:
                if self.binder is not None:
                    self.binder.bind(task, hostname)
            except Exception as e:  # noqa: BLE001
                log.error("bind of %s/%s failed: %s", task.namespace, task.name, e)
                self._record_event(
                    task, "Warning", "FailedScheduling",
                    f"failed to bind to {hostname}: {e}",
                )
                self.resync_task(task)
            else:
                # cache.go:600-610 — the Scheduled audit event
                self._record_event(
                    task, "Normal", "Scheduled",
                    f"Successfully assigned {task.namespace}/{task.name}"
                    f" to {hostname}",
                )

        self._run_effect(effect)

    def bind_batch(self, pairs) -> None:
        """Bind many (task_info, hostname) pairs: the same per-task state
        mutations as :meth:`bind` under ONE mutex hold, with the
        binder/event effects submitted as one job that preserves task
        order.  This is the bulk-commit path for fully-placed device
        sessions (actions/fast_apply.py) — at 50k binds the per-call
        mutex/submit overhead of bind() dominates the real work."""
        bound = []
        with self._mutex:
            # resolve everything before mutating anything, so a bad pair
            # cannot leave earlier tasks mutated with their binder
            # effects dropped (per-task bind() submits effects pairwise;
            # the batch must not weaken that failure contract)
            resolved = []
            for task_info, hostname in pairs:
                job, task = self._find_job_and_task(task_info)
                node = self.nodes.get(hostname)
                if node is None:
                    raise KeyError(
                        f"failed to bind task {task.uid} to host {hostname}:"
                        " host not found"
                    )
                resolved.append((job, task, node, hostname))
            for job, task, node, hostname in resolved:
                job.update_task_status(task, TaskStatus.Binding)
                task.node_name = hostname
                node.add_task(task)
                bound.append((task, hostname))

        def effect():
            for task, hostname in bound:
                try:
                    if self.binder is not None:
                        self.binder.bind(task, hostname)
                except Exception as e:  # noqa: BLE001
                    log.error(
                        "bind of %s/%s failed: %s", task.namespace, task.name, e
                    )
                    self._record_event(
                        task, "Warning", "FailedScheduling",
                        f"failed to bind to {hostname}: {e}",
                    )
                    self.resync_task(task)
                else:
                    self._record_event(
                        task, "Normal", "Scheduled",
                        f"Successfully assigned {task.namespace}/{task.name}"
                        f" to {hostname}",
                    )

        self._run_effect(effect)

    def _record_event(self, task: TaskInfo, type_: str, reason: str, message: str) -> None:
        """Record a pod-scoped Event through the bus (the user-facing
        audit trail, cache.go:832-867, 600-610); best-effort."""
        if self.client is None or not hasattr(self.client, "record_event"):
            return
        try:
            self.client.record_event(
                task.namespace,
                {"kind": "Pod", "namespace": task.namespace, "name": task.name},
                type_,
                reason,
                message,
            )
        except Exception as e:  # noqa: BLE001 — events must never fail ops
            log.error("record event failed: %s", e)

    def evict(self, task_info: TaskInfo, reason: str) -> None:
        """cache.go:498-554."""
        with self._mutex:
            job, task = self._find_job_and_task(task_info)
            node = self.nodes.get(task.node_name)
            if node is None:
                raise KeyError(
                    f"failed to evict task {task.uid}: host {task.node_name} not found"
                )
            job.update_task_status(task, TaskStatus.Releasing)
            node.update_task(task)

        def effect():
            try:
                if self.evictor is not None:
                    self.evictor.evict(task)
            except Exception as e:  # noqa: BLE001
                log.error("evict of %s/%s failed: %s", task.namespace, task.name, e)
                self.resync_task(task)
            else:
                # cache.go:528 — the Evict audit event (reason carries the
                # action: "preempt" / "reclaim")
                self._record_event(
                    task, "Normal", "Evict",
                    f"Evicted {task.namespace}/{task.name}: {reason}",
                )

        self._run_effect(effect)

    # ---- volume binding (cache.go:243-258, 617-623) ----

    @staticmethod
    def task_claim_names(task: TaskInfo) -> List[str]:
        """PVC claim names referenced by the task's pod."""
        if task.pod is None:
            return []
        claims = []
        for vol in task.pod.spec.volumes:
            ref = vol.source.get("persistentVolumeClaim")
            if ref and ref.get("claimName"):
                claims.append(ref["claimName"])
        return claims

    def allocate_volumes(self, task: TaskInfo, hostname: str) -> None:
        """AssumePodVolumes analogue: record whether every referenced PVC
        is already Bound (task.volume_ready), so bind_volumes knows
        whether there is provisioning left to do (cache.go:243-249)."""
        with self._mutex:
            all_bound = True
            for claim in self.task_claim_names(task):
                pvc = self.pvcs.get(f"{task.namespace}/{claim}")
                if pvc is None or pvc.status.get("phase") != "Bound":
                    all_bound = False
            task.volume_ready = all_bound

    def bind_volumes(self, task: TaskInfo) -> None:
        """BindPodVolumes analogue (cache.go:251-258): dynamically
        provision still-pending PVCs that carry a storage class — write
        the selected node, a volume name, and phase Bound through the
        client.  Raises on a PVC that cannot be bound (no storage class,
        nothing provisionable) — the commit path converts that into an
        unbind + resync, exactly like an apiserver bind failure."""
        if task.volume_ready:
            return
        for claim in self.task_claim_names(task):
            key = f"{task.namespace}/{claim}"
            with self._mutex:
                pvc = self.pvcs.get(key)
            if pvc is None:
                raise KeyError(f"persistentvolumeclaim {key} not found")
            if pvc.status.get("phase") == "Bound":
                continue
            if not pvc.spec.get("storageClassName"):
                raise RuntimeError(
                    f"pod has unbound immediate PersistentVolumeClaims: {key}"
                )
            pvc = pvc.clone()
            pvc.metadata.annotations["volume.kubernetes.io/selected-node"] = (
                task.node_name
            )
            pvc.spec["volumeName"] = f"pv-{pvc.metadata.name}"
            pvc.status["phase"] = "Bound"
            if self.client is not None and hasattr(self.client, "update_pvc"):
                self.client.update_pvc(pvc)
            self.add_pvc(pvc)
        task.volume_ready = True

    def resync_task(self, task: TaskInfo) -> None:
        """Requeue for resync from API truth (cache.go:687-709)."""
        with self._mutex:
            self.err_tasks.append(task)
        if self.client is not None:
            self.process_resync_task()

    def process_resync_task(self) -> None:
        """Re-fetch the pod and rebuild the task (cache.go syncTask)."""
        with self._mutex:
            if not self.err_tasks:
                return
            task = self.err_tasks.pop(0)
        if self.client is None:
            return
        pod = self.client.get_pod(task.namespace, task.name)
        with self._mutex:
            self._delete_task(task)
            if pod is not None:
                self._add_task(new_task_info(pod))

    # ---- status writeback ----

    def record_job_status_event(self, job: JobInfo) -> None:
        """cache.go:832-867 — pod conditions for unschedulable tasks."""
        if self.status_updater is None:
            return
        base_message = job.job_fit_errors
        for task in job.tasks.values():
            if task.status != TaskStatus.Pending:
                continue
            fit_errors = job.nodes_fit_errors.get(task.uid)
            message = fit_errors.error() if fit_errors is not None else base_message
            self._record_event(task, "Warning", "Unschedulable", message)
            try:
                self.status_updater.update_pod_condition(task, "Unschedulable", message)
            except Exception as e:  # noqa: BLE001
                log.error("update pod condition failed: %s", e)

    def update_job_status(self, job: JobInfo) -> Optional[scheduling.PodGroup]:
        """cache.go:871-894."""
        self.record_job_status_event(job)
        if self.status_updater is None or job.pod_group is None:
            return job.pod_group
        return self.status_updater.update_pod_group(job.pod_group)
