"""SchedulerCache — mutex-guarded mirror of cluster state.

Reference: pkg/scheduler/cache/cache.go + event_handlers.go.  Fed by event
handlers (wired to informers in production, called directly in tests —
the reference's own unit-test pattern, allocate_test.go:155-222); produces
deep-copied snapshots; executes bind/evict side effects asynchronously with
an errTasks resync queue.
"""

from __future__ import annotations

import threading
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Dict, List, Optional

from volcano_tpu.api import (
    ClusterInfo,
    JobInfo,
    new_task_info,
    NodeInfo,
    QueueInfo,
    TaskInfo,
    TaskStatus,
)
from volcano_tpu.api.job_info import get_job_id
from volcano_tpu.api.queue_info import NamespaceCollection
from volcano_tpu.apis import core, scheduling, scheme
from volcano_tpu.cache.interface import Binder, Cache, Evictor, StatusUpdater
from volcano_tpu.incremental.shares import ShareLedger
from volcano_tpu.utils.logging import get_logger

log = get_logger(__name__)


def is_terminated(status: TaskStatus) -> bool:
    return status in (TaskStatus.Succeeded, TaskStatus.Failed)


class PackEpoch:
    """What changed since the warm packer's last consumed revision —
    attached to every snapshot (ClusterInfo.pack_epoch) and consumed by
    ops/pack_cache.PackCache.  ``dirty_tasks``/``dirty_nodes`` are
    cumulative: entries survive until a packer acknowledges them via
    ``SchedulerCache.clear_dirty_through``, so a cycle that skips packing
    (different action set, crash) cannot lose invalidations.
    ``topology_rev`` bumps when the node SET changes — positional node
    planes cannot be delta-patched across that, so the packer rebuilds
    them wholesale.

    ``dirty_nodes`` is every node whose accounting moved (binds, evicts,
    pod events — only the DYNAMIC planes: idle/used/task count/ok);
    ``dirty_nodes_full`` is the subset whose node OBJECT changed
    (update_node), which additionally invalidates the static planes
    (labels/taints/allocatable/max tasks)."""

    __slots__ = (
        "rev",
        "topology_rev",
        "dirty_tasks",
        "dirty_nodes",
        "dirty_nodes_full",
    )

    def __init__(
        self, rev: int, topology_rev: int, dirty_tasks, dirty_nodes,
        dirty_nodes_full=(),
    ):
        self.rev = rev
        self.topology_rev = topology_rev
        self.dirty_tasks = dirty_tasks
        self.dirty_nodes = dirty_nodes
        self.dirty_nodes_full = set(dirty_nodes_full)


def _task_pack_relevant_changed(old_pod: core.Pod, new_pod: core.Pod) -> bool:
    """Did an update_pod change anything the packed TASK ROW encodes
    (resource requests, selector/affinity/tolerations, job membership)?
    Status/phase/node_name churn — the overwhelmingly common update in a
    bind/complete cycle — keeps the row clean, which is what makes a
    steady-state warm cycle actually warm.  Errs dirty on any doubt."""
    try:
        so, sn = old_pod.spec, new_pod.spec
        if so is not sn:
            if len(so.containers) != len(sn.containers) or any(
                a.resources != b.resources
                for a, b in zip(so.containers, sn.containers)
            ):
                return True
            if len(so.init_containers) != len(sn.init_containers) or any(
                a.resources != b.resources
                for a, b in zip(so.init_containers, sn.init_containers)
            ):
                return True
            if (
                so.node_selector != sn.node_selector
                or so.affinity != sn.affinity
                or so.tolerations != sn.tolerations
            ):
                return True
        mo, mn = old_pod.metadata, new_pod.metadata
        if mo is not mn:
            if (mo.annotations or {}).get(
                scheduling.GROUP_NAME_ANNOTATION_KEY
            ) != (mn.annotations or {}).get(scheduling.GROUP_NAME_ANNOTATION_KEY):
                return True
            # pod labels feed (anti-)affinity matching of OTHER tasks;
            # the packer only bit-encodes selector→node-label relations,
            # but a label change flips host-validation outcomes — dirty.
            if mo.labels != mn.labels:
                return True
        return False
    except Exception:  # noqa: BLE001 — unknown shapes never stay clean
        return True


class DefaultBinder(Binder):
    """POSTs the pod binding through the API client (cache.go:122-134)."""

    def __init__(self, client):
        self.client = client

    def bind(self, task: TaskInfo, hostname: str) -> None:
        self.client.bind_pod(task.namespace, task.name, hostname)


class DefaultEvictor(Evictor):
    """Deletes the pod (cache.go:141-149)."""

    def __init__(self, client):
        self.client = client

    def evict(self, task: TaskInfo) -> None:
        self.client.delete_pod(task.namespace, task.name)


class DefaultStatusUpdater(StatusUpdater):
    """cache.go defaultStatusUpdater."""

    def __init__(self, client):
        self.client = client

    def update_pod_condition(self, task: TaskInfo, reason: str, message: str) -> None:
        self.client.update_pod_condition(task.namespace, task.name, reason, message)

    def update_pod_group(self, pg: scheduling.PodGroup):
        return self.client.update_pod_group(pg)


class SchedulerCache(Cache):
    def __init__(
        self,
        binder: Optional[Binder] = None,
        evictor: Optional[Evictor] = None,
        status_updater: Optional[StatusUpdater] = None,
        scheduler_name: str = "volcano",
        default_queue: str = "default",
        default_priority: int = 0,
        sync_side_effects: bool = True,
        client=None,
        snapshot_reuse: bool = False,
        pipelined_commit: bool = False,
        commit_workers: int = 2,
    ):
        self._mutex = threading.RLock()
        self.scheduler_name = scheduler_name
        self.default_queue = default_queue
        self.default_priority = default_priority

        self.jobs: Dict[str, JobInfo] = {}  # guarded-by: self._mutex
        #: incremental fair-share ledger + schedulable-work counter,
        #: maintained by _mark_job (the choke point every job-mutating
        #: handler passes through) so micro-cycles can gate wakes and
        #: open restricted sessions without O(resident jobs) sweeps
        self.share_ledger = ShareLedger()  # guarded-by: self._mutex
        self.nodes: Dict[str, NodeInfo] = {}  # guarded-by: self._mutex
        self.queues: Dict[str, QueueInfo] = {}  # guarded-by: self._mutex
        self.priority_classes: Dict[str, core.PriorityClass] = {}  # guarded-by: self._mutex
        self.namespace_collections: Dict[str, NamespaceCollection] = {}  # guarded-by: self._mutex
        #: PVCs keyed "ns/name" (pvcInformer, cache.go:415-421)
        self.pvcs: Dict[str, core.PersistentVolumeClaim] = {}  # guarded-by: self._mutex

        self.client = client
        self.binder = binder or (DefaultBinder(client) if client else None)
        self.evictor = evictor or (DefaultEvictor(client) if client else None)
        self.status_updater = status_updater or (
            DefaultStatusUpdater(client) if client else None
        )

        #: tasks whose async side effects failed; re-synced from API truth
        #: (cache.go:687-709 errTasks workqueue).  Entries are
        #: ``[task, attempts, next_try_monotonic]``; uids are deduped
        #: (the reference's workqueue semantics) so a bind burst cannot
        #: enqueue the same task N times.
        self.err_tasks: List[list] = []  # guarded-by: self._mutex
        #: uid → [task, quarantined_at_monotonic] for entries that
        #: exhausted _RESYNC_MAX_RETRIES: requeueing such a poison task
        #: hot-loop forever would grind the queue (the pre-fix
        #: behavior).  A quarantined task leaves through fresh API truth
        #: (any watch event for its pod clears it) or, failing that,
        #: re-enters the queue after _QUARANTINE_COOLDOWN with its
        #: attempt budget reset — an unchanged pod gets no watch event,
        #: so without the cooldown a long bus outage could wedge the
        #: cached task in Binding permanently.  Visible via the
        #: ResyncFailed Warning Event and the
        #: volcano_resync_quarantined_tasks gauge.
        self.quarantined_tasks: Dict[str, list] = {}  # guarded-by: self._mutex
        #: uids popped from err_tasks whose (blocking, mutex-free) fetch
        #: is in flight — resync_task dedupes against this too, or a
        #: concurrent enqueue during the fetch window would mint a
        #: duplicate entry
        self._resync_inflight: set = set()  # guarded-by: self._mutex
        #: one-shot flag for the "client can't record events" warning
        self._warned_no_events = False
        #: change listeners for the event-driven scheduler loop: each is
        #: called with a coarse category string AFTER the mutating
        #: handler releases the mutex (so a listener that takes its own
        #: lock — the scheduler's wake condition — never nests inside
        #: the cache mutex).  Categories: "task" (schedulable work
        #: appeared/changed), "node" (capacity moved: pod finished/
        #: deleted, node object updated), "topology" (node set changed),
        #: "gang" (a PodGroup with min_member > 1 arrived), "group"
        #: (other scheduling-relevant object churn).  Bind echoes of our
        #: own placements are deliberately NOT emitted — they would
        #: wake the loop once per bind for cycles with nothing to do.
        self._change_listeners: List = []  # guarded-by: self._mutex
        #: job uid → latest unschedulable writeback digest.  Fit errors
        #: live on session clones (JobInfo.clone resets them), so the
        #: status writeback below is the one durable point that sees
        #: them — it parks a digest here for the /explain debug surface.
        #: Cleared when the job's writeback carries no pending fit
        #: errors anymore, and when the job leaves the cache.
        self.unschedulable_digest: Dict[str, dict] = {}  # guarded-by: self._mutex

        # ---- warm-cycle change tracking (ops/pack_cache.py) ----
        #: bumped on every pack-relevant mutation; the dirty dicts map
        #: uid/name → the revision that last dirtied it, so consumers can
        #: acknowledge a prefix without losing later invalidations
        self._rev = 0  # guarded-by: self._mutex
        self._topology_rev = 0  # guarded-by: self._mutex
        self._dirty_tasks: Dict[str, int] = {}  # guarded-by: self._mutex
        self._dirty_nodes: Dict[str, int] = {}  # guarded-by: self._mutex
        self._dirty_nodes_full: Dict[str, int] = {}  # guarded-by: self._mutex
        #: per-object last-mutation revision (never cleared — validity
        #: stamps for the opt-in snapshot clone pool below)
        self._job_mut_rev: Dict[str, int] = {}  # guarded-by: self._mutex
        self._node_mut_rev: Dict[str, int] = {}  # guarded-by: self._mutex
        #: lazily built cycle-persistent packer; jax-allocate picks it up
        #: through the session's cache reference
        self._pack_cache = None

        # ---- opt-in snapshot clone reuse ----
        #: when True, snapshot() reuses the previous session's clones for
        #: objects that session left untouched AND the cache has not
        #: mutated since — the handshake is close_session →
        #: release_session_clones.  Off by default: correctness relies on
        #: the session-side touched-set discipline, which custom actions
        #: outside the shipped set may not follow.
        self.snapshot_reuse = snapshot_reuse
        self._clone_gen = 0
        self._handed_nodes: Dict[str, NodeInfo] = {}
        self._handed_jobs: Dict[str, JobInfo] = {}
        self._handed_rev = -1
        self._pool_nodes: Dict[str, NodeInfo] = {}
        self._pool_jobs: Dict[str, JobInfo] = {}
        self._pool_rev = -1
        self._pool_open = False

        #: informer registration latch (run() is idempotent)
        self._watch_started = False
        #: optional informer-facing proxy (federation's shard filter):
        #: run() registers IT with the client instead of the cache, so
        #: every watch delivery flows through its forwarding rules.
        #: Must be set before run(); plain attribute, startup-ordered.
        self._informer_sink = None

        # The reference fires bind/evict in goroutines (cache.go:596-612).
        # sync_side_effects=True (default) keeps them on-thread for
        # deterministic tests and simpler failure semantics.
        self._sync = sync_side_effects
        self._pool: Optional[ThreadPoolExecutor] = None
        self._pending: List[Future] = []

        # ---- pipelined commit plane (cache/commit_plane.py) ----
        # Opt-in: bind/evict/status effects are queued and drained by a
        # pool of bind workers, coalesced into batched commit frames,
        # with a commit barrier at the next snapshot().  Off by default:
        # the synchronous effects stay the deterministic baseline every
        # equivalence test pins the pipelined plane against.
        self._commit_plane = None
        if pipelined_commit:
            from volcano_tpu.cache.commit_plane import CommitPlane

            self._commit_plane = CommitPlane(self, workers=commit_workers)
        # Fast-path eligibility for the coalesced commit frame: only the
        # DEFAULT binder/evictor/status-updater wired to THIS cache's
        # client are known to be equivalent to the frame's server-side
        # application; custom implementations (tests, recorders) keep
        # the per-object calls so they observe every effect.
        _cb = getattr(self.client, "commit_batch", None) if self.client \
            else None
        self._fast_bind = (
            _cb is not None
            and isinstance(self.binder, DefaultBinder)
            and self.binder.client is self.client
        )
        self._fast_evict = (
            _cb is not None
            and isinstance(self.evictor, DefaultEvictor)
            and self.evictor.client is self.client
        )
        self._fast_status = (
            _cb is not None
            and isinstance(self.status_updater, DefaultStatusUpdater)
            and self.status_updater.client is self.client
        )

    # ---- lifecycle ----

    def run(self) -> None:
        if not self._sync and self._pool is None:
            self._pool = ThreadPoolExecutor(max_workers=8)
        # idempotent: Scheduler.run() calls this unconditionally, and a
        # harness may already have started the informers — registering
        # the watch handlers twice would deliver every event twice.
        # The latch is set AFTER watch() returns: a registration that
        # raised mid-way (transient bus outage at startup) stays
        # retryable on the next run() instead of poisoning the latch
        # and leaving a silent informer-less scheduler.
        if self.client is not None and not self._watch_started:
            self.client.watch(self._informer_sink or self)
            self._watch_started = True

    def wait_for_cache_sync(self) -> bool:
        return True

    def flush(self) -> None:
        """Wait for async side effects (test/shutdown aid)."""
        if self._commit_plane is not None:
            self._commit_plane.barrier()
        for f in list(self._pending):
            f.result()
        self._pending.clear()

    def stop_commit_plane(self) -> None:
        """Drain and stop the pipelined commit workers (shutdown aid)."""
        if self._commit_plane is not None:
            self._commit_plane.stop()

    def enable_pipelined_commit(self, workers: int = 2) -> None:
        """Turn the pipelined commit plane on post-construction (bench /
        embedding aid — daemons pass ``pipelined_commit=True``)."""
        if self._commit_plane is None:
            from volcano_tpu.cache.commit_plane import CommitPlane

            self._commit_plane = CommitPlane(self, workers=workers)

    def _run_effect(self, fn, *args) -> None:
        if self._sync or self._pool is None:
            fn(*args)
        else:
            self._pending.append(self._pool.submit(fn, *args))

    # ---- change notification (the event-driven scheduler's wake) ----

    def add_change_listener(self, fn) -> None:
        """Register ``fn(category: str)`` to be called after every
        scheduling-relevant cache mutation (watch events and resyncs —
        never our own bind/evict accounting, which would be a feedback
        loop).  Listeners run outside the cache mutex, on the thread
        that delivered the event; they must be cheap and non-blocking
        (the scheduler's listener just flips a condition variable)."""
        with self._mutex:
            if fn not in self._change_listeners:
                self._change_listeners.append(fn)

    def remove_change_listener(self, fn) -> None:
        with self._mutex:
            if fn in self._change_listeners:
                self._change_listeners.remove(fn)

    def _emit_change(self, category: Optional[str]) -> None:
        """Fan a change category out to the listeners.  Called OUTSIDE
        the mutex by the public event handlers; ``None`` (a suppressed
        bind-echo) is a no-op."""
        if category is None:
            return
        with self._mutex:
            listeners = list(self._change_listeners)
        for fn in listeners:
            try:
                fn(category)
            except Exception as e:  # noqa: BLE001 — a bad listener must
                # not break informer delivery
                log.error("cache change listener failed: %s", e)

    def set_informer_sink(self, sink) -> None:
        """Route informer deliveries through ``sink`` (an object with
        the same handler surface — the federation shard filter).  Must
        run before :meth:`run` registers the watches."""
        if self._watch_started:
            raise RuntimeError(
                "set_informer_sink must run before the informers start"
            )
        self._informer_sink = sink

    def pending_spill_view(self) -> List[dict]:
        """Per-job view of still-Pending tasks for the federation
        spillover pass: ``[{job_id, min_member, ready, tasks}]`` taken
        under one mutex hold.  Task entries are the live TaskInfo
        references — the consumer reads only stable identity fields
        (namespace/name/resreq/pod) and re-verifies everything against
        store truth before acting (the CAS bind)."""
        out: List[dict] = []
        with self._mutex:
            for job in self.jobs.values():
                if job.pod_group is None:
                    continue
                pending = job.task_status_index.get(TaskStatus.Pending)
                if not pending:
                    continue
                out.append({
                    "job_id": job.uid,
                    "min_member": job.pod_group.spec.min_member or 0,
                    "ready": job.ready_task_num(),
                    "tasks": list(pending.values()),
                })
        return out

    def has_schedulable_pending(self) -> bool:
        """Is there any pending task a scheduling cycle could act on?
        The event-driven loop consults this before spending a session on
        a capacity-freed wake ("node"/"group" triggers): under churn,
        every completion fires one — running a full session per
        departure with nothing pending would double the cycle load for
        zero bindings.

        Answered O(1) from the incremental ledger's schedulable-work
        counter (the set of jobs with a live PodGroup and a non-empty
        Pending bucket — the exact predicate the old per-wake rescan
        evaluated over every resident job)."""
        with self._mutex:
            return self.share_ledger.schedulable_count > 0

    def ledger_counts(self):
        """(resident, schedulable) job counts from the incremental
        ledger — the volcano_resident_jobs / volcano_schedulable_jobs
        gauges."""
        with self._mutex:
            return (
                self.share_ledger.resident_count,
                self.share_ledger.schedulable_count,
            )

    @staticmethod
    def _classify_pod_update(old_ti: TaskInfo, new_ti: TaskInfo,
                             spec_changed: bool) -> Optional[str]:
        """Wake category for a pod MODIFIED event — or None for churn a
        scheduling cycle cannot act on (the common case in steady
        state: our own bind's watch echo and the kubelet's
        Pending→Running flip, which would otherwise wake the loop once
        per placement)."""
        if spec_changed:
            return "task"
        if is_terminated(new_ti.status) and not is_terminated(old_ti.status):
            return "node"  # capacity freed — stuck tasks may now fit
        if not old_ti.node_name and new_ti.node_name:
            return None  # bind echo of a placement this loop made
        if old_ti.status != new_ti.status and new_ti.status == TaskStatus.Pending:
            return "task"  # task returned to schedulable
        return None

    # ---- warm-cycle change tracking ----

    def _mark_task(self, uid: str) -> None:
        # requires-lock: self._mutex
        self._rev += 1
        self._dirty_tasks[uid] = self._rev

    def _mark_node(self, name: str) -> None:
        # requires-lock: self._mutex
        self._rev += 1
        self._dirty_nodes[name] = self._rev
        self._node_mut_rev[name] = self._rev

    def _mark_node_full(self, name: str) -> None:
        # requires-lock: self._mutex
        """Node OBJECT change: static packed planes invalidate too."""
        self._mark_node(name)
        self._dirty_nodes_full[name] = self._rev

    def _mark_job(self, uid: str) -> None:
        # requires-lock: self._mutex
        self._rev += 1
        self._job_mut_rev[uid] = self._rev
        # every handler marks AFTER mutating the JobInfo, so the ledger
        # observes the post-mutation truth here — one diff per event,
        # never a sweep.  (delete_pod_group marks with pod_group already
        # None before dropping the job, so the retraction is covered.)
        self.share_ledger.observe(self.jobs.get(uid), uid)

    def _mark_topology(self) -> None:
        # requires-lock: self._mutex
        self._rev += 1
        self._topology_rev = self._rev

    #: dirty-set growth bound for deployments whose action set never
    #: packs (host allocate only): nothing acks the sets, so once they
    #: exceed this, reset them and bump the topology revision — any
    #: future packer then cold-packs instead of trusting pruned sets
    _DIRTY_CAP = 250_000

    def _bound_dirty(self) -> None:
        # requires-lock: self._mutex
        if (
            len(self._dirty_tasks) > self._DIRTY_CAP
            or len(self._dirty_nodes) > self._DIRTY_CAP
        ):
            self._dirty_tasks.clear()
            self._dirty_nodes.clear()
            self._dirty_nodes_full.clear()
            self._mark_topology()

    def clear_dirty_through(self, epoch: PackEpoch) -> None:
        """Acknowledge consumption of an epoch's dirty sets (the warm
        packer calls this after a successful pack).  Entries dirtied
        AFTER the epoch's revision stay queued."""
        with self._mutex:
            for uid in list(epoch.dirty_tasks):
                if self._dirty_tasks.get(uid, epoch.rev + 1) <= epoch.rev:
                    del self._dirty_tasks[uid]
            for name in list(epoch.dirty_nodes):
                if self._dirty_nodes.get(name, epoch.rev + 1) <= epoch.rev:
                    del self._dirty_nodes[name]
            for name in list(epoch.dirty_nodes_full):
                if self._dirty_nodes_full.get(name, epoch.rev + 1) <= epoch.rev:
                    del self._dirty_nodes_full[name]

    @property
    def pack_cache(self):
        """The cycle-persistent warm packer bound to this cache (lazy —
        pure-host deployments that never run jax-allocate don't pay for
        it)."""
        if self._pack_cache is None:
            from volcano_tpu.ops.pack_cache import PackCache

            self._pack_cache = PackCache(self)
        return self._pack_cache

    # ---- event handlers: pods (event_handlers.go:39-254) ----

    def _get_or_create_job(self, ti: TaskInfo) -> Optional[JobInfo]:
        # requires-lock: self._mutex
        """event_handlers.go:44-58 — only pods carrying a PodGroup
        annotation get a job; others are node-accounting-only."""
        if not ti.job:
            return None
        if ti.job not in self.jobs:
            self.jobs[ti.job] = JobInfo(ti.job)
        return self.jobs[ti.job]

    def _add_task(self, ti: TaskInfo) -> None:
        # requires-lock: self._mutex
        """event_handlers.go:60-79."""
        job = self._get_or_create_job(ti)
        if job is not None:
            job.add_task_info(ti)
            self._mark_job(ti.job)
        if ti.node_name:
            self._mark_node(ti.node_name)
        if ti.node_name:
            if ti.node_name not in self.nodes:
                self.nodes[ti.node_name] = NodeInfo(None)
                self.nodes[ti.node_name].name = ti.node_name
            if not is_terminated(ti.status):
                try:
                    self.nodes[ti.node_name].add_task(ti)
                except ValueError as e:
                    # Transient double-add when our own bind's watch echo races
                    # the in-cache accounting — the reference logs and
                    # keeps the node-held task (event_handlers.go AddPod
                    # error path); state converges on the next update.
                    log.debug("add task to node: %s", e)

    def _delete_task(self, ti: TaskInfo) -> None:
        # requires-lock: self._mutex
        """event_handlers.go:126-151."""
        if ti.job and ti.job in self.jobs:
            job = self.jobs[ti.job]
            stored = job.tasks.get(ti.uid)
            if stored is not None:
                job.delete_task_info(stored)
                self._mark_job(ti.job)
        if ti.node_name and ti.node_name in self.nodes:
            node = self.nodes[ti.node_name]
            if ti.uid in node.tasks:
                node.remove_task(ti)
                self._mark_node(ti.node_name)

    def add_pod(self, pod: core.Pod) -> None:
        with self._mutex:
            ti = new_task_info(pod)
            self._mark_task(ti.uid)
            self._clear_quarantine(ti.uid)
            self._add_task(ti)
        # a freshly-submitted schedulable pod is THE micro-cycle trigger;
        # a pre-bound or terminated pod only moves accounting
        self._emit_change(
            "task"
            if not ti.node_name and ti.status == TaskStatus.Pending
            else None
        )

    def update_pod(self, old_pod: core.Pod, new_pod: core.Pod) -> None:
        with self._mutex:
            old_ti = new_task_info(old_pod)
            new_ti = new_task_info(new_pod)
            # status/node churn re-derives job/node accounting (marked by
            # _delete/_add below) but keeps the packed task row clean —
            # only spec-level changes invalidate it
            spec_changed = _task_pack_relevant_changed(old_pod, new_pod)
            if spec_changed:
                self._mark_task(new_ti.uid)
            self._clear_quarantine(new_ti.uid)
            self._delete_task(old_ti)
            self._add_task(new_ti)
        self._emit_change(
            self._classify_pod_update(old_ti, new_ti, spec_changed)
        )

    def delete_pod(self, pod: core.Pod) -> None:
        with self._mutex:
            ti = new_task_info(pod)
            self._mark_task(ti.uid)
            self._clear_quarantine(ti.uid)
            self._delete_task(ti)
        # a deleted bound pod frees capacity stuck tasks may want; a
        # deleted pending pod just removes work
        self._emit_change("node" if ti.node_name else None)

    # ---- event handlers: nodes (event_handlers.go:255-354) ----

    def add_node(self, node: core.Node) -> None:
        with self._mutex:
            name = node.metadata.name
            if name in self.nodes:
                self.nodes[name].set_node(node)
                self._mark_node_full(name)
                fresh = False
            else:
                self.nodes[name] = NodeInfo(node)
                self._mark_topology()
                self._mark_node_full(name)
                fresh = True
        self._emit_change("topology" if fresh else "node")

    def update_node(self, old_node: core.Node, new_node: core.Node) -> None:
        with self._mutex:
            name = new_node.metadata.name
            if name in self.nodes:
                self.nodes[name].set_node(new_node)
                self._mark_node_full(name)
                fresh = False
            else:
                self.nodes[name] = NodeInfo(new_node)
                self._mark_topology()
                self._mark_node_full(name)
                fresh = True
        self._emit_change("topology" if fresh else "node")

    def delete_node(self, node: core.Node) -> None:
        with self._mutex:
            popped = self.nodes.pop(node.metadata.name, None) is not None
            if popped:
                self._mark_topology()
                self._mark_node_full(node.metadata.name)
                # mutation stamps only matter for LIVE objects (absent
                # entry = never reusable) — drop so the dict tracks the
                # live node set, not historical churn
                self._node_mut_rev.pop(node.metadata.name, None)
        if popped:
            self._emit_change("topology")

    # ---- event handlers: podgroups (event_handlers.go:356-581) ----

    def _set_pod_group(self, pg: scheduling.PodGroup) -> None:
        with self._mutex:
            job_id = pg.key()
            if job_id not in self.jobs:
                self.jobs[job_id] = JobInfo(job_id)
            self.jobs[job_id].set_pod_group(pg)
            self._mark_job(job_id)

    def add_pod_group(self, pg: scheduling.PodGroup) -> None:
        self._set_pod_group(pg)
        # a gang group's members arrive as an event storm right behind
        # it — route the whole arrival to a full cycle (the gang/fair-
        # share re-equilibration path) instead of micro-scheduling a
        # half-arrived gang
        self._emit_change(
            "gang" if (pg.spec.min_member or 0) > 1 else "group"
        )

    def update_pod_group(self, old_pg, new_pg: scheduling.PodGroup) -> None:
        self._set_pod_group(new_pg)
        # the overwhelmingly common MODIFIED is our own status writeback
        # echoing back through the watch — only a SPEC change is
        # scheduling-relevant
        self._emit_change(
            "group" if old_pg is None or old_pg.spec != new_pg.spec else None
        )

    def delete_pod_group(self, pg: scheduling.PodGroup) -> None:
        with self._mutex:
            job = self.jobs.get(pg.key())
            if job is not None:
                job.pod_group = None
                self._mark_job(pg.key())
                # Jobs without scheduling spec drop out of snapshots; GC'd
                # when tasks drain (cleanup worker in the reference).
                if not job.tasks:
                    del self.jobs[pg.key()]
                    self._job_mut_rev.pop(pg.key(), None)
                    self.unschedulable_digest.pop(pg.key(), None)
        self._emit_change("group")

    # ---- dual-version handlers (cache.go:393-424: the v1alpha1
    # informer set converts BOTH old and new through the scheme, then
    # delegates) ----

    def add_pod_group_v1alpha1(self, pg) -> None:
        self.add_pod_group(scheme.pod_group_v1alpha1_to_hub(pg))

    def update_pod_group_v1alpha1(self, old_pg, new_pg) -> None:
        self.update_pod_group(
            scheme.pod_group_v1alpha1_to_hub(old_pg) if old_pg is not None else None,
            scheme.pod_group_v1alpha1_to_hub(new_pg),
        )

    def delete_pod_group_v1alpha1(self, pg) -> None:
        self.delete_pod_group(scheme.pod_group_v1alpha1_to_hub(pg))

    def add_queue_v1alpha1(self, queue) -> None:
        self.add_queue(scheme.queue_v1alpha1_to_hub(queue))

    def update_queue_v1alpha1(self, old_queue, new_queue) -> None:
        self.update_queue(
            scheme.queue_v1alpha1_to_hub(old_queue) if old_queue is not None else None,
            scheme.queue_v1alpha1_to_hub(new_queue),
        )

    def delete_queue_v1alpha1(self, queue) -> None:
        self.delete_queue(scheme.queue_v1alpha1_to_hub(queue))

    # ---- event handlers: queues (event_handlers.go:696-863) ----

    def add_queue(self, queue: scheduling.Queue) -> None:
        with self._mutex:
            qi = QueueInfo(queue)
            self.queues[qi.uid] = qi
        self._emit_change("group")

    def update_queue(self, old_queue, new_queue: scheduling.Queue) -> None:
        with self._mutex:
            qi = QueueInfo(new_queue)
            self.queues[qi.uid] = qi
        # status writebacks echo through the watch every cycle — only a
        # spec change (weight/capability) is scheduling-relevant
        self._emit_change(
            "group"
            if old_queue is None or old_queue.spec != new_queue.spec
            else None
        )

    def delete_queue(self, queue: scheduling.Queue) -> None:
        with self._mutex:
            self.queues.pop(queue.metadata.name, None)
        self._emit_change("group")

    # ---- event handlers: priority classes (event_handlers.go:865-958) ----

    def add_priority_class(self, pc: core.PriorityClass) -> None:
        with self._mutex:
            self.priority_classes[pc.metadata.name] = pc
            if pc.global_default:
                self.default_priority = pc.value
        self._emit_change("group")

    def delete_priority_class(self, pc: core.PriorityClass) -> None:
        with self._mutex:
            self.priority_classes.pop(pc.metadata.name, None)
            if pc.global_default:
                self.default_priority = 0
        self._emit_change("group")

    # ---- PVC handlers (pvcInformer wiring, cache.go:415-421) ----

    def _put_pvc(self, pvc: core.PersistentVolumeClaim) -> None:
        with self._mutex:
            self.pvcs[f"{pvc.metadata.namespace}/{pvc.metadata.name}"] = pvc

    def add_pvc(self, pvc: core.PersistentVolumeClaim) -> None:
        self._put_pvc(pvc)
        self._emit_change("group")

    def update_pvc(self, old, new: core.PersistentVolumeClaim) -> None:
        # echo suppression: bind_volumes already parked our own
        # provisioning write via _put_pvc, so when the watch echoes it
        # back the cached object matches the incoming one (modulo the
        # store's resourceVersion bump) — such an update carries no new
        # scheduling information and must not wake the event loop (the
        # same wake-per-placement feedback bind echoes are filtered for)
        with self._mutex:
            key = f"{new.metadata.namespace}/{new.metadata.name}"
            cached = self.pvcs.get(key)
        if cached is not None:
            a, b = cached.clone(), new.clone()
            a.metadata.resource_version = b.metadata.resource_version = 0
            if a == b:
                self._put_pvc(new)  # keep the fresher resourceVersion
                return
        self.add_pvc(new)

    def delete_pvc(self, pvc: core.PersistentVolumeClaim) -> None:
        with self._mutex:
            self.pvcs.pop(f"{pvc.metadata.namespace}/{pvc.metadata.name}", None)
        self._emit_change("group")

    # ---- event handlers: resource quotas (event_handlers.go:961-1036) ----

    def add_resource_quota(self, namespace: str, quota_name: str, weight: Optional[int]) -> None:
        with self._mutex:
            coll = self.namespace_collections.setdefault(
                namespace, NamespaceCollection(namespace)
            )
            coll.update(quota_name, weight)
        self._emit_change("group")

    def delete_resource_quota(self, namespace: str, quota_name: str) -> None:
        with self._mutex:
            coll = self.namespace_collections.get(namespace)
            if coll is not None:
                coll.delete(quota_name)
        self._emit_change("group")

    # ---- snapshot (cache.go:712-790) ----

    def snapshot(self, scope: str = "full") -> ClusterInfo:
        # ``scope`` is the incremental-session seam:
        #   "full"       — every job (the classic snapshot);
        #   "restricted" — clone ONLY jobs with schedulable work
        #                  (O(pending), the restricted micro-cycle);
        #   "shadow"     — full job set, but ALSO annotated like a
        #                  restricted snapshot, so one atomic world can
        #                  feed both the restricted session and its
        #                  shadow full-session cross-check (computing
        #                  the restricted set outside the mutex would
        #                  race cache churn into false divergence).
        # "restricted"/"shadow" attach ``share_seed`` (the ledger's
        # cloned totals) and ``restricted_uids`` (the schedulable jobs
        # that made it into the snapshot).
        #
        # COMMIT BARRIER: every in-flight pipelined effect (binds,
        # evicts, status writebacks handed off last cycle) must land
        # before new cluster state is read — this is what keeps the
        # overlapped commit plane coherent with the store and the replay
        # journal bit-identical to the synchronous path.  Failed items
        # enqueued their resyncs, which the drain below then retries.
        if self._commit_plane is not None:
            self._commit_plane.barrier()
        # backed-off resync entries retry on the cycle boundary — the
        # natural drain point, and the snapshot then reflects whatever
        # truth the retries recovered
        self.process_due_resyncs()
        with self._mutex:
            snapshot = ClusterInfo()

            # clone pool: reuse the previous session's clone for objects
            # that session left untouched and the cache has not mutated
            # since the clones were made
            self._bound_dirty()

            pool_n, pool_j = {}, {}
            if self.snapshot_reuse and not self._pool_open and self._pool_rev >= 0:
                pool_n, pool_j = self._pool_nodes, self._pool_jobs

            for node in self.nodes.values():
                if not node.ready():
                    continue
                pooled = pool_n.get(node.name)
                if (
                    pooled is not None
                    and self._node_mut_rev.get(node.name, self._rev + 1)
                    <= self._pool_rev
                ):
                    snapshot.nodes[node.name] = pooled
                else:
                    snapshot.nodes[node.name] = node.clone()

            for queue in self.queues.values():
                snapshot.queues[queue.uid] = queue.clone()

            for key, pvc in self.pvcs.items():
                snapshot.pvcs[key] = pvc.clone()

            for name, coll in self.namespace_collections.items():
                snapshot.namespace_info[name] = coll.snapshot()

            if scope == "restricted":
                job_iter = [
                    self.jobs[uid]
                    for uid in sorted(self.share_ledger.schedulable_uids())
                    if uid in self.jobs
                ]
            else:
                job_iter = self.jobs.values()
            for job in job_iter:
                # No scheduling spec → not schedulable (cache.go:765-770).
                if job.pod_group is None:
                    continue
                if job.queue not in snapshot.queues:
                    continue
                job.priority = self.default_priority
                pri_name = job.pod_group.spec.priority_class_name
                pc = self.priority_classes.get(pri_name)
                if pc is not None:
                    job.priority = pc.value
                pooled = pool_j.get(job.uid)
                if (
                    pooled is not None
                    and self._job_mut_rev.get(job.uid, self._rev + 1)
                    <= self._pool_rev
                ):
                    snapshot.jobs[job.uid] = pooled
                else:
                    snapshot.jobs[job.uid] = job.clone()
                # re-stamped even on pooled clones: priority classes are
                # not tracked by the mutation revs
                snapshot.jobs[job.uid].priority = job.priority

            snapshot.pack_epoch = PackEpoch(
                rev=self._rev,
                topology_rev=self._topology_rev,
                dirty_tasks=set(self._dirty_tasks),
                dirty_nodes=set(self._dirty_nodes),
                dirty_nodes_full=set(self._dirty_nodes_full),
            )
            if scope != "full":
                snapshot.share_seed = self.share_ledger.seed()
                if scope == "restricted":
                    snapshot.restricted_uids = set(snapshot.jobs)
                else:
                    snapshot.restricted_uids = (
                        self.share_ledger.schedulable_uids()
                        & set(snapshot.jobs)
                    )
            if self.snapshot_reuse:
                self._clone_gen += 1
                snapshot.clone_gen = self._clone_gen
                self._handed_nodes = dict(snapshot.nodes)
                self._handed_jobs = dict(snapshot.jobs)
                self._handed_rev = self._rev
                self._pool_nodes = {}
                self._pool_jobs = {}
                self._pool_rev = -1
                self._pool_open = True

            return snapshot

    def release_session_clones(
        self, clone_gen: int, touched_jobs, touched_nodes
    ) -> None:
        """close_session hands back the session's untouched clones so the
        next snapshot can reuse them (opt-in, ``snapshot_reuse=True``).
        ``touched_*`` are the session's mutation sets — anything in them
        (or from a stale generation) is simply dropped."""
        with self._mutex:
            if not self.snapshot_reuse or clone_gen != self._clone_gen:
                return
            self._pool_nodes = {
                name: cl
                for name, cl in self._handed_nodes.items()
                if name not in touched_nodes
            }
            self._pool_jobs = {
                uid: cl
                for uid, cl in self._handed_jobs.items()
                if uid not in touched_jobs
            }
            self._pool_rev = self._handed_rev
            self._handed_nodes = {}
            self._handed_jobs = {}
            self._pool_open = False

    # ---- side effects (cache.go:498-615) ----

    def _find_job_and_task(self, task_info: TaskInfo):
        # requires-lock: self._mutex
        job = self.jobs.get(task_info.job)
        if job is None:
            raise KeyError(f"failed to find job {task_info.job}")
        task = job.tasks.get(task_info.uid)
        if task is None:
            raise KeyError(
                f"failed to find task in status {task_info.status.name} by id {task_info.uid}"
            )
        return job, task

    def bind(self, task_info: TaskInfo, hostname: str) -> None:
        """cache.go:557-615."""
        with self._mutex:
            job, task = self._find_job_and_task(task_info)
            node = self.nodes.get(hostname)
            if node is None:
                raise KeyError(
                    f"failed to bind task {task.uid} to host {hostname}: host not found"
                )
            job.update_task_status(task, TaskStatus.Binding)
            task.node_name = hostname
            node.add_task(task)
            self._mark_job(task.job)
            self._mark_node(hostname)

        self._dispatch_binds([(task, hostname)])

    @staticmethod
    def _maybe_fail_bind() -> None:
        """``cache.bind_fail`` injection point: a burst of apiserver
        bind rejections feeding the errTask resync queue, through the
        exact except path a real rejection takes."""
        from volcano_tpu import faults

        fp = faults.get_plane()
        if fp.enabled and fp.should("cache.bind_fail"):
            raise RuntimeError("fault-injected bind failure")

    def bind_batch(self, pairs) -> None:
        """Bind many (task_info, hostname) pairs: the same per-task state
        mutations as :meth:`bind` under ONE mutex hold, with the
        binder/event effects submitted as one job that preserves task
        order.  This is the bulk-commit path for fully-placed device
        sessions (actions/fast_apply.py) — at 50k binds the per-call
        mutex/submit overhead of bind() dominates the real work."""
        bound = []
        with self._mutex:
            # resolve everything before mutating anything, so a bad pair
            # cannot leave earlier tasks mutated with their binder
            # effects dropped (per-task bind() submits effects pairwise;
            # the batch must not weaken that failure contract)
            resolved = []
            for task_info, hostname in pairs:
                job, task = self._find_job_and_task(task_info)
                node = self.nodes.get(hostname)
                if node is None:
                    raise KeyError(
                        f"failed to bind task {task.uid} to host {hostname}:"
                        " host not found"
                    )
                resolved.append((job, task, node, hostname))
            for job, task, node, hostname in resolved:
                job.update_task_status(task, TaskStatus.Binding)
                task.node_name = hostname
                node.add_task(task)
                self._mark_job(task.job)
                self._mark_node(hostname)
                bound.append((task, hostname))

        self._dispatch_binds(bound)

    # ---- commit dispatch: pipelined plane or synchronous effects ----

    def _dispatch_binds(self, pairs) -> None:
        if not pairs:
            return
        if self._commit_plane is not None:
            self._commit_plane.submit_binds(pairs)
        else:
            self._run_effect(
                self._run_bind_items, [(t, h, None) for t, h in pairs]
            )

    def _dispatch_evicts(self, pairs) -> None:
        if not pairs:
            return
        if self._commit_plane is not None:
            self._commit_plane.submit_evicts(pairs)
        else:
            self._run_effect(
                self._run_evict_items, [(t, r, None) for t, r in pairs]
            )

    def _run_bind_items(self, items, inject: bool = True) -> None:
        """Land ``[(task, hostname, doom)]`` binder effects: one
        coalesced commit frame when the default binder is wired to a
        commit_batch-capable client (in-process APIServer or the VBUS
        v2 remote), per-object binder calls otherwise.  ``doom`` is a
        pre-drawn injected failure (the commit plane evaluates fault
        points at submit time); ``inject`` draws cache.bind_fail here —
        the synchronous path, where this IS the submitting thread.
        Failures, injected or real, take the same FailedScheduling-event
        + resync path the synchronous effects always have."""
        ok = []
        for task, hostname, doom in items:
            try:
                if doom is not None:
                    raise doom
                if inject:
                    self._maybe_fail_bind()
            except Exception as e:  # noqa: BLE001
                self._fail_bind_item(task, hostname, e)
                continue
            ok.append((task, hostname))
        if not ok:
            return
        from volcano_tpu.metrics import metrics

        metrics.observe_bind_coalesce(len(ok))
        if self._fast_bind:
            frame = [
                {
                    "namespace": t.namespace, "name": t.name, "hostname": h,
                    "event": {
                        "type": "Normal", "reason": "Scheduled",
                        "message": f"Successfully assigned"
                                   f" {t.namespace}/{t.name} to {h}",
                    },
                }
                for t, h in ok
            ]
            try:
                results = self.client.commit_batch(binds=frame)["binds"]
            except Exception as e:  # noqa: BLE001 — frame-level failure
                # (bus down mid-flight): every item takes the resync path
                for t, h in ok:
                    self._fail_bind_item(t, h, e)
                return
            for (t, h), err in zip(ok, results):
                if err is not None:
                    self._fail_bind_item(t, h, RuntimeError(err))
                else:
                    self._observe_bind_latency(t, h)
            return
        for task, hostname in ok:
            try:
                if self.binder is not None:
                    self.binder.bind(task, hostname)
            except Exception as e:  # noqa: BLE001
                self._fail_bind_item(task, hostname, e)
            else:
                self._observe_bind_latency(task, hostname)
                # cache.go:600-610 — the Scheduled audit event
                self._record_event(
                    task, "Normal", "Scheduled",
                    f"Successfully assigned {task.namespace}/{task.name}"
                    f" to {hostname}",
                )

    @staticmethod
    def _observe_bind_latency(task: TaskInfo, hostname: str = "") -> None:
        """volcano_submit_to_bind_latency_milliseconds: store creation
        timestamp → bind effect landed — the sustained-load SLO number,
        recorded here so the synchronous and pipelined paths share the
        one landing site.  Synthetic fixtures carry small ordinal
        timestamps, not epochs — only a plausible wall-clock stamp is
        observed (everything else would land in +Inf and poison the
        percentiles).  The flight-recorder ``bind:landed`` span rides
        the same site: one landing, every sink."""
        import time as _time

        from volcano_tpu.metrics import metrics

        metrics.update_pod_schedule_status("successes")
        pod = task.pod
        ts = pod.metadata.creation_timestamp if pod is not None else 0
        if ts and ts > 1e9:  # epoch seconds, not an ordinal fixture stamp
            metrics.observe_submit_to_bind(max(_time.time() - ts, 0.0))
        from volcano_tpu import obs

        if obs.enabled():
            args = {"pod": f"{task.namespace}/{task.name}"}
            if hostname:
                args["node"] = hostname
            gang = ""
            if pod is not None:
                from volcano_tpu.apis import scheduling as _sched

                gang = pod.metadata.annotations.get(
                    _sched.GROUP_NAME_ANNOTATION_KEY, ""
                )
            if gang:
                args["gang"] = f"{task.namespace}/{gang}"
            obs.complete(
                "bind:landed", 0.0, cat="bind",
                trace_id=obs.trace_id_for_pod(task.namespace, task.name),
                args=args,
            )

    def _fail_bind_item(self, task, hostname, e) -> None:
        from volcano_tpu.metrics import metrics

        log.error("bind of %s/%s failed: %s", task.namespace, task.name, e)
        metrics.register_commit_failure("bind")
        metrics.update_pod_schedule_status("errors")
        self._record_event(
            task, "Warning", "FailedScheduling",
            f"failed to bind to {hostname}: {e}",
        )
        self.resync_task(task)

    def _run_evict_items(self, items) -> None:
        """Land ``[(task, reason, doomed)]`` evictor effects — same
        fast/slow split and failure semantics as the bind items."""
        ok = []
        for task, reason, doom in items:
            if doom is not None:
                self._fail_evict_item(task, doom)
                continue
            ok.append((task, reason))
        if not ok:
            return
        if self._fast_evict:
            frame = [
                {
                    "namespace": t.namespace, "name": t.name,
                    "event": {
                        "type": "Normal", "reason": "Evict",
                        "message": f"Evicted {t.namespace}/{t.name}: {r}",
                    },
                }
                for t, r in ok
            ]
            try:
                results = self.client.commit_batch(evicts=frame)["evicts"]
            except Exception as e:  # noqa: BLE001
                for t, _r in ok:
                    self._fail_evict_item(t, e)
                return
            for (t, _r), err in zip(ok, results):
                if err is not None:
                    self._fail_evict_item(t, RuntimeError(err))
            return
        for task, reason in ok:
            try:
                if self.evictor is not None:
                    self.evictor.evict(task)
            except Exception as e:  # noqa: BLE001
                self._fail_evict_item(task, e)
            else:
                # cache.go:528 — the Evict audit event (reason carries
                # the action: "preempt" / "reclaim")
                self._record_event(
                    task, "Normal", "Evict",
                    f"Evicted {task.namespace}/{task.name}: {reason}",
                )

    def _fail_evict_item(self, task, e) -> None:
        from volcano_tpu.metrics import metrics

        log.error("evict of %s/%s failed: %s", task.namespace, task.name, e)
        metrics.register_commit_failure("evict")
        self.resync_task(task)

    def _record_event(self, task: TaskInfo, type_: str, reason: str, message: str) -> None:
        """Record a pod-scoped Event through the bus (the user-facing
        audit trail, cache.go:832-867, 600-610); best-effort."""
        if self.client is None or not hasattr(self.client, "record_event"):
            # SchedulerClient and RemoteAPIServer both record; a client
            # genuinely without the capability silently losing the audit
            # trail is worth exactly one log line, not one per event
            if self.client is not None and not self._warned_no_events:
                self._warned_no_events = True
                log.warning(
                    "cache client %s cannot record events — the "
                    "Scheduled/Unschedulable audit trail is disabled",
                    type(self.client).__name__,
                )
            return
        try:
            self.client.record_event(
                task.namespace,
                {"kind": "Pod", "namespace": task.namespace, "name": task.name},
                type_,
                reason,
                message,
            )
        except Exception as e:  # noqa: BLE001 — events must never fail ops
            log.error("record event failed: %s", e)

    def evict(self, task_info: TaskInfo, reason: str) -> None:
        """cache.go:498-554."""
        with self._mutex:
            job, task = self._find_job_and_task(task_info)
            node = self.nodes.get(task.node_name)
            if node is None:
                raise KeyError(
                    f"failed to evict task {task.uid}: host {task.node_name} not found"
                )
            job.update_task_status(task, TaskStatus.Releasing)
            node.update_task(task)
            self._mark_job(task.job)
            self._mark_node(task.node_name)

        self._dispatch_evicts([(task, reason)])

    # ---- volume binding (cache.go:243-258, 617-623) ----

    @staticmethod
    def task_claim_names(task: TaskInfo) -> List[str]:
        """PVC claim names referenced by the task's pod."""
        if task.pod is None:
            return []
        claims = []
        for vol in task.pod.spec.volumes:
            ref = vol.source.get("persistentVolumeClaim")
            if ref and ref.get("claimName"):
                claims.append(ref["claimName"])
        return claims

    def allocate_volumes(self, task: TaskInfo, hostname: str) -> None:
        """AssumePodVolumes analogue: record whether every referenced PVC
        is already Bound (task.volume_ready), so bind_volumes knows
        whether there is provisioning left to do (cache.go:243-249)."""
        with self._mutex:
            all_bound = True
            for claim in self.task_claim_names(task):
                pvc = self.pvcs.get(f"{task.namespace}/{claim}")
                if pvc is None or pvc.status.get("phase") != "Bound":
                    all_bound = False
            task.volume_ready = all_bound

    def bind_volumes(self, task: TaskInfo) -> None:
        """BindPodVolumes analogue (cache.go:251-258): dynamically
        provision still-pending PVCs that carry a storage class — write
        the selected node, a volume name, and phase Bound through the
        client.  Raises on a PVC that cannot be bound (no storage class,
        nothing provisionable) — the commit path converts that into an
        unbind + resync, exactly like an apiserver bind failure."""
        if task.volume_ready:
            return
        for claim in self.task_claim_names(task):
            key = f"{task.namespace}/{claim}"
            with self._mutex:
                pvc = self.pvcs.get(key)
            if pvc is None:
                raise KeyError(f"persistentvolumeclaim {key} not found")
            if pvc.status.get("phase") == "Bound":
                continue
            if not pvc.spec.get("storageClassName"):
                raise RuntimeError(
                    f"pod has unbound immediate PersistentVolumeClaims: {key}"
                )
            pvc = pvc.clone()
            pvc.metadata.annotations["volume.kubernetes.io/selected-node"] = (
                task.node_name
            )
            pvc.spec["volumeName"] = f"pv-{pvc.metadata.name}"
            pvc.status["phase"] = "Bound"
            if self.client is not None and hasattr(self.client, "update_pvc"):
                self.client.update_pvc(pvc)
            # _put_pvc, not add_pvc: our own provisioning write must not
            # wake the event loop (the watch echo is suppressed the same
            # way bind echoes are)
            self._put_pvc(pvc)
        task.volume_ready = True

    #: resync retry bound + backoff (cache.go:687-709 errTasks uses a
    #: rate-limited workqueue with MaxRetries; these are that policy)
    _RESYNC_MAX_RETRIES = 5
    _RESYNC_BACKOFF_BASE = 0.2  # seconds; exponential per attempt
    _QUARANTINE_COOLDOWN = 30.0  # seconds before a quarantined task retries
    #: per-cycle drain bounds: each retry is a blocking get_pod on the
    #: scheduling thread, so during a bus outage an unbounded drain
    #: would stall snapshot() by queue-length × RPC-timeout
    _RESYNC_DRAIN_MAX = 16
    _RESYNC_DRAIN_BUDGET_S = 1.0

    def resync_task(self, task: TaskInfo) -> None:
        """Requeue for resync from API truth (cache.go:687-709).
        Deduped by uid; a task already in quarantine stays there until
        fresh API truth for its pod arrives."""
        import time as _time

        with self._mutex:
            if (
                task.uid in self.quarantined_tasks
                or task.uid in self._resync_inflight
                or any(e[0].uid == task.uid for e in self.err_tasks)
            ):
                return
            self.err_tasks.append([task, 0, _time.monotonic()])
        if self.client is not None:
            self.process_resync_task()

    def process_resync_task(self) -> None:
        """Re-fetch the pod and rebuild the task (cache.go syncTask).
        One DUE entry per call; a failed fetch backs off exponentially
        and, past _RESYNC_MAX_RETRIES, quarantines the task with a
        Warning Event instead of requeueing forever."""
        import time as _time

        if self.client is None:
            return
        now = _time.monotonic()
        with self._mutex:
            entry = None
            for i, e in enumerate(self.err_tasks):
                if e[2] <= now:
                    entry = self.err_tasks.pop(i)
                    break
            if entry is None:
                return
            self._resync_inflight.add(entry[0].uid)
        task, attempts = entry[0], entry[1]
        try:
            from volcano_tpu import faults

            fp = faults.get_plane()
            if fp.enabled and fp.should("cache.resync_fail"):
                raise RuntimeError("fault-injected resync fetch failure")
            pod = self.client.get_pod(task.namespace, task.name)
        except Exception as e:  # noqa: BLE001 — API truth unreachable
            # note: the requeue/quarantine insertions below happen
            # BEFORE the finally's inflight release, so dedup never has
            # a gap where the task is in neither set
            attempts += 1
            if attempts >= self._RESYNC_MAX_RETRIES:
                log.error(
                    "resync of %s/%s failed %d times (%s); quarantining",
                    task.namespace, task.name, attempts, e,
                )
                self._record_event(
                    task, "Warning", "ResyncFailed",
                    f"task state resync failed {attempts} times and was "
                    f"quarantined pending fresh API truth: {e}",
                )
                with self._mutex:
                    self.quarantined_tasks[task.uid] = [
                        task, _time.monotonic()
                    ]
                    self._update_quarantine_gauge()
            else:
                backoff = self._RESYNC_BACKOFF_BASE * (2 ** (attempts - 1))
                log.warning(
                    "resync of %s/%s failed (%s); retry %d/%d in %.1fs",
                    task.namespace, task.name, e, attempts,
                    self._RESYNC_MAX_RETRIES, backoff,
                )
                with self._mutex:
                    self.err_tasks.append(
                        [task, attempts, _time.monotonic() + backoff]
                    )
            return
        finally:
            with self._mutex:
                self._resync_inflight.discard(task.uid)
        with self._mutex:
            # resync exists precisely because the cached view may have
            # diverged from API truth — the refetched spec can differ,
            # so the packed task row must not be reused
            self._mark_task(task.uid)
            self._delete_task(task)
            if pod is not None:
                self._add_task(new_task_info(pod))
        # a resynced task is schedulable work again (the failed bind was
        # unwound against API truth) — wake the event loop for it
        self._emit_change("task" if pod is not None else None)

    def process_due_resyncs(self) -> None:
        """Drain every due resync entry (called once per scheduling
        cycle from snapshot(), so backed-off entries retry without a
        dedicated timer thread).  Quarantined tasks past the cooldown
        re-enter the queue with a fresh attempt budget — a slow retry
        lane, since an unchanged pod never produces the watch event
        that is the quarantine's fast exit."""
        import time as _time

        now = _time.monotonic()
        with self._mutex:
            expired = [
                uid for uid, (task, ts) in self.quarantined_tasks.items()
                if now - ts >= self._QUARANTINE_COOLDOWN
            ]
            for uid in expired:
                task, _ts = self.quarantined_tasks.pop(uid)
                self.err_tasks.append([task, 0, now])
            if expired:
                self._update_quarantine_gauge()
        drain_deadline = now + self._RESYNC_DRAIN_BUDGET_S
        # bounded by _RESYNC_DRAIN_MAX alone: each due iteration pops one
        # entry, and the due-check exits when the queue has nothing left
        # (the old `min(len(self.err_tasks), …)` pre-read touched the
        # guarded queue outside the mutex — the lint's first catch)
        for _ in range(self._RESYNC_DRAIN_MAX):
            with self._mutex:
                due = any(e[2] <= _time.monotonic() for e in self.err_tasks)
            if not due or _time.monotonic() >= drain_deadline:
                return
            self.process_resync_task()

    def _update_quarantine_gauge(self) -> None:
        # requires-lock: self._mutex
        from volcano_tpu.metrics import metrics

        metrics.update_resync_quarantined(len(self.quarantined_tasks))

    def _clear_quarantine(self, uid: str) -> None:
        # requires-lock: self._mutex
        """Fresh API truth for a quarantined task's pod arrived through
        the watch — the quarantine's exit condition."""
        if self.quarantined_tasks.pop(uid, None) is not None:
            self._update_quarantine_gauge()

    # ---- status writeback ----

    def record_job_status_event(self, job: JobInfo) -> None:
        """cache.go:832-867 — pod conditions for unschedulable tasks."""
        if self.status_updater is None:
            return
        base_message = job.job_fit_errors
        tasks_digest: Dict[str, dict] = {}
        for task in job.tasks.values():
            if task.status != TaskStatus.Pending:
                continue
            fit_errors = job.nodes_fit_errors.get(task.uid)
            message = fit_errors.error() if fit_errors is not None else base_message
            if message:
                tasks_digest[task.uid] = {
                    "name": task.name,
                    "message": message,
                }
            self._record_event(task, "Warning", "Unschedulable", message)
            try:
                self.status_updater.update_pod_condition(task, "Unschedulable", message)
            except Exception as e:  # noqa: BLE001
                log.error("update pod condition failed: %s", e)
        with self._mutex:
            if tasks_digest:
                self.unschedulable_digest[job.uid] = {
                    "namespace": job.namespace,
                    "name": job.name,
                    "queue": job.queue,
                    "job_fit_errors": job.job_fit_errors,
                    "tasks": tasks_digest,
                }
            else:
                self.unschedulable_digest.pop(job.uid, None)

    def update_job_status(self, job: JobInfo) -> Optional[scheduling.PodGroup]:
        """cache.go:871-894."""
        self.record_job_status_event(job)
        if self.status_updater is None or job.pod_group is None:
            return job.pod_group
        return self.status_updater.update_pod_group(job.pod_group)

    def update_job_status_async(self, job: JobInfo) -> Optional[scheduling.PodGroup]:
        """Pipelined per-job status writeback: capture the whole
        writeback — Unschedulable events + PodScheduled conditions for
        pending tasks, plus the PodGroup status update — as ONE
        commit-plane item, so a 50k-pod cycle's close issues O(jobs)
        coalesced frames instead of O(pods) bus round trips.  Falls back
        to the synchronous :meth:`update_job_status` when the plane is
        off.  The /explain digest is parked synchronously (it is
        host-side state the next request may read); the bus writes land
        before the next snapshot's commit barrier."""
        if self._commit_plane is None:
            return self.update_job_status(job)
        payload = {"events": [], "conditions": [], "pod_group": None}
        if self.status_updater is not None:
            # same capture as record_job_status_event, deferred delivery
            base_message = job.job_fit_errors
            tasks_digest: Dict[str, dict] = {}
            for task in job.tasks.values():
                if task.status != TaskStatus.Pending:
                    continue
                fit_errors = job.nodes_fit_errors.get(task.uid)
                message = (
                    fit_errors.error() if fit_errors is not None
                    else base_message
                )
                if message:
                    tasks_digest[task.uid] = {
                        "name": task.name,
                        "message": message,
                    }
                payload["events"].append(
                    (task, "Warning", "Unschedulable", message)
                )
                payload["conditions"].append(
                    (task, "Unschedulable", message)
                )
            with self._mutex:
                if tasks_digest:
                    self.unschedulable_digest[job.uid] = {
                        "namespace": job.namespace,
                        "name": job.name,
                        "queue": job.queue,
                        "job_fit_errors": job.job_fit_errors,
                        "tasks": tasks_digest,
                    }
                else:
                    self.unschedulable_digest.pop(job.uid, None)
            if job.pod_group is not None:
                payload["pod_group"] = job.pod_group
        if payload["events"] or payload["conditions"] or payload["pod_group"]:
            self._commit_plane.submit_status(payload)
        return job.pod_group

    @staticmethod
    def _fail_status_attempts(n: int) -> None:
        """A failed async status writeback is a failed schedule attempt
        for each affected JOB: the synchronous path's JobUpdater
        converts its exception into ``schedule_attempts_total{error}``,
        but with the commit plane on, JobUpdater already returned
        success by the time the worker sees the failure — so the plane
        counts the error attempts itself (one per job payload), landing
        before the commit barrier releases the next cycle.  Closes the
        README known-gap where these failures were visible only in
        ``volcano_commit_failures_total``."""
        from volcano_tpu.metrics import metrics

        for _ in range(n):
            metrics.register_schedule_attempt("error")

    def _run_status_items(self, items) -> None:
        """Land ``[(payload, doomed)]`` status-writeback items (one
        payload = one job's whole writeback).  Fast path: the batch of
        jobs becomes one commit frame (events + conditions + PodGroup
        statuses).  Slow path: the per-object calls the synchronous
        writeback makes.  Failures are logged and counted — both in
        ``volcano_commit_failures_total{status}`` and as one
        ``schedule_attempts_total{error}`` per affected job — and the
        next cycle's updater recomputes and retries, the same
        convergence a synchronous writeback error relies on."""
        from volcano_tpu.metrics import metrics

        live = []
        for payload, doom in items:
            if doom is not None:
                metrics.register_commit_failure("status")
                self._fail_status_attempts(1)
                log.error("status writeback dropped by injected fault; "
                          "next cycle retries")
                continue
            live.append(payload)
        if not live:
            return
        if self._fast_status:
            # flatten the per-job payloads into one frame, remembering
            # which job each frame row came from so per-row errors can
            # be attributed back (one error ATTEMPT per failed job, no
            # matter how many of its rows failed)
            events, conditions, pod_groups = [], [], []
            ev_owner, cond_owner, pg_owner = [], [], []
            for pi, p in enumerate(live):
                for t, type_, reason, message in p["events"]:
                    events.append({
                        "namespace": t.namespace,
                        "involved": {"kind": "Pod",
                                     "namespace": t.namespace,
                                     "name": t.name},
                        "type": type_, "reason": reason,
                        "message": message,
                    })
                    ev_owner.append(pi)
                for t, reason, message in p["conditions"]:
                    conditions.append({
                        "namespace": t.namespace, "name": t.name,
                        "reason": reason, "message": message,
                    })
                    cond_owner.append(pi)
                if p["pod_group"] is not None:
                    pod_groups.append(p["pod_group"])
                    pg_owner.append(pi)
            try:
                results = self.client.commit_batch(
                    events=events, conditions=conditions,
                    pod_groups=pod_groups,
                )
            except Exception as e:  # noqa: BLE001 — frame-level failure:
                # every job's writeback was lost
                metrics.register_commit_failure("status")
                self._fail_status_attempts(len(live))
                log.error("batched status writeback failed: %s", e)
                return
            failed_jobs = set()
            for section, owners in (
                ("events", ev_owner),
                ("conditions", cond_owner),
                ("pod_groups", pg_owner),
            ):
                for i, err in enumerate(results.get(section, ())):
                    if err is not None:
                        metrics.register_commit_failure("status")
                        if i < len(owners):
                            failed_jobs.add(owners[i])
                        log.error("status writeback %s failed: %s",
                                  section, err)
            self._fail_status_attempts(len(failed_jobs))
            return
        for p in live:
            failed = False
            for t, type_, reason, message in p["events"]:
                self._record_event(t, type_, reason, message)
            for t, reason, message in p["conditions"]:
                try:
                    self.status_updater.update_pod_condition(
                        t, reason, message
                    )
                except Exception as e:  # noqa: BLE001
                    metrics.register_commit_failure("status")
                    failed = True
                    log.error("update pod condition failed: %s", e)
            if p["pod_group"] is not None and self.status_updater is not None:
                try:
                    self.status_updater.update_pod_group(p["pod_group"])
                except Exception as e:  # noqa: BLE001
                    metrics.register_commit_failure("status")
                    failed = True
                    log.error("update pod group failed: %s", e)
            if failed:
                self._fail_status_attempts(1)
