"""Pipelined commit plane — the device→host result queue drained by
bind workers.

The device phase won (BENCH_r05: ~35 ms of compute inside a 161 ms
flagship cycle); what remained of session latency was OUR OWN commit
path: binder/evictor round trips, Scheduled/Evict audit events, and the
per-job status writeback — O(pods) bus round trips issued synchronously
after the kernel had already finished.  This module takes that work off
the cycle's critical path:

* ``jax_allocate``/``jax_preempt`` (and the host actions — everything
  routes through ``SchedulerCache.bind/bind_batch/evict``) hand their
  commit effects to this queue and RETURN; a small pool of bind workers
  drains it in the background, so the bus traffic of cycle N overlaps
  cycle N+1's ORDER/pack/device phase.
* Workers COALESCE queued items into batched commit frames
  (``client.apiserver.commit_batch`` — one store transaction, one
  watch-notification flush, one VBUS frame over the wire) instead of
  per-object round trips.  ``volcano_bind_coalesce_size`` records the
  achieved batching.
* A **commit barrier** at the next session's snapshot
  (``SchedulerCache.snapshot`` → :meth:`barrier`) guarantees every
  in-flight effect has landed before new cluster state is read, so
  cache/store coherence and ``trace.replay.verify`` bit-identity are
  exactly the synchronous path's.  ``volcano_commit_overlap_ratio``
  reports how much of the commit work actually hid behind host work.

Failure semantics are unchanged: a failed bind/evict takes the same
FailedScheduling-event + ``resync_task`` path the synchronous effects
take — just later, and always before the next snapshot.

Fault points: ``commit.fail`` dooms a queued item (evaluated at SUBMIT
time on the scheduling thread, so chaos schedules stay deterministic),
``commit.delay`` sleeps a worker before it lands a batch (keeping the
queue observably non-empty while faults fire — the chaos suite's
commits-in-flight window).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from volcano_tpu.metrics import metrics
from volcano_tpu.utils.logging import get_logger

log = get_logger(__name__)

#: binds per coalesced frame — bounds frame size (JSON payload) while
#: keeping a 50k-bind cycle to ~a dozen frames instead of 50k
_MAX_COALESCE = 4096


class CommitPlane:
    """Queue + worker pool for a :class:`SchedulerCache`'s async commit
    effects.  The cache owns execution (``_run_bind_items`` /
    ``_run_evict_items`` / ``_run_status_items``); this class owns
    ordering, coalescing, the barrier, and the metrics."""

    def __init__(self, cache, workers: int = 2,
                 max_coalesce: int = _MAX_COALESCE):
        self.cache = cache
        self.max_coalesce = max_coalesce
        self._cv = threading.Condition()
        #: ("bind", task, hostname, doomed, meta) | ("evict", task,
        #: reason, doomed, meta) | ("status", payload, None, doomed,
        #: meta) — ``meta`` is the flight-recorder handoff (submitting
        #: span context + enqueue stamp), None with the recorder off
        self._items: deque = deque()  # guarded-by: self._cv
        self._inflight = 0  # guarded-by: self._cv
        self._stopped = False  # guarded-by: self._cv
        #: WALL-CLOCK time the plane was active (≥1 worker draining)
        #: since the last barrier — summed per-worker busy time would
        #: overstate overlap whenever workers drain concurrently
        self._busy_s = 0.0  # guarded-by: self._cv
        self._active_since: Optional[float] = None  # guarded-by: self._cv
        #: read by bench/observability after a barrier
        self.last_barrier: Dict[str, float] = {}
        self._threads = [
            threading.Thread(
                target=self._worker_loop, name=f"vtpu-bind-worker-{i}",
                daemon=True,
            )
            for i in range(max(1, workers))
        ]
        for t in self._threads:
            t.start()

    # ---- submission (scheduling thread) ----
    #
    # Fault points are evaluated at SUBMIT time, on the scheduling
    # thread: items are evaluated in deterministic order (a seeded chaos
    # schedule dooms the same items regardless of worker interleave) and
    # the firing journals inside the cycle that caused it — on a worker
    # the firing could land between cycles, outside any journal window.
    # The doomed item carries its exception and fails in the worker,
    # through the exact failure path a real rejection takes.

    def _doom(self, extra_point: Optional[str] = None):
        from volcano_tpu import faults

        fp = faults.get_plane()
        if not fp.enabled:
            return None
        doom = None
        if fp.should("commit.fail"):
            doom = RuntimeError("fault-injected commit failure")
        if extra_point is not None and fp.should(extra_point):
            # both streams always advance — exhausting one rule must not
            # shift the other's decisions (faults/plane.py discipline)
            doom = doom or RuntimeError("fault-injected bind failure")
        return doom

    @staticmethod
    def _obs_meta():
        """Flight-recorder handoff captured at SUBMIT time on the
        scheduling thread: (trace_id, span_id, enqueue_perf) of the
        submitting cycle's span, so the worker-side flush span parents
        into the cycle that queued the work and the queue wait is
        measurable.  None with the recorder off — zero per-item cost."""
        from volcano_tpu import obs

        if not obs.enabled():
            return None
        ctx = obs.current()
        if ctx is None:
            return ("", "", time.perf_counter())
        return (ctx[0], ctx[1], time.perf_counter())

    def submit_binds(self, pairs: List[Tuple[object, str]]) -> None:
        meta = self._obs_meta()
        with self._cv:
            for task, hostname in pairs:
                self._items.append(
                    ("bind", task, hostname,
                     self._doom("cache.bind_fail"), meta)
                )
            self._cv.notify_all()
            self._update_depth()

    def submit_evicts(self, pairs: List[Tuple[object, str]]) -> None:
        meta = self._obs_meta()
        with self._cv:
            for task, reason in pairs:
                self._items.append(("evict", task, reason, self._doom(),
                                    meta))
            self._cv.notify_all()
            self._update_depth()

    def submit_status(self, payload: dict) -> None:
        with self._cv:
            self._items.append(("status", payload, None, self._doom(),
                                self._obs_meta()))
            self._cv.notify_all()
            self._update_depth()

    def _update_depth(self) -> None:
        # requires-lock: self._cv
        metrics.update_commit_queue_depth(len(self._items) + self._inflight)

    @property
    def depth(self) -> int:
        with self._cv:
            return len(self._items) + self._inflight

    # ---- drain (bind workers) ----

    def _worker_loop(self) -> None:
        while True:
            with self._cv:
                while not self._items and not self._stopped:
                    self._cv.wait()
                if not self._items and self._stopped:
                    return
                batch = []
                while self._items and len(batch) < self.max_coalesce:
                    batch.append(self._items.popleft())
                self._inflight += 1
                if self._active_since is None:
                    self._active_since = time.perf_counter()
                self._update_depth()
            try:
                self._execute(batch)
            except Exception as e:  # noqa: BLE001 — a worker must survive
                # anything; per-item failures were already routed to the
                # resync path inside _execute
                log.error("commit-plane batch failed unexpectedly: %s", e)
            finally:
                with self._cv:
                    self._inflight -= 1
                    if self._inflight == 0 and self._active_since is not None:
                        self._busy_s += (
                            time.perf_counter() - self._active_since
                        )
                        self._active_since = None
                    self._update_depth()
                    self._cv.notify_all()

    def _execute(self, batch) -> None:
        from volcano_tpu import faults

        fp = faults.get_plane()
        if fp.enabled and fp.should("commit.delay"):
            # a slow bus/binder leg — on the WORKER, never the
            # scheduling thread, which is the whole point of the plane
            time.sleep(fp.param_ms("commit.delay") / 1e3)
        # execute as CONSECUTIVE same-kind runs in submission order —
        # grouping all binds before all evicts would invert the
        # evict-then-bind ordering Statement.commit emits, and watchers
        # (controllers, audit tooling) would transiently observe a node
        # holding both the victim and its replacement.  Each run still
        # coalesces into one frame.  (inject=False on binds: the fault
        # points were already evaluated at submit time — the worker
        # must not draw a second decision.)
        with self._flush_span(batch):
            i = 0
            while i < len(batch):
                kind = batch[i][0]
                j = i
                while j < len(batch) and batch[j][0] == kind:
                    j += 1
                run = batch[i:j]
                i = j
                if kind == "bind":
                    self.cache._run_bind_items(
                        [(t, h, doom) for _k, t, h, doom, _m in run],
                        inject=False,
                    )
                elif kind == "evict":
                    self.cache._run_evict_items(
                        [(t, r, doom) for _k, t, r, doom, _m in run]
                    )
                else:
                    self.cache._run_status_items(
                        [(p, doom) for _k, p, _x, doom, _m in run]
                    )

    @staticmethod
    def _flush_span(batch):
        """The worker-side ``commit:flush`` span: parented to the
        submitting cycle's span (captured at submit — workers have no
        ambient context of their own), carrying the batch size and the
        oldest item's queue wait.  Null span with the recorder off."""
        from volcano_tpu import obs

        if not obs.enabled():
            return obs.span("commit:flush")  # the shared null span
        now = time.perf_counter()
        metas = [it[4] for it in batch if it[4] is not None]
        args = {"items": len(batch)}
        if metas:
            args["queue_wait_ms"] = round(
                max(now - m[2] for m in metas) * 1e3, 3
            )
        parent = next((m for m in metas if m[1]), None)
        if parent is not None:
            return obs.adopt({"t": parent[0], "s": parent[1]},
                             "commit:flush", cat="commit", args=args)
        return obs.span("commit:flush", cat="commit", args=args)

    # ---- the commit barrier ----

    def barrier(self, timeout: Optional[float] = None) -> bool:
        """Block until every submitted effect has landed — called at the
        next session's snapshot.  Returns False on timeout (items still
        in flight).  Also computes the cycle's overlap ratio: of the
        plane's busy time since the last barrier, the fraction that ran
        while the scheduler was doing OTHER work instead of waiting
        here."""
        deadline = None if timeout is None else time.monotonic() + timeout
        t0 = time.perf_counter()
        with self._cv:
            while self._items or self._inflight:
                if deadline is not None and time.monotonic() >= deadline:
                    return False
                self._cv.wait(0.05)
            wait_s = time.perf_counter() - t0
            busy_s = self._busy_s
            self._busy_s = 0.0
        if busy_s > 0:
            ratio = max(0.0, min(1.0, 1.0 - wait_s / busy_s))
        else:
            ratio = 1.0
        self.last_barrier = {
            "wait_ms": wait_s * 1e3,
            "busy_ms": busy_s * 1e3,
            "overlap_ratio": ratio,
        }
        if busy_s > 0 or wait_s > 0:
            metrics.update_commit_overlap_ratio(ratio)
        return True

    def stop(self) -> None:
        """Drain and stop the workers (test/shutdown aid)."""
        self.barrier()
        with self._cv:
            self._stopped = True
            self._cv.notify_all()
        for t in self._threads:
            t.join(timeout=5.0)
