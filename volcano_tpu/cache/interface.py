"""Cache interface — the session's only channel for side effects.

Reference: pkg/scheduler/cache/interface.go:27-77.
"""

from __future__ import annotations

import abc
from typing import Optional

from volcano_tpu.api import ClusterInfo, JobInfo, TaskInfo
from volcano_tpu.apis import scheduling


class Cache(abc.ABC):
    """Mirror of cluster state + executor of bind/evict/status effects."""

    @abc.abstractmethod
    def run(self) -> None:
        """Start watching events (interface.go:30)."""

    @abc.abstractmethod
    def snapshot(self) -> ClusterInfo:
        """Deep-copied, session-immutable cluster state (interface.go:36)."""

    @abc.abstractmethod
    def wait_for_cache_sync(self) -> bool: ...

    @abc.abstractmethod
    def bind(self, task: TaskInfo, hostname: str) -> None:
        """Bind the task's pod to the host (interface.go:39)."""

    @abc.abstractmethod
    def evict(self, task: TaskInfo, reason: str) -> None:
        """Evict the task's pod (interface.go:42)."""

    @abc.abstractmethod
    def record_job_status_event(self, job: JobInfo) -> None:
        """Emit a cluster event for the job's scheduling outcome (interface.go:45)."""

    @abc.abstractmethod
    def update_job_status(self, job: JobInfo) -> Optional[scheduling.PodGroup]:
        """Write PodGroup status back (interface.go:48)."""

    def allocate_volumes(self, task: TaskInfo, hostname: str) -> None:
        """interface.go:51 — volume binding is a no-op in the default cache."""

    def bind_volumes(self, task: TaskInfo) -> None:
        """interface.go:54."""


class Binder(abc.ABC):
    """interface.go:60-63."""

    @abc.abstractmethod
    def bind(self, task: TaskInfo, hostname: str) -> None: ...


class Evictor(abc.ABC):
    """interface.go:66-69."""

    @abc.abstractmethod
    def evict(self, task: TaskInfo) -> None: ...


class StatusUpdater(abc.ABC):
    """interface.go:72-77."""

    @abc.abstractmethod
    def update_pod_condition(self, task: TaskInfo, reason: str, message: str) -> None: ...

    @abc.abstractmethod
    def update_pod_group(self, pg: scheduling.PodGroup) -> Optional[scheduling.PodGroup]: ...
