"""Cache interface — the session's only channel for side effects.

Reference: pkg/scheduler/cache/interface.go:27-77.
"""

from __future__ import annotations

import abc
from typing import Optional

from volcano_tpu.api import ClusterInfo, JobInfo, TaskInfo
from volcano_tpu.apis import scheduling


class Cache(abc.ABC):
    """Mirror of cluster state + executor of bind/evict/status effects."""

    @abc.abstractmethod
    def run(self) -> None:
        """Start watching events (interface.go:30)."""

    @abc.abstractmethod
    def snapshot(self) -> ClusterInfo:
        """Deep-copied, session-immutable cluster state (interface.go:36)."""

    @abc.abstractmethod
    def wait_for_cache_sync(self) -> bool: ...

    @abc.abstractmethod
    def bind(self, task: TaskInfo, hostname: str) -> None:
        """Bind the task's pod to the host (interface.go:39)."""

    @abc.abstractmethod
    def evict(self, task: TaskInfo, reason: str) -> None:
        """Evict the task's pod (interface.go:42)."""

    @abc.abstractmethod
    def record_job_status_event(self, job: JobInfo) -> None:
        """Emit a cluster event for the job's scheduling outcome (interface.go:45)."""

    @abc.abstractmethod
    def update_job_status(self, job: JobInfo) -> Optional[scheduling.PodGroup]:
        """Write PodGroup status back (interface.go:48)."""

    def allocate_volumes(self, task: TaskInfo, hostname: str) -> None:
        """interface.go:51 — volume binding is a no-op in the default cache."""

    def bind_volumes(self, task: TaskInfo) -> None:
        """interface.go:54."""

    # ---- event-driven scheduling surface (optional; this build) ----
    # Defaults are no-ops so any Cache implementation composes with the
    # wake-on-event loop: a cache that never notifies simply leaves the
    # scheduler purely periodic.

    def add_change_listener(self, fn) -> None:
        """Register ``fn(category: str)`` to fire after scheduling-
        relevant cache mutations (watch events/resyncs, never the
        scheduler's own bind/evict accounting).  Categories:
        task / node / topology / gang / group.  Listeners must be cheap
        and non-blocking; they run on the event-delivery thread."""

    def remove_change_listener(self, fn) -> None: ...

    def has_schedulable_pending(self) -> bool:
        """Is there pending work a scheduling cycle could act on?  The
        event loop consults this before spending a session on a
        capacity-freed wake; True (the conservative default) means
        "always run the cycle"."""
        return True


class Binder(abc.ABC):
    """interface.go:60-63."""

    @abc.abstractmethod
    def bind(self, task: TaskInfo, hostname: str) -> None: ...


class Evictor(abc.ABC):
    """interface.go:66-69."""

    @abc.abstractmethod
    def evict(self, task: TaskInfo) -> None: ...


class StatusUpdater(abc.ABC):
    """interface.go:72-77."""

    @abc.abstractmethod
    def update_pod_condition(self, task: TaskInfo, reason: str, message: str) -> None: ...

    @abc.abstractmethod
    def update_pod_group(self, pg: scheduling.PodGroup) -> Optional[scheduling.PodGroup]: ...
