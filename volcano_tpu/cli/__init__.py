"""vtctl — the CLI (reference: vcctl, cmd/cli/vcctl.go + pkg/cli)."""

from volcano_tpu.cli.vtctl import main

__all__ = ["main"]
