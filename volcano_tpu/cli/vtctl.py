"""vtctl — job and queue operations.

Reference: cmd/cli/vcctl.go:43-49 + pkg/cli/{job,queue}:
  vtctl job run|list|view|suspend|resume|delete
  vtctl queue create|get|list|operate|delete
  vtctl describe job|podgroup   (conditions + Events + the
                                 unschedulable-reason histogram)

Commands run against an APIServer instance: in-process when embedded
(tests, single-process deployments) or a served endpoint when the control
plane runs separately.  suspend/resume emit Command CRs consumed by the
job controller (pkg/cli/job/suspend.go, resume.go).

The TPU build adds ``vtctl trace record|replay|diff|export`` over the
cycle journal (volcano_tpu/trace): record synthetic cycles to a journal
directory, deterministically replay a captured cycle through any
executor and diff bindings, and export a cycle's timeline as Chrome
trace JSON.
"""

from __future__ import annotations

import argparse
import sys
import time
from typing import Dict, List, Optional

from volcano_tpu.apis import batch, bus, core, scheduling
from volcano_tpu.client import ApiError, APIServer, VolcanoClient


def _parse_resource_list(text: str) -> Dict[str, str]:
    """"cpu=1000m,memory=100Mi" → dict (cli/util.go populateResourceListV1)."""
    out: Dict[str, str] = {}
    for part in (text or "").split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(f"invalid resource {part!r}, expected name=quantity")
        name, quantity = part.split("=", 1)
        out[name.strip()] = quantity.strip()
    return out


def _construct_job(args) -> batch.Job:
    """pkg/cli/job/util.go constructLaunchJobFlagsJob."""
    requests = _parse_resource_list(args.requests)
    limits = _parse_resource_list(args.limits)
    task = batch.TaskSpec(
        name=args.taskname,
        replicas=args.replicas,
        template=core.PodTemplateSpec(
            metadata=core.ObjectMeta(name=args.name),
            spec=core.PodSpec(
                containers=[
                    core.Container(
                        name=args.name,
                        image=args.image,
                        resources={"requests": requests, "limits": limits},
                    )
                ]
            ),
        ),
    )
    return batch.Job(
        metadata=core.ObjectMeta(name=args.name, namespace=args.namespace),
        spec=batch.JobSpec(
            min_available=args.min_available,
            queue=args.queue,
            scheduler_name=args.scheduler,
            tasks=[task],
        ),
    )


def _load_job_file(path: str) -> batch.Job:
    import yaml

    if not (path.endswith(".yaml") or path.endswith(".yml")):
        raise ValueError("only support yaml file")
    with open(path) as f:
        data = yaml.safe_load(f)
    return batch.Job.from_dict(data)


def _issue_command(vc: VolcanoClient, namespace: str, job_name: str, action: str) -> None:
    """suspend/resume create a Command CR targeted at the job."""
    vc.create_command(
        bus.Command(
            metadata=core.ObjectMeta(
                name=f"{job_name}-{action.lower()}-{int(time.time() * 1000)}",
                namespace=namespace,
            ),
            action=action,
            target_object=core.OwnerReference(kind="Job", name=job_name),
        )
    )


# ---- job subcommands ----

def _job_run(vc: VolcanoClient, args, out) -> int:
    if not args.name and not args.filename:
        print("job name cannot be left blank", file=out)
        return 1
    job = _load_job_file(args.filename) if args.filename else _construct_job(args)
    if args.filename and args.namespace != "default":
        job.metadata.namespace = args.namespace
    created = vc.create_job(job)
    print(f"run job {created.metadata.name} successfully", file=out)
    return 0


def _job_list(vc: VolcanoClient, args, out) -> int:
    jobs = vc.list_jobs(args.namespace if args.namespace != "" else None)
    print(
        f"{'Name':<25}{'Creation':<21}{'Phase':<12}{'Replicas':<10}"
        f"{'Min':<6}{'Pending':<9}{'Running':<9}{'Succeeded':<11}{'Failed':<8}",
        file=out,
    )
    for job in jobs:
        replicas = sum(t.replicas for t in job.spec.tasks)
        created = time.strftime(
            "%Y-%m-%d %H:%M:%S", time.localtime(job.metadata.creation_timestamp)
        )
        s = job.status
        print(
            f"{job.metadata.name:<25}{created:<21}{s.state.phase:<12}{replicas:<10}"
            f"{s.min_available:<6}{s.pending:<9}{s.running:<9}{s.succeeded:<11}{s.failed:<8}",
            file=out,
        )
    return 0


def _job_view(vc: VolcanoClient, args, out) -> int:
    job = vc.get_job(args.namespace, args.name)
    if job is None:
        print(f"job {args.namespace}/{args.name} not found", file=out)
        return 1
    import yaml

    print(yaml.safe_dump(job.to_dict(), sort_keys=False), file=out)

    # Events section (kubectl-describe style): the audit trail for the
    # job's pods and its podgroup (cache.go:600-610, 832-867 recorders).
    # Pod names follow <job>-<task>-<idx> — match exactly that shape per
    # task spec (a bare "<job>-" prefix would also swallow events of a
    # sibling job named "<job>-something").
    import re

    jn = re.escape(job.metadata.name)
    patterns = [
        re.compile(rf"^{jn}-{re.escape(t.name)}-\d+$") for t in job.spec.tasks
    ]
    def _belongs(name: str) -> bool:
        return name == job.metadata.name or any(p.match(name) for p in patterns)

    events = _collect_events(vc, args.namespace, _belongs)
    if events:
        _print_events(events, out)
    return 0


def _job_suspend(vc: VolcanoClient, args, out) -> int:
    _issue_command(vc, args.namespace, args.name, batch.ABORT_JOB_ACTION)
    print(f"suspend job {args.name} successfully", file=out)
    return 0


def _job_resume(vc: VolcanoClient, args, out) -> int:
    _issue_command(vc, args.namespace, args.name, batch.RESUME_JOB_ACTION)
    print(f"resume job {args.name} successfully", file=out)
    return 0


def _job_delete(vc: VolcanoClient, args, out) -> int:
    vc.delete_job(args.namespace, args.name)
    print(f"delete job {args.name} successfully", file=out)
    return 0


# ---- queue subcommands ----

def _queue_create(vc: VolcanoClient, args, out) -> int:
    vc.create_queue(
        scheduling.Queue(
            metadata=core.ObjectMeta(name=args.name, namespace=""),
            spec=scheduling.QueueSpec(weight=args.weight),
        )
    )
    print(f"create queue {args.name} successfully", file=out)
    return 0


def _queue_get(vc: VolcanoClient, args, out) -> int:
    queue = vc.get_queue(args.name)
    if queue is None:
        print(f"queue {args.name} not found", file=out)
        return 1
    print(f"{'Name':<25}{'Weight':<8}{'State':<10}{'Inqueue':<9}{'Pending':<9}{'Running':<9}", file=out)
    s = queue.status
    print(
        f"{queue.metadata.name:<25}{queue.spec.weight:<8}{s.state or queue.spec.state:<10}"
        f"{s.inqueue:<9}{s.pending:<9}{s.running:<9}",
        file=out,
    )
    return 0


def _queue_list(vc: VolcanoClient, args, out) -> int:
    print(f"{'Name':<25}{'Weight':<8}{'State':<10}{'Inqueue':<9}{'Pending':<9}{'Running':<9}", file=out)
    for queue in vc.list_queues():
        s = queue.status
        print(
            f"{queue.metadata.name:<25}{queue.spec.weight:<8}{s.state or queue.spec.state:<10}"
            f"{s.inqueue:<9}{s.pending:<9}{s.running:<9}",
            file=out,
        )
    return 0


def _queue_operate(vc: VolcanoClient, args, out) -> int:
    """pkg/cli/queue/operate.go — open/close via Command CR, update weight
    directly."""
    if args.action in ("open", "close"):
        action = "OpenQueue" if args.action == "open" else "CloseQueue"
        vc.create_command(
            bus.Command(
                metadata=core.ObjectMeta(
                    name=f"{args.name}-{args.action}-{int(time.time() * 1000)}", namespace=""
                ),
                action=action,
                target_object=core.OwnerReference(kind="Queue", name=args.name),
            )
        )
    elif args.action == "update":
        if args.weight is None:
            print("update action requires --weight", file=out)
            return 1
        queue = vc.get_queue(args.name)
        if queue is None:
            print(f"queue {args.name} not found", file=out)
            return 1
        queue.spec.weight = args.weight
        vc.update_queue(queue)
    else:
        print(f"invalid action {args.action}", file=out)
        return 1
    print(f"operate queue {args.name} successfully", file=out)
    return 0


def _queue_delete(vc: VolcanoClient, args, out) -> int:
    vc.delete_queue(args.name)
    print(f"delete queue {args.name} successfully", file=out)
    return 0


# ---- describe subcommands (the "why is my job pending" surface) ----

def _collect_events(vc: VolcanoClient, namespace: str, belongs) -> list:
    return sorted(
        (
            e
            for e in vc.api.list("Event", namespace)
            if belongs(e.involved_object.get("name", ""))
        ),
        key=lambda e: e.metadata.resource_version,
    )


def _print_events(events, out) -> None:
    if not events:
        print("Events:             <none>", file=out)
        return
    print("Events:", file=out)
    print(
        f"  {'Type':<8} {'Count':<6} {'Reason':<18} {'Object':<32} Message",
        file=out,
    )
    for e in events:
        obj = f"{e.involved_object.get('kind', '')}/{e.involved_object.get('name', '')}"
        print(
            f"  {e.type:<8} {e.count:<6} {e.reason:<18} {obj:<32} {e.message}",
            file=out,
        )


def _describe_scheduling(vc: VolcanoClient, namespace: str, name: str,
                         pg, belongs, out) -> None:
    """The shared body of ``describe job`` / ``describe podgroup``:
    PodGroup conditions, the unschedulable-reason histogram aggregated
    out of recorded Warning/Unschedulable Events, and the raw Events
    table.  An aggregated Event's message is the LATEST occurrence's
    detail (the correlator refreshes it), so each event contributes its
    current per-reason node counts once — NOT multiplied by the
    historical repeat count, which would inflate the current cause by
    however long the task was stuck on a previous one.  Reads only the
    API surface, so it renders identically over the in-process backend
    and ``--bus``."""
    from volcano_tpu.api.unschedule_info import parse_fit_errors

    if pg is not None:
        s = pg.status
        print(f"Phase:              {s.phase}", file=out)
        print(f"Min Member:         {pg.spec.min_member}", file=out)
        print(f"Queue:              {pg.spec.queue}", file=out)
        if s.conditions:
            print("Conditions:", file=out)
            print(f"  {'Type':<16} {'Status':<8} {'Reason':<22} Message", file=out)
            for c in s.conditions:
                print(
                    f"  {c.type:<16} {c.status:<8} {c.reason:<22} {c.message}",
                    file=out,
                )
        else:
            print("Conditions:         <none>", file=out)
    else:
        print("PodGroup:           <none>", file=out)

    events = _collect_events(vc, namespace, belongs)
    histogram: Dict[str, int] = {}
    for e in events:
        if e.type != "Warning" or e.reason != "Unschedulable":
            continue
        parsed = parse_fit_errors(e.message)
        if parsed is None:
            continue
        for reason, count in parsed[1].items():
            histogram[reason] = histogram.get(reason, 0) + count
    if histogram:
        print("Unschedulable Reasons:", file=out)
        print(f"  {'Nodes':<7} Reason", file=out)
        for reason, count in sorted(histogram.items(), key=lambda kv: -kv[1]):
            print(f"  {count:<7} {reason}", file=out)
    _print_events(events, out)


def _describe_job(vc: VolcanoClient, args, out) -> int:
    job = vc.get_job(args.namespace, args.name)
    if job is None:
        print(f"job {args.namespace}/{args.name} not found", file=out)
        return 1
    print(f"Name:               {job.metadata.name}", file=out)
    print(f"Namespace:          {job.metadata.namespace}", file=out)
    print(f"Scheduler:          {job.spec.scheduler_name}", file=out)
    s = job.status
    print(
        f"Status:             pending={s.pending} running={s.running} "
        f"succeeded={s.succeeded} failed={s.failed}",
        file=out,
    )

    # pod names follow <job>-<task>-<idx> (the _job_view matcher)
    import re

    jn = re.escape(job.metadata.name)
    patterns = [
        re.compile(rf"^{jn}-{re.escape(t.name)}-\d+$") for t in job.spec.tasks
    ]

    def belongs(name: str) -> bool:
        return name == job.metadata.name or any(p.match(name) for p in patterns)

    # the job controller names the PodGroup after the job (actions.go:423)
    pg = vc.get_pod_group(args.namespace, args.name)
    _describe_scheduling(vc, args.namespace, args.name, pg, belongs, out)
    return 0


def _describe_podgroup(vc: VolcanoClient, args, out) -> int:
    pg = vc.get_pod_group(args.namespace, args.name)
    if pg is None:
        # the group may live on the bus as a raw v1alpha1 kind
        from volcano_tpu.apis import scheme as _scheme

        raw = vc.api.get("PodGroupV1alpha1", args.namespace, args.name)
        if raw is not None:
            pg = _scheme.pod_group_v1alpha1_to_hub(raw)
    if pg is None:
        print(f"podgroup {args.namespace}/{args.name} not found", file=out)
        return 1
    print(f"Name:               {pg.metadata.name}", file=out)
    print(f"Namespace:          {pg.metadata.namespace}", file=out)

    prefix = f"{pg.metadata.name}-"

    def belongs(name: str) -> bool:
        return name == pg.metadata.name or name.startswith(prefix)

    _describe_scheduling(vc, args.namespace, args.name, pg, belongs, out)
    return 0


# ---- shards (the federation observability surface) ----

def _shards(vc: VolcanoClient, args, out) -> int:
    """Render the live shard map: per-shard lease holders, the member
    heartbeats, and each member's published stats (nodes owned,
    spillover counters, rebalances, capacity-sketch freshness and the
    sketch-vs-truth verification split).  Reads ONLY the shard-map
    ConfigMap through the API surface — sketch age is computed against
    the newest renew tick ON the map, never a call-time clock — so the
    output is byte-identical over the in-process backend and ``--bus``
    for the same store state."""
    from volcano_tpu.federation import read_shard_map

    rec = read_shard_map(vc.api)
    if rec is None:
        print("no shard map — the federation has not run "
              "(start schedulers with --shards N)", file=out)
        return 1
    n = int(rec.get("nShards", 0))
    print(f"Shards:             {n}", file=out)
    scale = rec.get("autoscale")
    if scale:
        # the autoscaler's last committed decision — stored fields
        # only, so the line stays byte-identical across backends
        print(
            f"Autoscale:          target {scale.get('target', n)} "
            f"({scale.get('direction', '?')}: "
            f"{scale.get('reason', '')}; "
            f"decisions {scale.get('decisions', 0)})",
            file=out,
        )
    print(f"  {'SHARD':<7}{'HOLDER':<22}{'LEASE':<8}{'RENEWED':<20}", file=out)
    for i in range(n):
        entry = rec.get("shards", {}).get(str(i), {})
        holder = entry.get("holder") or "<unheld>"
        lease = entry.get("leaseDurationSeconds", 0)
        renewed = entry.get("renewTime", 0)
        print(f"  {i:<7}{holder:<22}{lease:<8g}{renewed:<20}", file=out)
    members = rec.get("members", {})
    print("Members:", file=out)
    if not members:
        print("  <none>", file=out)
    for ident in sorted(members):
        m = members[ident]
        print(
            f"  {ident:<22}heartbeat {m.get('heartbeat', 0)}  "
            f"lease {m.get('leaseDurationSeconds', 0):g}s",
            file=out,
        )
    stats = rec.get("stats", {})
    if stats:
        print("Stats:", file=out)
        for ident in sorted(stats):
            s = stats[ident]
            spill = s.get("spillover", {})
            spill_txt = " ".join(
                f"{k}={spill[k]}" for k in sorted(spill)
            ) or "<none>"
            print(
                f"  {ident:<22}nodes={s.get('nodesOwned', 0)} "
                f"rebalances={s.get('rebalances', 0)} "
                f"spillover: {spill_txt}",
                file=out,
            )
            # cross-shard gang assembly (federation/broker.py) — only
            # members running the broker publish the blob, so the line
            # is absent (not zeroed) for --gang-broker off members
            gang = s.get("gangAssembly")
            if gang is not None:
                gang_txt = " ".join(
                    f"{k}={gang[k]}" for k in sorted(gang)
                ) or "<none>"
                print(f"  {'':<22}gang-assembly: {gang_txt}", file=out)
            # the free-capacity sketch rides the lease heartbeat, so
            # its age is the member's heartbeat measured against the
            # NEWEST renew tick on the map (stored fields only — a
            # call-time clock would break cross-backend byte-identity);
            # a sketch older than the member's lease TTL is the signal
            # foreign solicitation is flying blind on this member
            sketch = s.get("sketch")
            if sketch is not None:
                latest = max(
                    [e.get("renewTime", 0)
                     for e in rec.get("shards", {}).values()]
                    + [m.get("heartbeat", 0) for m in members.values()]
                    + [0]
                )
                m = members.get(ident, {})
                hb = m.get("heartbeat", 0)
                ttl = m.get("leaseDurationSeconds", 0)
                age = max(0.0, float(latest) - float(hb))
                fresh = "fresh" if age <= ttl else "STALE"
                print(
                    f"  {'':<22}sketch: slots={sketch.get('freeSlots', 0)} "
                    f"topNodes={len(sketch.get('topNodes') or ())} "
                    f"age={age:g}s/ttl={ttl:g}s ({fresh})",
                    file=out,
                )
            # sketch-vs-truth: how often a sketch-solicited candidate
            # survived (verified) or failed (stale) the bind-time
            # per-node truth check — the observable cost of trading
            # the O(cluster) mirror for O(shards·K) sketches
            checks = s.get("sketchChecks")
            if checks is not None:
                checks_txt = " ".join(
                    f"{k}={checks[k]}" for k in sorted(checks)
                ) or "<none>"
                print(f"  {'':<22}sketch-checks: {checks_txt}", file=out)
    return 0


# ---- bus (the replicated persistent bus observability surface) ----

def _bus_status(vc: VolcanoClient, args, out) -> int:
    """Render the bus durability/replication status: role, leader
    identity, term/epoch, applied + committed sequence, WAL/snapshot
    sizes and fsync stats, and per-follower replication lag (entries +
    ms).  Reads ONLY the ``bus_status`` payload (stored/derived state,
    no call-time clocks), so the output is byte-identical over the
    in-process backend and ``--bus`` for the same store state — the
    ``vtctl shards`` discipline."""
    api = vc.api
    st = api.bus_status() if hasattr(api, "bus_status") else {
        "role": "standalone", "persistent": False,
    }
    print(f"Role:               {st.get('role', 'unknown')}", file=out)
    if st.get("identity"):
        print(f"Identity:           {st['identity']} "
              f"(index {st.get('index', '?')} of "
              f"{st.get('replicas', '?')})", file=out)
    if "leader" in st:
        print(f"Leader:             {st.get('leader') or '<none elected>'}",
              file=out)
    print(f"Persistent:         {str(bool(st.get('persistent'))).lower()}",
          file=out)
    if not st.get("persistent"):
        return 0
    print(f"Epoch:              {st.get('epoch', '')}", file=out)
    print(f"Term:               {st.get('term', 0)}", file=out)
    if "membership_epoch" in st:
        members = ", ".join(st.get("membership", ()))
        print(f"Membership:         epoch {st['membership_epoch']} "
              f"[{members}]", file=out)
    print(f"Applied seq:        {st.get('seq', 0)}", file=out)
    if "commit_seq" in st:
        print(f"Committed seq:      {st['commit_seq']}", file=out)
    if "quorum" in st:
        print(f"Quorum:             {st['quorum']} of "
              f"{st.get('replicas', 1)}", file=out)
    print(f"WAL:                {st.get('wal_size_bytes', 0)} bytes, "
          f"{st.get('wal_records', 0)} records since snapshot", file=out)
    print(f"Snapshot:           {st.get('snapshot_size_bytes', 0)} bytes "
          f"@ seq {st.get('snapshot_seq', 0)}", file=out)
    print(f"Last fsync:         {st.get('last_fsync_ms', 0)} ms "
          f"at {st.get('last_fsync_ts', 0)}", file=out)
    if "wal_codec" in st:
        print(f"WAL codec:          {st['wal_codec']}", file=out)
    followers = st.get("followers", {})
    if followers:
        print("Followers:", file=out)
        print(f"  {'ID':<22}{'ACKED':<9}{'LAG':<7}{'LAG-MS':<9}"
              f"{'CODEC':<7}", file=out)
        for fid in sorted(followers):
            f = followers[fid]
            print(
                f"  {fid:<22}{f.get('acked_seq', 0):<9}"
                f"{f.get('lag_entries', 0):<7}{f.get('lag_ms', 0):<9g}"
                f"{f.get('codec', 'json'):<7}",
                file=out,
            )
    elif st.get("role") == "leader" and int(st.get("replicas", 1)) > 1:
        print("Followers:          <none attached>", file=out)
    return 0


def _bus_membership_change(vc: VolcanoClient, args, out, what: str) -> int:
    """Shared driver for ``bus add-replica`` / ``bus remove-replica``:
    sends the VBUS v7 membership op (the server routes it to the
    leader) and renders the committed config."""
    api = vc.api
    method = getattr(api, f"bus_{what}_replica", None)
    if method is None:
        # the in-process backend has no replication group to change
        print("error: dynamic membership needs a replicated bus — "
              "connect with --bus tcp://...", file=out)
        return 1
    res = method(args.url)
    members = "\n".join(f"  {u}" for u in res.get("endpoints", ()))
    print(f"membership change committed at seq {res.get('seq', 0)} "
          f"(epoch {res.get('epoch', 0)}):", file=out)
    print(members, file=out)
    return 0


def _bus_add_replica(vc: VolcanoClient, args, out) -> int:
    return _bus_membership_change(vc, args, out, "add")


def _bus_remove_replica(vc: VolcanoClient, args, out) -> int:
    return _bus_membership_change(vc, args, out, "remove")


# ---- trace subcommands (volcano_tpu/trace) ----

def _faults_validate(vc: VolcanoClient, args, out) -> int:
    """Parse a fault schedule and print it normalized — catches a
    typo'd point name or malformed modifier before it reaches a daemon
    flag (where it would be a startup error at deploy time)."""
    from volcano_tpu.faults import parse_faults

    spec = parse_faults(args.spec)  # ValueError → main's error path
    print(f"seed: {spec.seed}", file=out)
    if not spec.rules:
        print("no fault rules (plane would be a no-op)", file=out)
    for rule in spec.rules.values():
        mods = []
        if rule.count is not None:
            mods.append(f"at most {rule.count} firings")
        if rule.after:
            mods.append(f"after {rule.after} evaluations")
        if rule.ms:
            mods.append(f"{rule.ms:g} ms")
        suffix = f" ({', '.join(mods)})" if mods else ""
        print(f"  {rule.point}: p={rule.probability:g}{suffix}", file=out)
    print(f"normalized: {spec.format()}", file=out)
    return 0


def _trace_record(vc: VolcanoClient, args, out) -> int:
    """Record synthetic scheduling cycles into a journal: per cycle, the
    event timeline plus (sampled) the packed session + kernel assignment
    that trace replay re-executes."""
    import time as _time

    from volcano_tpu import trace as _trace
    from volcano_tpu.ops.kernels import DEFAULT_WEIGHTS
    from volcano_tpu.ops.synthetic import generate_snapshot
    from volcano_tpu.trace.replay import run_snapshot

    rec = _trace.TraceRecorder(
        journal=_trace.Journal(args.dir, keep=args.keep),
        snapshot_every=args.snapshot_every,
    )
    # install globally so the dispatch/executor-layer instrumentation
    # (dispatch:allocate naming the executor auto picked, degradation
    # and remote-fallback events) lands in the journal too
    prev = _trace.get_recorder()
    _trace.set_recorder(rec)
    try:
        for i in range(args.cycles):
            snap = generate_snapshot(
                n_tasks=args.tasks,
                n_nodes=args.nodes,
                gang_size=args.gang_size,
                seed=args.seed + i,
            )
            # the journal cycle id, NOT i — the recorder resumes after a
            # non-empty journal's newest cycle
            cid = rec.begin_cycle()
            t0 = _time.perf_counter()
            with rec.span("kernel:execute", "kernel", executor=args.executor):
                assignment = run_snapshot(snap, executor=args.executor)
            rec.capture(
                snap, assignment, executor=args.executor,
                weights=DEFAULT_WEIGHTS, gang_rounds=3,
            )
            placed = int((assignment[: snap.n_tasks] >= 0).sum())
            rec.event("cycle-summary", "scheduler", placed=placed)
            rec.end_cycle(duration_s=_time.perf_counter() - t0)
            print(
                f"cycle {cid}: {placed}/{snap.n_tasks} placed"
                + (
                    " [snapshot]"
                    if cid in rec.journal.snapshot_cycles()
                    else ""
                ),
                file=out,
            )
    finally:
        _trace.set_recorder(prev)
    print(
        f"recorded {args.cycles} cycle(s) to {args.dir} "
        f"(snapshots every {args.snapshot_every or 'never'})",
        file=out,
    )
    return 0


def _trace_replay(vc: VolcanoClient, args, out) -> int:
    from volcano_tpu.trace.replay import verify

    result = verify(args.dir, cycle=args.cycle, executor=args.executor)
    print(result.summary(), file=out)
    return 0 if result.match else 1


def _trace_diff(vc: VolcanoClient, args, out) -> int:
    """Replay and print the per-task binding diff (empty when identical),
    plus the cycle's recorded explain summary — a diff in which tasks
    simply went unplaced reads very differently when the journal shows
    the device proved them unschedulable (reason histogram) than when
    scoring genuinely diverged."""
    from volcano_tpu import trace as _trace
    from volcano_tpu.trace.replay import verify

    result = verify(args.dir, cycle=args.cycle, executor=args.executor)
    print(result.summary(), file=out)
    for task_idx, rec_node, rep_node in result.diffs[: args.limit]:
        print(
            f"  task[{task_idx}]: recorded node {rec_node} != "
            f"replayed node {rep_node}",
            file=out,
        )
    if len(result.diffs) > args.limit:
        print(f"  ... {len(result.diffs) - args.limit} more", file=out)
    try:
        record = _trace.Journal(args.dir).read_cycle(result.cycle)
    except Exception:  # noqa: BLE001 — events may be pruned; diff stands
        record = {}
    for e in record.get("events", []):
        if e.get("name") in ("explain-summary", "explain-no-victim"):
            a = e.get("args", {})
            print(
                f"  explain[{e['name']}]: {a.get('tasks', 0)} task(s) "
                f"unschedulable, reasons: {a.get('reasons', {})}",
                file=out,
            )
    return 0 if result.match else 1


def _trace_export(vc: VolcanoClient, args, out) -> int:
    from volcano_tpu.trace.export import (
        export_chrome_trace,
        export_merged_chrome_trace,
    )

    dirs = list(args.dir or [])
    if len(dirs) > 1:
        # per-process journals merge under distinct pid/tid rows on a
        # shared wall-clock origin — the multiproc drills' combined view
        text = export_merged_chrome_trace(
            dirs, cycle=args.cycle, path=args.out or None
        )
    else:
        text = export_chrome_trace(
            dirs[0], cycle=args.cycle, path=args.out or None
        )
    if args.out:
        print(f"wrote Chrome trace to {args.out}", file=out)
    else:
        print(text, file=out)
    return 0


# ---- flight recorder (volcano_tpu/obs): the cross-process waterfall ----

def _trace_identity(vc: VolcanoClient, args, out, gang: bool) -> int:
    """Shared body of ``vtctl trace pod`` / ``vtctl trace gang``:
    collect the durably-held telemetry segments from the bus, select
    the identity's trace (matched spans + ancestor closure + the
    cycles' process-scope sub-spans) and render the submit→bind
    waterfall; ``--chrome`` additionally writes the merged
    multi-process trace_event JSON with real pid/tid rows.  Reads only
    the API surface — identical over in-process and ``--bus``."""
    import json as _json

    from volcano_tpu import obs

    spans = obs.collect_spans(vc.api)
    if gang:
        idents = [(args.namespace, args.name)]
    else:
        # a pod's waterfall unions the pod, its PodGroup, and its
        # owning Job (the controller's status-writeback trace)
        idents = obs.related_identities(vc.api, args.namespace, args.name)
    trace = obs.select_union(spans, idents)
    kind = "gang" if gang else "pod"
    print(f"Flight recorder — {kind} {args.namespace}/{args.name} "
          f"(trace {obs.trace_id_for(args.namespace, args.name)})",
          file=out)
    obs.render_waterfall(trace, out)
    if getattr(args, "chrome", ""):
        with open(args.chrome, "w") as f:
            f.write(_json.dumps(obs.chrome_export(trace), indent=1))
        print(f"wrote merged Chrome trace to {args.chrome}", file=out)
    return 0 if trace else 1


def _trace_pod(vc: VolcanoClient, args, out) -> int:
    return _trace_identity(vc, args, out, gang=False)


def _trace_gang(vc: VolcanoClient, args, out) -> int:
    return _trace_identity(vc, args, out, gang=True)


# ---- top (federated /metrics aggregation) ----

#: the write-path ops whose latency the COMMIT column aggregates
_COMMIT_OPS = ("create", "commit_batch", "cas_bind", "txn_commit")


def _top_targets(vc: VolcanoClient, args) -> Dict[str, str]:
    """member label → host:port /metrics address.  Discovery is
    configuration-free: scheduler members advertise ``metricsAddr`` on
    the shard lease map's stats blob, apiserver replicas advertise
    ``metrics_address`` on ``bus_status`` (every endpoint in the
    ``--bus`` list is asked, since followers answer locally).
    ``--metrics a,b`` adds explicit extra targets."""
    from volcano_tpu.federation import read_shard_map

    targets: Dict[str, str] = {}
    try:
        rec = read_shard_map(vc.api)
    except ApiError:
        rec = None
    if rec:
        for ident in sorted(rec.get("stats") or {}):
            addr = (rec["stats"][ident] or {}).get("metricsAddr")
            if addr:
                targets[ident] = addr
    bus = getattr(args, "bus", "") or ""
    if bus:
        from volcano_tpu.bus import BusError, connect_bus

        for i, url in enumerate(u.strip() for u in bus.split(",")):
            if not url:
                continue
            try:
                remote = connect_bus(url, wait=2.0)
                try:
                    st = remote.bus_status()
                finally:
                    remote.close()
            except (BusError, ApiError):
                continue
            addr = st.get("metrics_address")
            if addr:
                targets[f"apiserver-{i} [{st.get('role', '?')}]"] = addr
    else:
        st = vc.api.bus_status() if hasattr(vc.api, "bus_status") else {}
        addr = st.get("metrics_address")
        if addr:
            targets[f"apiserver [{st.get('role', '?')}]"] = addr
    for addr in (getattr(args, "metrics", "") or "").split(","):
        addr = addr.strip()
        if addr:
            targets.setdefault(addr, addr)
    return targets


def _max_burn(s) -> float:
    """Worst fast-window SLO burn rate in one scrape — max over the
    ``volcano_slo_burn{window="fast"}`` series (summing across SLOs
    would manufacture a breach out of several healthy ones)."""
    values = [
        v for (name, labels), v in s.series.items()
        if name == "volcano_slo_burn" and ("window", "fast") in labels
    ]
    return max(values) if values else 0.0


def _top(vc: VolcanoClient, args, out) -> int:
    """Aggregate /metrics across the whole membership (one row per
    member + a cluster TOTAL row); ``--watch N`` redraws every N
    seconds (``--count`` bounds the frames), ``--json`` emits the same
    numbers machine-readably."""
    import time as _time

    watch = getattr(args, "watch", 0.0) or 0.0
    if watch <= 0:
        return _top_once(vc, args, out)
    count = getattr(args, "count", 0) or 0
    frames = 0
    rc = 0
    try:
        while True:
            rc = _top_once(vc, args, out)
            frames += 1
            if count and frames >= count:
                return rc
            _time.sleep(watch)
            print("", file=out)
    except KeyboardInterrupt:
        return rc


def _top_once(vc: VolcanoClient, args, out) -> int:
    """One ``vtctl top`` frame: per-member rows + a cluster-wide TOTAL
    row.  With ``--interval S`` two scrapes bound a window and the
    counters/histograms become rates and windowed percentiles;
    otherwise the columns are process-lifetime cumulative."""
    import time as _time

    from volcano_tpu.metrics import scrape as _scrape

    targets = _top_targets(vc, args)
    if not targets:
        print("no scrape targets discovered — need a running federation "
              "(shard map with metricsAddr), a --bus endpoint list, or "
              "explicit --metrics host:port", file=out)
        return 1

    def scrape_all() -> Dict[str, object]:
        scrapes = {}
        for label, addr in targets.items():
            try:
                scrapes[label] = _scrape.parse_metrics(
                    _scrape.fetch_metrics(addr)
                )
            except OSError as e:
                print(f"  scrape of {label} ({addr}) failed: {e}", file=out)
        return scrapes

    first = scrape_all()
    interval = getattr(args, "interval", 0.0) or 0.0
    if interval > 0:
        _time.sleep(interval)
        second = scrape_all()
        scrapes = {
            label: _scrape.delta(second[label], first[label])
            for label in second if label in first
        }
        window = f"{interval:g}s window"
    else:
        scrapes = first
        window = "cumulative"
    if not scrapes:
        print("every scrape failed", file=out)
        return 1

    def stats_for(s) -> dict:
        q = _scrape.histogram_quantile
        cycles = s.histogram("volcano_e2e_scheduling_latency_milliseconds")
        commit = _scrape.merge_histograms([h for h in (
            *(s.histogram("volcano_bus_request_latency_milliseconds",
                          method=op) for op in _COMMIT_OPS),
            *(s.histogram("volcano_bus_server_request_latency_milliseconds",
                          op=op) for op in _COMMIT_OPS),
        ) if h])
        return {
            "cycles": int((cycles or {}).get("count", 0)),
            "binds": int(s.value("volcano_pod_schedule_successes")),
            "s2bP99Ms": q(s.histogram(
                "volcano_submit_to_bind_latency_milliseconds"), 0.99),
            "commitP99Ms": q(commit, 0.99),
            "fsyncP99Ms": q(s.histogram(
                "volcano_wal_fsync_latency_milliseconds"), 0.99),
            "quorumP99Ms": q(s.histogram(
                "volcano_repl_quorum_wait_milliseconds"), 0.99),
            "dropped": int(s.value("volcano_telemetry_dropped_total")),
            "burn": _max_burn(s),
        }

    def row(label: str, st: dict) -> str:
        return (
            f"  {label:<30}"
            f"{st['cycles']:<8}"
            f"{st['binds']:<8}"
            f"{st['s2bP99Ms']:<9.1f}"
            f"{st['commitP99Ms']:<11.1f}"
            f"{st['fsyncP99Ms']:<10.1f}"
            f"{st['quorumP99Ms']:<11.1f}"
            f"{st['dropped']:<8}"
            f"{st['burn']:<6.2f}"
        )

    # cluster-wide: histograms merge pointwise, counters sum; the BURN
    # column takes the fleet max (a burn is a per-process judgement)
    total = _scrape.Scrape()
    for s in scrapes.values():
        for key, v in s.series.items():
            name = key[0]
            if name.endswith("_total") or name.endswith("_counts") or (
                "pod_schedule" in name
            ):
                total.series[key] = total.series.get(key, 0.0) + v
        for key, h in s.histograms.items():
            cur = total.histograms.get(key)
            total.histograms[key] = (
                _scrape.merge_histograms([cur, h]) if cur else h
            )
    member_stats = {label: stats_for(scrapes[label])
                    for label in sorted(scrapes)}
    cluster = stats_for(total)
    cluster["burn"] = max(
        [st["burn"] for st in member_stats.values()], default=0.0
    )
    if getattr(args, "json", False):
        import json as _json

        report = {"window": window, "members": member_stats,
                  "cluster": cluster}
        if interval > 0:
            report["bindRatePerS"] = round(cluster["binds"] / interval, 3)
        print(_json.dumps(report, indent=1, sort_keys=True), file=out)
        return 0
    print(f"Cluster metrics ({window}; {len(scrapes)} member(s)):",
          file=out)
    print(
        f"  {'MEMBER':<30}{'CYCLES':<8}{'BINDS':<8}{'S2B-99':<9}"
        f"{'COMMIT-99':<11}{'FSYNC-99':<10}{'QUORUM-99':<11}{'DROPPED':<8}"
        f"{'BURN':<6}",
        file=out,
    )
    for label, st in member_stats.items():
        print(row(label, st), file=out)
    print(row("CLUSTER", cluster), file=out)
    if interval > 0:
        print(f"  cluster bind rate: {cluster['binds'] / interval:.1f}/s",
              file=out)
    return 0


def _select_incidents(vc: VolcanoClient, args):
    from volcano_tpu import obs

    records = obs.list_incidents(vc.api)
    identity = getattr(args, "identity", "") or ""
    if identity:
        records = [r for r in records
                   if r["meta"].get("identity") == identity]
    return records


def _fmt_ts(ts: float) -> str:
    """Stored capture timestamp → fixed UTC rendering (derived from
    stored fields only — the byte-identity discipline)."""
    import datetime as _dt

    return _dt.datetime.utcfromtimestamp(ts).strftime(
        "%Y-%m-%dT%H:%M:%SZ")


def _incidents_list(vc: VolcanoClient, args, out) -> int:
    records = _select_incidents(vc, args)
    if not records:
        print("no incident bundles published on this bus", file=out)
        return 0
    print(f"  {'#':<4}{'WHEN (UTC)':<22}{'IDENTITY':<24}{'TRIGGER':<28}"
          f"{'SPANS':<7}ALERTS", file=out)
    for i, rec in enumerate(records):
        meta = rec["meta"]
        alerts = ",".join(a.get("name", "?")
                          for a in meta.get("alerts") or []) or "-"
        print(
            f"  {i:<4}{_fmt_ts(meta.get('ts', 0.0)):<22}"
            f"{meta.get('identity', '?'):<24}"
            f"{meta.get('reason', '?'):<28}"
            f"{len(rec['spans']):<7}{alerts}",
            file=out,
        )
    return 0


def _incidents_show(vc: VolcanoClient, args, out) -> int:
    import json as _json

    from volcano_tpu import obs

    records = _select_incidents(vc, args)
    if not records:
        print("no matching incident bundle", file=out)
        return 1
    index = args.index if args.index is not None else len(records) - 1
    if not 0 <= index < len(records):
        print(f"error: index {index} out of range "
              f"(0..{len(records) - 1})", file=out)
        return 1
    rec = records[index]
    meta = dict(rec["meta"])
    print(f"incident {rec['object']}:", file=out)
    print(_json.dumps(meta, indent=1, sort_keys=True), file=out)
    if rec["spans"]:
        print("", file=out)
        obs.render_waterfall(rec["spans"], out)
    return 0


def _incidents_collect(vc: VolcanoClient, args, out) -> int:
    """Pull every member's published incident summary into one local
    directory — the fleet-wide black-box retrieval."""
    import json as _json
    import os as _os

    records = _select_incidents(vc, args)
    if not records:
        print("no incident bundles published on this bus", file=out)
        return 0
    _os.makedirs(args.out, exist_ok=True)
    for rec in records:
        path = _os.path.join(args.out, f"{rec['object']}.json")
        with open(path, "w") as f:
            _json.dump(rec, f, indent=1, sort_keys=True)
    print(f"collected {len(records)} incident summar"
          f"{'y' if len(records) == 1 else 'ies'} into {args.out}",
          file=out)
    return 0


def _incidents_capture(vc: VolcanoClient, args, out) -> int:
    """Operator-initiated capture: arm the cluster-wide boost, wait
    the settle window so boosted-fidelity spans land, write a bundle
    locally from whatever the bus holds."""
    from volcano_tpu.obs.incident import IncidentManager, set_capture_boost

    identity = args.identity or "vtctl"
    try:
        boost = set_capture_boost(vc.api, identity, "manual",
                                  args.boost_ttl)
    except Exception as e:  # noqa: BLE001 — boostless capture still
        # beats no capture
        print(f"  capture-boost CAS failed ({e}); capturing unboosted",
              file=out)
        boost = None
    if args.settle > 0:
        time.sleep(args.settle)
    mgr = IncidentManager(
        vc.api, identity, args.dir,
        boost_ttl_s=args.boost_ttl, settle_s=0.0,
    )
    path = mgr.capture("manual", detail="vtctl incidents capture",
                       boost=boost)
    print(f"bundle: {path}", file=out)
    return 0


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(prog="vtctl", description="volcano-tpu control CLI")
    parser.add_argument(
        "--bus", default="",
        help="talk to a live vtpu-apiserver at tcp://host:port (the "
        "kubeconfig equivalent for the multi-process topology)",
    )
    sub = parser.add_subparsers(dest="group", required=True)

    job = sub.add_parser("job").add_subparsers(dest="cmd", required=True)

    run = job.add_parser("run")
    run.add_argument("--name", "-N", default="")
    run.add_argument("--namespace", "-n", default="default")
    run.add_argument("--image", "-i", default="busybox")
    run.add_argument("--replicas", "-r", type=int, default=1)
    run.add_argument("--min", dest="min_available", type=int, default=1)
    run.add_argument("--requests", "-R", default="cpu=1000m,memory=100Mi")
    run.add_argument("--limits", "-L", default="cpu=1000m,memory=100Mi")
    run.add_argument("--scheduler", "-S", default="volcano-tpu")
    run.add_argument("--queue", "-q", default="default")
    run.add_argument("--taskname", default="task")
    run.add_argument("--filename", "-f", default="")

    for name in ("list",):
        p = job.add_parser(name)
        p.add_argument("--namespace", "-n", default="")

    for name in ("view", "suspend", "resume", "delete"):
        p = job.add_parser(name)
        p.add_argument("--name", "-N", required=True)
        p.add_argument("--namespace", "-n", default="default")

    queue = sub.add_parser("queue").add_subparsers(dest="cmd", required=True)
    qc = queue.add_parser("create")
    qc.add_argument("--name", "-N", required=True)
    qc.add_argument("--weight", "-w", type=int, default=1)
    qg = queue.add_parser("get")
    qg.add_argument("--name", "-N", required=True)
    queue.add_parser("list")
    qo = queue.add_parser("operate")
    qo.add_argument("--name", "-N", required=True)
    qo.add_argument("--action", "-a", required=True, choices=["open", "close", "update"])
    qo.add_argument("--weight", "-w", type=int, default=None)
    qd = queue.add_parser("delete")
    qd.add_argument("--name", "-N", required=True)

    desc = sub.add_parser(
        "describe",
        description="conditions + events + unschedulable-reason histogram",
    ).add_subparsers(dest="cmd", required=True)
    for name in ("job", "podgroup"):
        p = desc.add_parser(name)
        p.add_argument("--name", "-N", required=True)
        p.add_argument("--namespace", "-n", default="default")

    shards = sub.add_parser(
        "shards",
        description="live shard map: lease holders, member heartbeats, "
        "spillover counters (sharded scheduler federation)",
    )
    shards.set_defaults(cmd=None)

    bus_p = sub.add_parser(
        "bus",
        description="replicated persistent bus (WAL + leader/follower "
        "apiserver HA)",
    ).add_subparsers(dest="cmd", required=True)
    bus_p.add_parser(
        "status",
        description="role, leader identity, term, WAL/snapshot sizes, "
        "fsync stats, per-follower replication lag, membership epoch",
    )
    bus_add = bus_p.add_parser(
        "add-replica",
        description="admit ONE new replica to the running replication "
        "group (dynamic membership, VBUS v7): start the new "
        "vtpu-apiserver with --replicas listing the whole new group "
        "(itself last) so it catches up as a learner, then run this — "
        "the leader logs a replicated membership record once the "
        "joiner's lag has closed",
    )
    bus_add.add_argument("url", help="the joiner's bus endpoint "
                         "(tcp://host:port)")
    bus_rm = bus_p.add_parser(
        "remove-replica",
        description="retire ONE replica from the running group; "
        "refused when the remaining members could not commit a write "
        "(reachable-majority floor) or when aimed at the leader",
    )
    bus_rm.add_argument("url", help="the retiring replica's bus endpoint")

    trace_p = sub.add_parser(
        "trace", description="cycle journal: record, replay, diff, export"
    ).add_subparsers(dest="cmd", required=True)

    tr = trace_p.add_parser("record", description="record synthetic cycles")
    tr.add_argument("--dir", "-d", required=True, help="journal directory")
    tr.add_argument("--tasks", type=int, default=1024)
    tr.add_argument("--nodes", type=int, default=256)
    tr.add_argument("--gang-size", dest="gang_size", type=int, default=8)
    tr.add_argument("--cycles", type=int, default=1)
    tr.add_argument("--seed", type=int, default=0)
    tr.add_argument(
        "--snapshot-every", dest="snapshot_every", type=int, default=1,
        help="capture a replayable snapshot every Nth cycle (0 = never)",
    )
    tr.add_argument("--keep", type=int, default=64, help="journal ring size")
    tr.add_argument(
        "--executor", default="jax",
        choices=["native", "jax", "blocked", "pallas", "auto"],
    )

    for name in ("replay", "diff"):
        tp = trace_p.add_parser(name)
        tp.add_argument("--dir", "-d", required=True)
        tp.add_argument("--cycle", type=int, default=None)
        tp.add_argument(
            "--executor", default="jax",
            choices=["native", "jax", "blocked", "pallas", "auto"],
        )
        if name == "diff":
            tp.add_argument("--limit", type=int, default=20)

    te = trace_p.add_parser("export")
    te.add_argument(
        "--dir", "-d", required=True, action="append",
        help="journal directory; repeat to merge several per-process "
        "journals into one Chrome trace with distinct pid rows on a "
        "shared clock origin",
    )
    te.add_argument("--cycle", type=int, default=None)
    te.add_argument("--out", "-o", default="", help="output file (default stdout)")

    for name in ("pod", "gang"):
        tp = trace_p.add_parser(
            name,
            description="flight recorder: render the cross-process "
            "submit→bind waterfall for one "
            + ("gang (PodGroup identity)" if name == "gang"
               else "pod identity")
            + " from the telemetry segments on the bus",
        )
        tp.add_argument("--name", "-N", required=True)
        tp.add_argument("--namespace", "-n", default="default")
        tp.add_argument(
            "--chrome", default="",
            help="also write the merged multi-process Chrome "
            "trace_event JSON here (real pid/tid rows)",
        )

    top = sub.add_parser(
        "top",
        description="aggregate /metrics across the whole membership "
        "(scheduler shards discovered from the shard lease map, "
        "apiserver replicas from the --bus endpoint list): per-member "
        "and cluster-wide rates, commit/fsync/quorum latency columns",
    )
    top.set_defaults(cmd=None)
    top.add_argument(
        "--metrics", default="",
        help="extra host:port /metrics targets, comma-separated "
        "(for daemons outside the federation/replica discovery)",
    )
    top.add_argument(
        "--interval", type=float, default=0.0,
        help="seconds between two scrapes: columns become windowed "
        "rates/percentiles instead of process-lifetime cumulative",
    )
    top.add_argument(
        "--watch", type=float, default=0.0, metavar="N",
        help="redraw every N seconds until interrupted",
    )
    top.add_argument(
        "--count", type=int, default=0,
        help="with --watch: stop after this many frames (0 = forever)",
    )
    top.add_argument(
        "--json", action="store_true",
        help="emit the per-member and cluster stats as JSON",
    )

    inc = sub.add_parser(
        "incidents", aliases=["incident"],
        description="cluster incident bundles — the black box the SLO "
        "burn-rate watchdog (or an operator) captures at a breach: "
        "kept traces, metrics window, bus/shard state, capture-boost "
        "record (volcano_tpu/obs/incident.py)",
    ).add_subparsers(dest="cmd", required=True)
    il = inc.add_parser(
        "list", description="every incident summary published on the "
        "bus, fleet-wide, oldest first",
    )
    il.add_argument("--identity", default="",
                    help="only bundles captured by this daemon identity")
    ish = inc.add_parser(
        "show", description="one incident's meta + the breach-window "
        "waterfall, from the stored summary",
    )
    ish.add_argument("--identity", default="")
    ish.add_argument("--index", type=int, default=None,
                     help="row from `incidents list` (default: latest)")
    ic = inc.add_parser(
        "collect", description="download every member's published "
        "incident summary into a local directory",
    )
    ic.add_argument("--identity", default="")
    ic.add_argument("--out", "-o", required=True,
                    help="destination directory")
    icap = inc.add_parser(
        "capture", description="operator-initiated capture: CAS the "
        "cluster-wide capture boost, wait --settle seconds for "
        "full-fidelity spans to land, write one bundle locally",
    )
    icap.add_argument("--dir", "-d", required=True,
                      help="bundle ring directory")
    icap.add_argument("--identity", default="",
                      help="identity stamped on the bundle "
                      "(default 'vtctl')")
    icap.add_argument("--settle", type=float, default=2.0,
                      help="seconds between boost and bundle write")
    icap.add_argument("--boost-ttl", type=float, default=30.0,
                      help="capture-boost TTL seconds")

    faults_p = sub.add_parser(
        "faults",
        description="fault-injection schedules (volcano_tpu.faults)",
    ).add_subparsers(dest="cmd", required=True)
    fv = faults_p.add_parser(
        "validate",
        description="parse a --faults/VTPU_FAULTS spec and print the "
        "normalized schedule (rejects typos before a chaos run)",
    )
    fv.add_argument("--spec", "-s", required=True)

    lint = sub.add_parser(
        "lint",
        description="run the project-invariant static-analysis suite "
        "(volcano_tpu.analysis: lock discipline, determinism, jit "
        "safety, VBUS serde drift); extra arguments are forwarded, "
        "e.g. `vtctl lint --pass lock --report out.json`",
    )
    lint.set_defaults(cmd=None)
    lint.add_argument("lint_args", nargs=argparse.REMAINDER)

    explore = sub.add_parser(
        "explore",
        description="deterministic interleaving explorer for the "
        "election / lease / gang-assembly protocols "
        "(volcano_tpu.analysis.explore); extra arguments are "
        "forwarded, e.g. `vtctl explore --quick` or "
        "`vtctl explore --replay election:71`",
    )
    explore.set_defaults(cmd=None)
    explore.add_argument("explore_args", nargs=argparse.REMAINDER)

    return parser


_HANDLERS = {
    ("job", "run"): _job_run,
    ("job", "list"): _job_list,
    ("job", "view"): _job_view,
    ("job", "suspend"): _job_suspend,
    ("job", "resume"): _job_resume,
    ("job", "delete"): _job_delete,
    ("queue", "create"): _queue_create,
    ("queue", "get"): _queue_get,
    ("queue", "list"): _queue_list,
    ("queue", "operate"): _queue_operate,
    ("queue", "delete"): _queue_delete,
    ("describe", "job"): _describe_job,
    ("describe", "podgroup"): _describe_podgroup,
    ("shards", None): _shards,
    ("top", None): _top,
    ("bus", "status"): _bus_status,
    ("bus", "add-replica"): _bus_add_replica,
    ("bus", "remove-replica"): _bus_remove_replica,
    ("faults", "validate"): _faults_validate,
    ("trace", "record"): _trace_record,
    ("trace", "replay"): _trace_replay,
    ("trace", "diff"): _trace_diff,
    ("trace", "export"): _trace_export,
    ("trace", "pod"): _trace_pod,
    ("trace", "gang"): _trace_gang,
    ("incidents", "list"): _incidents_list,
    ("incidents", "show"): _incidents_show,
    ("incidents", "collect"): _incidents_collect,
    ("incidents", "capture"): _incidents_capture,
    # the singular alias parses with group="incident"
    ("incident", "list"): _incidents_list,
    ("incident", "show"): _incidents_show,
    ("incident", "collect"): _incidents_collect,
    ("incident", "capture"): _incidents_capture,
}


def main(argv: Optional[List[str]] = None, api: Optional[APIServer] = None, out=None) -> int:
    out = out if out is not None else sys.stdout
    raw = list(sys.argv[1:] if argv is None else argv)
    # `lint` is intercepted before argparse: pure source analysis — no
    # store, no bus — and its flags are forwarded verbatim (argparse
    # REMAINDER refuses leading optionals).  The scan skips the root
    # parser's own options so `vtctl --bus X lint …` routes here too
    # (the bus is simply ignored; lint never touches a store).
    i = 0
    while i < len(raw):
        tok = raw[i]
        if tok == "--bus":
            i += 2
            continue
        if tok.startswith("--bus="):
            i += 1
            continue
        if tok == "lint":
            from volcano_tpu.analysis.__main__ import main as lint_main

            return lint_main(raw[i + 1:], out=out)
        if tok == "explore":
            # same shape as lint: in-process protocol exploration — no
            # store, no bus — with flags forwarded verbatim
            from volcano_tpu.analysis.explore import main as explore_main

            return explore_main(raw[i + 1:], out=out)
        break  # any other first positional/option: normal dispatch
    args = build_parser().parse_args(argv)
    remote = None
    if api is None and getattr(args, "bus", ""):
        from volcano_tpu.bus import BusError, connect_bus

        try:
            api = remote = connect_bus(args.bus, wait=10.0)
        except BusError as e:
            print(f"error: {e}", file=out)
            return 1
    if api is None:
        api = APIServer()  # empty standalone instance
    vc = VolcanoClient(api)
    handler = _HANDLERS[(args.group, args.cmd)]
    try:
        return handler(vc, args, out)
    except (ApiError, ValueError, OSError) as e:
        print(f"error: {e}", file=out)
        return 1
    except RuntimeError as e:
        # only for trace commands: RuntimeError there means a
        # supported-but-unavailable executor (replay --executor native
        # without the C++ toolchain, pallas off-TPU) — a user error,
        # not a crash.  Elsewhere it's a genuine internal error whose
        # traceback must surface.
        if args.group == "trace":
            print(f"error: {e}", file=out)
            return 1
        raise
    finally:
        if remote is not None:
            remote.close()


if __name__ == "__main__":
    sys.exit(main())
