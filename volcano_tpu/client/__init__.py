from volcano_tpu.client.apiserver import (
    ADDED,
    MODIFIED,
    DELETED,
    AdmissionError,
    AlreadyExistsError,
    APIServer,
    ApiError,
    ConflictError,
    NotFoundError,
)
from volcano_tpu.client.clients import KubeClient, SchedulerClient, VolcanoClient

__all__ = [
    "ADDED",
    "MODIFIED",
    "DELETED",
    "AdmissionError",
    "AlreadyExistsError",
    "APIServer",
    "ApiError",
    "ConflictError",
    "NotFoundError",
    "KubeClient",
    "SchedulerClient",
    "VolcanoClient",
]
