"""In-process API server — the communication bus of the framework.

Reference architecture: Volcano's only bus is the Kubernetes API server
(SURVEY.md §1); every binary talks exclusively to it via list/watch in and
REST out.  This standalone framework ships its own in-process equivalent:
a thread-safe versioned object store with watch fan-out and admission
hooks.  Controllers, the scheduler cache, admission and the CLI all
connect here.

The swap is real: ``volcano_tpu.bus.RemoteAPIServer`` implements this
exact interface over TCP against a ``vtpu-apiserver`` daemon (which is
this store behind ``volcano_tpu.bus.BusServer``), so every consumer runs
unchanged in either the single-process or the multi-process deployment
topology — pass ``--bus tcp://host:port`` to any daemon binary.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple


# Watch event types (client-go semantics).
ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"

WatchHandler = Callable[[str, Optional[object], Optional[object]], None]
# AdmissionHook(operation, obj) -> obj (mutating) or raises AdmissionError.
AdmissionHook = Callable[[str, object], object]


class ApiError(Exception):
    pass


class NotFoundError(ApiError):
    pass


class AlreadyExistsError(ApiError):
    pass


class ConflictError(ApiError):
    pass


class AdmissionError(ApiError):
    """Request rejected by an admission hook (the webhook deny path)."""


class APIServer:
    def __init__(self):
        self._lock = threading.RLock()
        self._store: Dict[str, Dict[str, object]] = {}  # guarded-by: self._lock
        self._watchers: Dict[str, List[WatchHandler]] = {}  # guarded-by: self._lock
        self._admission: Dict[Tuple[str, str], List[AdmissionHook]] = {}  # guarded-by: self._lock
        self._rv = 0  # guarded-by: self._lock
        #: reverse owner index for cascade deletion (the k8s garbage
        #: collector the reference relies on for Job → Pod/PodGroup/
        #: ConfigMap cleanup): (owner kind, ns, owner name) → set of
        #: (child kind, child key).  Entries are validated lazily at
        #: cascade time, so staleness is harmless.
        self._owned: Dict[Tuple[str, str, str], set] = {}  # guarded-by: self._lock

    # ---- helpers ----

    @contextlib.contextmanager
    def locked(self):
        """Hold the store lock.  Watch notifications fire under this
        lock, so a caller holding it can atomically combine a list with
        a subscription point — the primitive the network bus
        (volcano_tpu/bus) builds its gapless watch establishment on."""
        with self._lock:
            yield

    @staticmethod
    def _key(obj) -> str:
        return f"{obj.metadata.namespace}/{obj.metadata.name}"

    def _bump(self, obj) -> None:
        # requires-lock: self._lock
        self._rv += 1
        obj.metadata.resource_version = self._rv
        if not obj.metadata.creation_timestamp:
            obj.metadata.creation_timestamp = time.time()

    def _notify(self, kind: str, event: str, old, new) -> None:
        # requires-lock: self._lock
        for handler in self._watchers.get(kind, []):
            handler(event, old, new)

    def _run_admission(self, kind: str, operation: str, obj):
        # requires-lock: self._lock
        hooks = self._admission.get((kind, operation), [])
        if not hooks:
            return obj
        from volcano_tpu import obs

        if not obs.enabled():
            # recorder off: no trace-id hash / args dict built while
            # holding the store lock (the zero-cost-off budget)
            for hook in hooks:
                obj = hook(operation, obj) or obj
            return obj
        meta = getattr(obj, "metadata", None)
        with obs.span(
            "admission:review", cat="admission",
            trace_id=obs.trace_id_for(meta.namespace or "", meta.name or "")
            if meta is not None else None,
            args={"kind": kind, "operation": operation},
        ):
            for hook in hooks:
                obj = hook(operation, obj) or obj
        return obj

    def bus_status(self) -> dict:
        """Durability/replication status surface (``vtctl bus status``).
        The plain in-process store is neither persistent nor
        replicated; ``bus.PersistentAPIServer`` overrides this with the
        WAL/snapshot/replication fields, and ``bus.RemoteAPIServer``
        fetches the same payload over the wire — one renderer, every
        backend."""
        out = {"role": "standalone", "persistent": False}
        addr = getattr(self, "metrics_address", "")
        if addr:
            # the serving daemon's /metrics address — how `vtctl top`
            # discovers scrape targets from the --bus endpoint list
            out["metrics_address"] = addr
        return out

    # ---- admission registration (the webhook configuration) ----

    def register_admission(self, kind: str, operation: str, hook: AdmissionHook) -> None:
        """operation ∈ {CREATE, UPDATE}; hooks run in registration order,
        mutating first then validating by convention."""
        with self._lock:
            # registration races request-threads running the admission
            # chain (the bus server registers webhooks while serving) —
            # the unlocked setdefault was a lock-discipline lint catch
            self._admission.setdefault((kind, operation), []).append(hook)

    # ---- watch (the informer feed) ----

    def watch(self, kind: str, handler: WatchHandler, send_initial: bool = True) -> None:
        with self._lock:
            self._watchers.setdefault(kind, []).append(handler)
            if send_initial:
                for obj in list(self._store.get(kind, {}).values()):
                    handler(ADDED, None, obj)

    def unwatch(self, kind: str, handler: WatchHandler) -> None:
        """Detach a watch handler (a restarted BusServer must not leave
        its previous incarnation's central watchers firing forever)."""
        with self._lock:
            handlers = self._watchers.get(kind, [])
            if handler in handlers:
                handlers.remove(handler)

    # ---- CRUD ----

    def _register_owners(self, obj, key: str) -> None:
        # requires-lock: self._lock
        for ref in obj.metadata.owner_references:
            if not ref.controller:
                continue
            parent = (ref.kind, obj.metadata.namespace, ref.name)
            self._owned.setdefault(parent, set()).add((obj.kind, key))

    def _unregister_owners(self, obj, key: str) -> None:
        # requires-lock: self._lock
        """Prune the reverse index when a child is deleted or its owner
        refs change on update — without this the index grows unbounded
        and keys re-created under a dead owner's name inherit its doom."""
        for ref in obj.metadata.owner_references:
            if not ref.controller:
                continue
            parent = (ref.kind, obj.metadata.namespace, ref.name)
            members = self._owned.get(parent)
            if members is not None:
                members.discard((obj.kind, key))
                if not members:
                    del self._owned[parent]

    @staticmethod
    def _controlled_by(child, owner) -> bool:
        """Does ``child`` carry a controller ownerReference matching
        ``owner``?  The k8s GC matches owners by UID; fall back to
        kind+name when either side predates UID assignment."""
        for ref in child.metadata.owner_references:
            if not ref.controller:
                continue
            if ref.kind != owner.kind or ref.name != owner.metadata.name:
                continue
            if ref.uid and owner.metadata.uid:
                return ref.uid == owner.metadata.uid
            return True
        return False

    def create(self, obj):
        with self._lock:
            kind = obj.kind
            obj = self._run_admission(kind, "CREATE", obj)
            bucket = self._store.setdefault(kind, {})
            key = self._key(obj)
            if key in bucket:
                raise AlreadyExistsError(f"{kind} {key} already exists")
            self._bump(obj)
            stored = obj.clone()
            bucket[key] = stored
            self._register_owners(stored, key)
            self._notify(kind, ADDED, None, stored.clone())
            return obj

    def update(self, obj, expected_rv: Optional[int] = None):
        """Update; with ``expected_rv`` set, an optimistic-concurrency
        CAS: succeeds only if the stored resourceVersion still equals it
        (the k8s semantics the reference's ConfigMap leader lock relies
        on, cmd/scheduler/app/server.go:110-156).  Admission UPDATE hooks
        run either way, as they do for real k8s CAS updates."""
        with self._lock:
            kind = obj.kind
            obj = self._run_admission(kind, "UPDATE", obj)
            bucket = self._store.setdefault(kind, {})
            key = self._key(obj)
            old = bucket.get(key)
            if old is None:
                raise NotFoundError(f"{kind} {key} not found")
            if (
                expected_rv is not None
                and old.metadata.resource_version != expected_rv
            ):
                raise ConflictError(
                    f"{kind} {key} resourceVersion {old.metadata.resource_version}"
                    f" != expected {expected_rv}"
                )
            self._bump(obj)
            stored = obj.clone()
            bucket[key] = stored
            self._unregister_owners(old, key)
            self._register_owners(stored, key)
            self._notify(kind, MODIFIED, old.clone(), stored.clone())
            return obj

    def compare_and_update(self, obj, expected_rv: int):
        """CAS alias: ``update`` with a required expected resourceVersion."""
        return self.update(obj, expected_rv=expected_rv)

    def update_status(self, obj):
        """Status subresource write — same store, no admission."""
        with self._lock:
            kind = obj.kind
            bucket = self._store.setdefault(kind, {})
            key = self._key(obj)
            old = bucket.get(key)
            if old is None:
                raise NotFoundError(f"{kind} {key} not found")
            self._bump(obj)
            stored = obj.clone()
            bucket[key] = stored
            self._unregister_owners(old, key)
            self._register_owners(stored, key)
            self._notify(kind, MODIFIED, old.clone(), stored.clone())
            return obj

    def get(self, kind: str, namespace: str, name: str):
        with self._lock:
            obj = self._store.get(kind, {}).get(f"{namespace}/{name}")
            return obj.clone() if obj is not None else None

    def cas_bind(self, namespace: str, name: str, hostname: str,
                 expected_rv: Optional[int] = None):
        """Optimistic-concurrency binding write: set the pod's nodeName
        iff it is still unbound (and, when ``expected_rv`` is given, its
        resourceVersion is unchanged) — one atomic check-and-bind under
        the store lock.  The federation spillover primitive: concurrent
        schedulers racing for one pod resolve HERE, at the store, with a
        ConflictError for the loser (Omega-style shared-state
        concurrency; PAPERS.md).  Like the binding subresource it skips
        admission."""
        with self._lock:
            pod = self._store.get("Pod", {}).get(f"{namespace}/{name}")
            if pod is None:
                raise NotFoundError(f"Pod {namespace}/{name} not found")
            if pod.spec.node_name:
                raise ConflictError(
                    f"pod {namespace}/{name} already bound to "
                    f"{pod.spec.node_name}"
                )
            if (
                expected_rv is not None
                and pod.metadata.resource_version != expected_rv
            ):
                raise ConflictError(
                    f"Pod {namespace}/{name} resourceVersion "
                    f"{pod.metadata.resource_version} != expected "
                    f"{expected_rv}"
                )
            bound = pod.clone()
            bound.spec.node_name = hostname
            return self.update_status(bound)

    def txn_commit(self, binds=()) -> Dict[str, object]:
        """Atomic multi-object transaction: apply N ``cas_bind``s
        all-or-nothing under ONE store lock hold — the product of
        ``commit_batch`` (N effects, one transaction) and ``cas_bind``
        (conditional single-object bind), and the primitive cross-shard
        gang assembly stands on (federation/broker.py): a gang placed
        partly at home and partly on foreign shards either binds whole
        or not at all, so no observer — watcher, scheduler, or a crash
        — can ever see a partial gang.

        ``binds`` items: ``{namespace, name, hostname, expected_rv?}``.
        Every precondition (pod exists, still unbound, resourceVersion
        matches when given) is checked before ANY effect lands; the
        return is::

            {"committed": bool,
             "results": [None | "<error>" per item, input order],
             "objects": [bound pods] when committed, else []}

        On abort the per-item results say exactly which claim went
        stale (the caller discards the whole assembly and retries with
        fresh truth — the Omega conflict model, gang-sized).  Like the
        binding subresource it skips admission.  The persistent store
        overrides this to log the whole transaction as ONE WAL record
        riding the atomic ``commit_batch`` path, replicated and
        quorum-acked as a unit."""
        binds = list(binds)
        with self._lock:
            results: List[Optional[str]] = []
            pods = []
            seen: set = set()
            for b in binds:
                key = f"{b['namespace']}/{b['name']}"
                pod = self._store.get("Pod", {}).get(key)
                err = None
                if key in seen:
                    # two claims for one pod in one transaction: the
                    # sequential cas_bind equivalent would conflict on
                    # the second — committing last-write-wins would let
                    # a buggy planner believe two slots landed
                    err = (
                        f"ConflictError: duplicate claim for Pod {key} "
                        f"in one transaction"
                    )
                elif not b.get("hostname"):
                    # malformed items must abort in the SWEEP — a
                    # KeyError in the apply loop would land after
                    # earlier binds, creating the durable partial gang
                    # this op exists to forbid (the wire hands client
                    # payloads straight here)
                    err = (
                        f"ApiError: bind item for Pod {key} is missing "
                        f"a hostname"
                    )
                elif pod is None:
                    err = f"NotFoundError: Pod {key} not found"
                elif pod.spec.node_name:
                    err = (
                        f"ConflictError: pod {key} already bound to "
                        f"{pod.spec.node_name}"
                    )
                elif (
                    b.get("expected_rv") is not None
                    and pod.metadata.resource_version != b["expected_rv"]
                ):
                    err = (
                        f"ConflictError: Pod {key} resourceVersion "
                        f"{pod.metadata.resource_version} != expected "
                        f"{b['expected_rv']}"
                    )
                seen.add(key)
                results.append(err)
                pods.append(pod)
            if any(results):
                return {"committed": False, "results": results,
                        "objects": []}
            out = []
            for b, pod in zip(binds, pods):
                bound = pod.clone()
                bound.spec.node_name = b["hostname"]
                out.append(self.update_status(bound))
            return {"committed": True, "results": [None] * len(binds),
                    "objects": out}

    # ---- coalesced commit transaction (the multi-bind frame) ----

    def commit_batch(
        self,
        binds=(),
        evicts=(),
        events=(),
        conditions=(),
        pod_groups=(),
    ) -> Dict[str, List[Optional[str]]]:
        """Apply one coalesced commit frame — N pod bindings, evictions,
        audit events, pod conditions, and PodGroup status writebacks —
        under ONE store lock hold, so the whole scheduler cycle's commit
        is a single store transaction with one watch-notification flush
        instead of O(pods) independent round trips.

        Sections (plain dicts; ``pod_groups`` are API objects):

        * ``binds``: ``{namespace, name, hostname, event?}`` — the
          binding subresource write (get + node_name + update_status,
          exactly ``KubeClient.bind_pod``); on success the optional
          ``event`` (``{type, reason, message}``) is recorded — the
          per-object path's success-gated Scheduled audit event.
        * ``evicts``: ``{namespace, name, event?}`` — pod delete with
          the same success-gated Evict event.
        * ``events``: ``{namespace, involved, type, reason, message}``
          — standalone audit events (Unschedulable writebacks), run
          through the same aggregation correlator as record_event.
        * ``conditions``: ``{namespace, name, reason, message}`` — the
          PodScheduled=False condition write.
        * ``pod_groups``: PodGroup objects for status writeback, with
          the raw-v1alpha1 fallback ``SchedulerClient.update_pod_group``
          applies.

        Per-item failures are COLLECTED, not raised: the return maps
        each section to a list of ``None`` (success) or an error string
        aligned with the input order, so the caller can route failed
        binds/evicts to the resync path exactly like the per-object
        effects do.  Like ``update_status``, the binding/status writes
        skip admission (status subresources); event creates run the
        in-process admission chain via the normal ``create`` path.

        The per-item application lives in :func:`apply_commit_batch`,
        which works against ANY APIServer surface — the remote client's
        old-server fallback runs the same items per-object over the
        wire."""
        with self._lock:
            return apply_commit_batch(
                self, binds=binds, evicts=evicts, events=events,
                conditions=conditions, pod_groups=pod_groups,
            )

    def list(self, kind: str, namespace: Optional[str] = None) -> List:
        with self._lock:
            out = []
            for key, obj in self._store.get(kind, {}).items():
                if namespace is None or obj.metadata.namespace == namespace:
                    out.append(obj.clone())
            return sorted(out, key=lambda o: (o.metadata.namespace, o.metadata.name))

    def delete(self, kind: str, namespace: str, name: str):
        with self._lock:
            bucket = self._store.get(kind, {})
            key = f"{namespace}/{name}"
            old = bucket.pop(key, None)
            if old is None:
                raise NotFoundError(f"{kind} {key} not found")
            self._unregister_owners(old, key)
            # Owner-reference cascade — the k8s garbage collector the
            # reference leans on: deleting a Job must take its Pods,
            # PodGroup, and plugin resources (ConfigMaps/Secrets) with
            # it (createJobPod sets the controller ownerReference;
            # pkg/apis/helpers CreatedBy*).  Children are popped
            # transitively under the same lock; DELETED notifications
            # fire parent-first so controller caches unwind top-down.
            # A stale index entry — the child was deleted directly and a
            # NEW object re-created under the same (kind, key) — must NOT
            # cascade: like the k8s GC, ownership is re-verified against
            # the child's CURRENT controller ownerReference (by UID when
            # both sides carry one, else kind+name).
            deleted = [(kind, old)]
            frontier = [old]
            while frontier:
                owner = frontier.pop()
                parent = (
                    owner.kind,
                    owner.metadata.namespace,
                    owner.metadata.name,
                )
                survivors = set()
                for ckind, ckey in self._owned.pop(parent, ()):  # noqa: B020
                    cbucket = self._store.get(ckind, {})
                    child = cbucket.get(ckey)
                    if child is None:
                        continue  # stale index entry — drop
                    if not self._controlled_by(child, owner):
                        # same owner key but a different controller (the
                        # owner name was re-created with a new uid) —
                        # keep the entry for that owner's own cascade
                        survivors.add((ckind, ckey))
                        continue
                    del cbucket[ckey]
                    self._unregister_owners(child, ckey)
                    deleted.append((ckind, child))
                    frontier.append(child)
                if survivors:
                    self._owned[parent] = survivors
            for dkind, dobj in deleted:
                self._notify(dkind, DELETED, dobj.clone(), None)
            return old

def apply_commit_batch(
    api,
    binds=(),
    evicts=(),
    events=(),
    conditions=(),
    pod_groups=(),
) -> Dict[str, List[Optional[str]]]:
    """Apply the commit-frame sections through ``api``'s public surface
    — delegating to the SAME typed-client helpers the per-object
    effects use (``KubeClient.bind_pod`` / ``update_pod_condition``,
    the event correlator, ``SchedulerClient.update_pod_group``'s
    v1alpha1 fallback), so batched and per-object semantics cannot
    drift.  One copy shared by the in-process store transaction (which
    wraps this in its lock) and the bus client's per-object old-server
    fallback."""
    from volcano_tpu.apis import scheme
    from volcano_tpu.client.clients import KubeClient, record_event_via

    kube = KubeClient(api)

    results: Dict[str, List[Optional[str]]] = {
        "binds": [], "evicts": [], "events": [],
        "conditions": [], "pod_groups": [],
    }

    def _err(e: Exception) -> str:
        return f"{type(e).__name__}: {e}"

    def _commit_event(namespace: str, name: str, event) -> None:
        # success-gated audit event for a bind/evict item — best-effort,
        # like the per-object _record_event discipline
        if not event:
            return
        try:
            record_event_via(
                api, namespace,
                {"kind": "Pod", "namespace": namespace, "name": name},
                event["type"], event["reason"], event["message"],
            )
        except ApiError:
            pass

    for b in binds:
        try:
            kube.bind_pod(b["namespace"], b["name"], b["hostname"])
            results["binds"].append(None)
        except ApiError as e:
            results["binds"].append(_err(e))
            continue
        _commit_event(b["namespace"], b["name"], b.get("event"))
    for ev in evicts:
        try:
            api.delete("Pod", ev["namespace"], ev["name"])
            results["evicts"].append(None)
        except ApiError as e:
            results["evicts"].append(_err(e))
            continue
        _commit_event(ev["namespace"], ev["name"], ev.get("event"))
    for e in events:
        try:
            record_event_via(
                api, e["namespace"], e["involved"], e["type"],
                e["reason"], e["message"],
            )
            results["events"].append(None)
        except ApiError as exc:
            results["events"].append(_err(exc))
    for c in conditions:
        try:
            # silently no-ops when the pod is gone, like the per-object
            # update_pod_condition
            kube.update_pod_condition(
                c["namespace"], c["name"], c["reason"], c["message"]
            )
            results["conditions"].append(None)
        except ApiError as e:
            results["conditions"].append(_err(e))
    for pg in pod_groups:
        try:
            api.update_status(pg)
            results["pod_groups"].append(None)
        except NotFoundError:
            # raw-v1alpha1 residents (the dual informer set) get status
            # written to THAT kind, like SchedulerClient.update_pod_group
            # — including its missing-from-both silent no-op (a job
            # deleted mid-cycle must not read as a commit failure)
            try:
                api.update_status(scheme.pod_group_hub_to_v1alpha1(pg))
                results["pod_groups"].append(None)
            except NotFoundError:
                results["pod_groups"].append(None)
            except ApiError as e:
                results["pod_groups"].append(_err(e))
        except ApiError as e:
            results["pod_groups"].append(_err(e))
    return results

