"""Typed clients over the in-process API server.

Reference: the generated clientsets (pkg/client) — here thin typed
facades, since serde/codegen is unnecessary for in-process dataclasses.
``SchedulerClient`` is the adapter the scheduler cache drives for its
informer feed and bind/evict/status side effects.
"""

from __future__ import annotations

from typing import List, Optional

from volcano_tpu.apis import batch, bus, core, scheduling, scheme
from volcano_tpu.client.apiserver import ADDED, APIServer, DELETED, MODIFIED, NotFoundError


class KubeClient:
    """Core-group operations (pods/nodes/services/configmaps/secrets/pvcs)."""

    def __init__(self, api: APIServer):
        self.api = api

    # pods
    def create_pod(self, pod: core.Pod) -> core.Pod:
        return self.api.create(pod)

    def get_pod(self, namespace: str, name: str) -> Optional[core.Pod]:
        return self.api.get("Pod", namespace, name)

    def list_pods(self, namespace: Optional[str] = None) -> List[core.Pod]:
        return self.api.list("Pod", namespace)

    def delete_pod(self, namespace: str, name: str) -> None:
        self.api.delete("Pod", namespace, name)

    def bind_pod(self, namespace: str, name: str, hostname: str) -> None:
        """POST /binding equivalent (cache.go defaultBinder:122-134)."""
        pod = self.api.get("Pod", namespace, name)
        if pod is None:
            raise NotFoundError(f"pod {namespace}/{name} not found")
        pod.spec.node_name = hostname
        self.api.update_status(pod)

    def update_pod(self, pod: core.Pod) -> core.Pod:
        return self.api.update(pod)

    def update_pod_status(self, pod: core.Pod) -> core.Pod:
        return self.api.update_status(pod)

    def update_pod_condition(self, namespace: str, name: str, reason: str, message: str) -> None:
        pod = self.api.get("Pod", namespace, name)
        if pod is None:
            return
        for cond in pod.status.conditions:
            if cond.type == "PodScheduled":
                cond.status, cond.reason, cond.message = "False", reason, message
                break
        else:
            pod.status.conditions.append(
                core.PodCondition(type="PodScheduled", status="False", reason=reason, message=message)
            )
        self.api.update_status(pod)

    # nodes
    def create_node(self, node: core.Node) -> core.Node:
        return self.api.create(node)

    def list_nodes(self) -> List[core.Node]:
        return self.api.list("Node")

    # namespaced simple kinds
    def create_service(self, svc: core.Service) -> core.Service:
        return self.api.create(svc)

    def get_service(self, namespace: str, name: str) -> Optional[core.Service]:
        return self.api.get("Service", namespace, name)

    def create_config_map(self, cm: core.ConfigMap) -> core.ConfigMap:
        return self.api.create(cm)

    def get_config_map(self, namespace: str, name: str) -> Optional[core.ConfigMap]:
        return self.api.get("ConfigMap", namespace, name)

    def update_config_map(self, cm: core.ConfigMap) -> core.ConfigMap:
        return self.api.update(cm)

    def create_secret(self, secret: core.Secret) -> core.Secret:
        return self.api.create(secret)

    def get_secret(self, namespace: str, name: str) -> Optional[core.Secret]:
        return self.api.get("Secret", namespace, name)

    def delete_secret(self, namespace: str, name: str) -> None:
        self.api.delete("Secret", namespace, name)

    def create_network_policy(self, np: core.NetworkPolicy) -> core.NetworkPolicy:
        return self.api.create(np)

    def create_pvc(self, pvc: core.PersistentVolumeClaim) -> core.PersistentVolumeClaim:
        return self.api.create(pvc)

    def update_pvc(self, pvc: core.PersistentVolumeClaim) -> core.PersistentVolumeClaim:
        return self.api.update(pvc)

    def list_pvcs(self, namespace: Optional[str] = None) -> List[core.PersistentVolumeClaim]:
        return self.api.list("PersistentVolumeClaim", namespace)

    def get_pvc(self, namespace: str, name: str) -> Optional[core.PersistentVolumeClaim]:
        return self.api.get("PersistentVolumeClaim", namespace, name)

    def create_priority_class(self, pc: core.PriorityClass) -> core.PriorityClass:
        return self.api.create(pc)

    def create_event(self, event: core.Event) -> core.Event:
        return self.api.create(event)

    def list_events(self, namespace: Optional[str] = None) -> List[core.Event]:
        return self.api.list("Event", namespace)


class VolcanoClient:
    """CRD-group operations (jobs/podgroups/queues/commands)."""

    def __init__(self, api: APIServer):
        self.api = api

    # jobs
    def create_job(self, job: batch.Job) -> batch.Job:
        return self.api.create(job)

    def get_job(self, namespace: str, name: str) -> Optional[batch.Job]:
        return self.api.get("Job", namespace, name)

    def list_jobs(self, namespace: Optional[str] = None) -> List[batch.Job]:
        return self.api.list("Job", namespace)

    def update_job(self, job: batch.Job) -> batch.Job:
        return self.api.update(job)

    def update_job_status(self, job: batch.Job) -> batch.Job:
        return self.api.update_status(job)

    def delete_job(self, namespace: str, name: str) -> None:
        self.api.delete("Job", namespace, name)

    # podgroups
    def create_pod_group(self, pg: scheduling.PodGroup) -> scheduling.PodGroup:
        return self.api.create(pg)

    def get_pod_group(self, namespace: str, name: str) -> Optional[scheduling.PodGroup]:
        return self.api.get("PodGroup", namespace, name)

    def list_pod_groups(self, namespace: Optional[str] = None) -> List[scheduling.PodGroup]:
        return self.api.list("PodGroup", namespace)

    def update_pod_group(self, pg: scheduling.PodGroup) -> scheduling.PodGroup:
        return self.api.update_status(pg)

    def delete_pod_group(self, namespace: str, name: str) -> None:
        self.api.delete("PodGroup", namespace, name)

    # versioned creates (the v1alpha1 client surface; objects convert
    # through the scheme to the hub/storage version before the store —
    # pkg/apis/scheduling/scheme semantics)
    def create_pod_group_v1alpha1(self, pg):
        """Returns the stored object converted BACK to v1alpha1 — a
        versioned clientset is uniformly versioned on create and get."""
        hub = self.api.create(scheme.pod_group_v1alpha1_to_hub(pg))
        return scheme.pod_group_hub_to_v1alpha1(hub)

    def create_queue_v1alpha1(self, queue):
        hub = self.api.create(scheme.queue_v1alpha1_to_hub(queue))
        return scheme.queue_hub_to_v1alpha1(hub)

    def get_queue_v1alpha1(self, name: str):
        q = self.get_queue(name)
        return scheme.queue_hub_to_v1alpha1(q) if q is not None else None

    # queues
    def create_queue(self, queue: scheduling.Queue) -> scheduling.Queue:
        return self.api.create(queue)

    def get_queue(self, name: str) -> Optional[scheduling.Queue]:
        return self.api.get("Queue", "", name)

    def list_queues(self) -> List[scheduling.Queue]:
        return self.api.list("Queue")

    def update_queue(self, queue: scheduling.Queue) -> scheduling.Queue:
        return self.api.update(queue)

    def update_queue_status(self, queue: scheduling.Queue) -> scheduling.Queue:
        return self.api.update_status(queue)

    def delete_queue(self, name: str) -> None:
        self.api.delete("Queue", "", name)

    # commands
    def create_command(self, cmd: bus.Command) -> bus.Command:
        return self.api.create(cmd)

    def delete_command(self, namespace: str, name: str) -> None:
        self.api.delete("Command", namespace, name)

    def list_commands(self, namespace: Optional[str] = None) -> List[bus.Command]:
        return self.api.list("Command", namespace)


class SchedulerClient:
    """The scheduler cache's view: informer wiring + side-effect REST calls.

    Mirrors the informer set in pkg/scheduler/cache/cache.go:321-427 (the
    subset with behavioral content: pods, nodes, podgroups, queues,
    priority classes, resource quotas)."""

    def __init__(self, api: APIServer):
        self.api = api
        self.kube = KubeClient(api)
        self.vc = VolcanoClient(api)

    def watch(self, cache) -> None:
        def pods(event, old, new):
            if event == ADDED:
                cache.add_pod(new)
            elif event == MODIFIED:
                cache.update_pod(old, new)
            elif event == DELETED:
                cache.delete_pod(old)

        def nodes(event, old, new):
            if event == ADDED:
                cache.add_node(new)
            elif event == MODIFIED:
                cache.update_node(old, new)
            elif event == DELETED:
                cache.delete_node(old)

        def pod_groups(event, old, new):
            if event == ADDED:
                cache.add_pod_group(new)
            elif event == MODIFIED:
                cache.update_pod_group(old, new)
            elif event == DELETED:
                cache.delete_pod_group(old)

        def queues(event, old, new):
            if event == ADDED:
                cache.add_queue(new)
            elif event == MODIFIED:
                cache.update_queue(old, new)
            elif event == DELETED:
                cache.delete_queue(old)

        def priority_classes(event, old, new):
            if event in (ADDED, MODIFIED):
                cache.add_priority_class(new)
            elif event == DELETED:
                cache.delete_priority_class(old)

        def pvcs(event, old, new):
            if event == ADDED:
                cache.add_pvc(new)
            elif event == MODIFIED:
                cache.update_pvc(old, new)
            elif event == DELETED:
                cache.delete_pvc(old)

        # dual informer set (cache.go:393-424): legacy writers that put
        # RAW v1alpha1 objects on the bus feed the same cache through the
        # converting handler set
        def pod_groups_v1alpha1(event, old, new):
            if event == ADDED:
                cache.add_pod_group_v1alpha1(new)
            elif event == MODIFIED:
                cache.update_pod_group_v1alpha1(old, new)
            elif event == DELETED:
                cache.delete_pod_group_v1alpha1(old)

        def queues_v1alpha1(event, old, new):
            if event == ADDED:
                cache.add_queue_v1alpha1(new)
            elif event == MODIFIED:
                cache.update_queue_v1alpha1(old, new)
            elif event == DELETED:
                cache.delete_queue_v1alpha1(old)

        self.api.watch("Pod", pods)
        self.api.watch("Node", nodes)
        self.api.watch("PodGroup", pod_groups)
        self.api.watch("Queue", queues)
        self.api.watch("PodGroupV1alpha1", pod_groups_v1alpha1)
        self.api.watch("QueueV1alpha1", queues_v1alpha1)
        self.api.watch("PriorityClass", priority_classes)
        self.api.watch("PersistentVolumeClaim", pvcs)

    # side effects used by SchedulerCache
    def bind_pod(self, namespace: str, name: str, hostname: str) -> None:
        self.kube.bind_pod(namespace, name, hostname)

    def delete_pod(self, namespace: str, name: str) -> None:
        self.kube.delete_pod(namespace, name)

    def get_pod(self, namespace: str, name: str) -> Optional[core.Pod]:
        return self.kube.get_pod(namespace, name)

    def update_pod_condition(self, namespace: str, name: str, reason: str, message: str) -> None:
        self.kube.update_pod_condition(namespace, name, reason, message)

    def update_pod_group(self, pg: scheduling.PodGroup) -> Optional[scheduling.PodGroup]:
        try:
            return self.vc.update_pod_group(pg)
        except NotFoundError:
            # the object may live on the bus as a RAW v1alpha1 kind (the
            # dual informer set read it); write status back to THAT kind
            try:
                v1 = scheme.pod_group_hub_to_v1alpha1(pg)
                self.api.update_status(v1)
                return pg
            except NotFoundError:
                return None

    def update_pvc(self, pvc: core.PersistentVolumeClaim) -> core.PersistentVolumeClaim:
        return self.kube.update_pvc(pvc)

    def commit_batch(self, binds=(), evicts=(), events=(), conditions=(),
                     pod_groups=()):
        """Coalesced commit frame — one store transaction for N binds /
        evicts / events / conditions / PodGroup writebacks (the commit
        plane's fast path).  Works against both backends: the in-process
        APIServer applies it under one lock hold, the RemoteAPIServer
        ships it as one VBUS frame (with a per-object fallback for
        old servers)."""
        return self.api.commit_batch(
            binds=binds, evicts=evicts, events=events,
            conditions=conditions, pod_groups=pod_groups,
        )

    def record_event(
        self,
        namespace: str,
        involved: dict,
        type_: str,
        reason: str,
        message: str,
    ) -> core.Event:
        return record_event_via(self.api, namespace, involved, type_,
                                reason, message)


def record_event_via(
    api,
    namespace: str,
    involved: dict,
    type_: str,
    reason: str,
    message: str,
) -> core.Event:
    """Event recorder (the scheduler's user-facing audit trail —
    cache.go:304-306 eventBroadcaster + :600-610, 832-867 call
    sites).  Repeats of the same (object, type, reason) aggregate
    into one Event with a bumped ``count`` — the k8s correlator's
    aggregation key excludes the message precisely so that
    variable-detail repeats (\"failed to bind to n1: ...\", \"... n2:
    ...\") cannot mint unbounded distinct Events for one stuck
    object across scheduling cycles.

    ``api`` is any APIServer surface (in-process or a bus
    RemoteAPIServer) — the single copy shared by SchedulerClient and
    the bus client, so Events recorded over the wire aggregate
    identically to in-process ones."""
    import hashlib

    digest = hashlib.sha1(
        f"{involved.get('kind')}/{involved.get('name')}|{type_}|{reason}".encode()
    ).hexdigest()[:10]
    name = f"{involved.get('name', 'obj')}.{digest}"
    existing = api.get("Event", namespace, name)
    if existing is not None:
        existing.count += 1
        # refresh to the latest occurrence's detail, like the k8s
        # correlator — operators act on the current cause, not the
        # first-seen one
        existing.message = message
        return api.update(existing)
    return api.create(
        core.Event(
            metadata=core.ObjectMeta(name=name, namespace=namespace),
            involved_object=involved,
            type=type_,
            reason=reason,
            message=message,
        )
    )
