"""Daemon entry points — the cmd/{scheduler,controllers,admission}
binaries of the reference (cmd/scheduler/main.go:46-68,
cmd/controllers/main.go, cmd/admission/main.go), rebuilt as daemon
classes over the in-process API server plus argparse mains.

Each daemon carries the reference binary's serving surface: healthz +
/metrics HTTP (ServingServer) and optional ConfigMap-lock leader
election (LeaderElector) gating its work loop.
"""

from volcano_tpu.cmd.admission import AdmissionDaemon
from volcano_tpu.cmd.apiserver import ApiServerDaemon
from volcano_tpu.cmd.controllers import ControllersDaemon
from volcano_tpu.cmd.scheduler import SchedulerDaemon

__all__ = [
    "AdmissionDaemon",
    "ApiServerDaemon",
    "ControllersDaemon",
    "SchedulerDaemon",
]
