"""vtpu-admission — the admission-webhook daemon.

Reference: cmd/admission/app/server.go:37-99 — registers the webhook
configurations (validate/mutate jobs, validate pods) and serves; here
registration targets the in-process API server's admission chain and
the serving surface carries healthz + metrics.
"""

from __future__ import annotations

import argparse
import time

from volcano_tpu.admission import register_webhooks
from volcano_tpu.client import APIServer  # noqa: F401 — the in-process default
from volcano_tpu.cmd.daemon import apply_faults
from volcano_tpu.cmd.scheduler import add_common_args, resolve_bus
from volcano_tpu.serving import ServingServer
from volcano_tpu.utils.logging import get_logger

log = get_logger(__name__)


class AdmissionDaemon:
    """The admission binary: webhook registration + serving surface."""

    def __init__(
        self,
        api: APIServer,
        gate_pods: bool = False,
        listen_host: str = "127.0.0.1",
        listen_port: int = 0,
        debug_enabled: bool = False,
        flight_recorder: bool = None,
    ):
        self.api = api
        register_webhooks(api, gate_pods=gate_pods)
        self.serving = ServingServer(
            host=listen_host, port=listen_port, debug_enabled=debug_enabled
        )
        if flight_recorder is None:
            import os

            flight_recorder = os.environ.get(
                "VTPU_FLIGHT_RECORDER", ""
            ) not in ("", "0")
        self.flight_recorder = flight_recorder
        self._obs_exporter = None

    def start(self) -> "AdmissionDaemon":
        from volcano_tpu.metrics import metrics

        metrics.set_identity(daemon="admission", role="admission")
        if self.flight_recorder:
            import os

            from volcano_tpu import obs

            self._obs_exporter = obs.enable(
                self.api, identity=f"admission-{os.getpid()}"
            )
        self.serving.start()
        log.info("admission daemon serving on :%d", self.serving.port)
        return self

    def stop(self) -> None:
        if self._obs_exporter is not None:
            from volcano_tpu import obs

            if obs.get_exporter() is self._obs_exporter:
                obs.disable()
            else:
                self._obs_exporter.stop()
            self._obs_exporter = None
        self.serving.stop()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="vtpu-admission")
    parser.add_argument("--gate-pods", action="store_true")
    add_common_args(parser)
    args = parser.parse_args(argv)
    apply_faults(args.faults)
    daemon = AdmissionDaemon(
        resolve_bus(args.bus),
        gate_pods=args.gate_pods,
        listen_host=args.listen_host,
        listen_port=args.listen_port,
        debug_enabled=args.enable_debug_stacks,
        flight_recorder=True if args.flight_recorder else None,
        # --watchdog is a no-op here: the webhook daemon runs no work
        # loop and owns none of the declared SLO signals
    )
    daemon.start()
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        daemon.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
