"""vtpu-apiserver — the standalone API-server daemon.

The reference deploys Kubernetes' API server as the bus all binaries
meet at; this is the standalone build's equivalent: the in-process
object store (client/apiserver.py) served over TCP by
``bus.BusServer``, plus the standard serving surface (healthz +
/metrics) every other daemon carries.

With this daemon up, every other binary — vtpu-scheduler,
vtpu-controllers, vtpu-admission, vtctl — connects with
``--bus tcp://host:port`` and the system runs as the reference's
multi-process deployment topology, including cross-process leader
election (the scheduler's ConfigMap lease lives on this store).

Durability + HA (ROADMAP item 4):

* ``--data-dir DIR`` swaps the volatile store for
  ``bus.PersistentAPIServer`` — every store transaction is WAL'd and
  fsynced before acking, snapshots rotate the log, and a restart with
  the same dir resumes watch cursors instead of forcing a 410 relist
  storm.
* ``--replicas tcp://a,tcp://b,... --replica-index I`` joins this
  daemon to a replication group (requires ``--data-dir``): one leader
  takes writes, followers replicate its WAL and serve reads/watches,
  and a SIGKILLed leader is replaced by the most-advanced survivor
  within one lease TTL (``--repl-lease-ttl``).  Point clients at the
  whole list: ``--bus tcp://a,tcp://b,...``.
"""

from __future__ import annotations

import argparse
import os
import threading
from typing import List, Optional

from volcano_tpu.bus.server import BusServer
from volcano_tpu.client.apiserver import APIServer
from volcano_tpu.serving import ServingServer
from volcano_tpu.utils.logging import get_logger

log = get_logger(__name__)

DEFAULT_BUS_PORT = 7180


class ApiServerDaemon:
    """The apiserver binary: store + bus listener + serving surface,
    optionally durable (``data_dir``) and replicated (``replicas``)."""

    def __init__(
        self,
        api: Optional[APIServer] = None,
        listen_host: str = "127.0.0.1",
        bus_port: int = DEFAULT_BUS_PORT,
        listen_port: int = 0,
        backlog_size: int = 4096,
        bookmark_interval: float = 2.0,
        debug_enabled: bool = False,
        seed_nodes: int = 0,
        seed_node_cpu: str = "8",
        seed_node_mem: str = "32Gi",
        data_dir: str = "",
        snapshot_every: int = 256,
        replicas: Optional[List[str]] = None,
        replica_index: int = 0,
        repl_lease_ttl: float = 2.0,
        flight_recorder: Optional[bool] = None,
        watchdog: Optional[bool] = None,
        incident_dir: Optional[str] = None,
    ):
        if flight_recorder is None:
            flight_recorder = os.environ.get(
                "VTPU_FLIGHT_RECORDER", ""
            ) not in ("", "0")
        self.flight_recorder = flight_recorder
        self._obs_exporter = None
        if watchdog is None:
            watchdog = os.environ.get("VTPU_WATCHDOG", "") not in ("", "0")
        if incident_dir is None:
            incident_dir = os.environ.get("VTPU_INCIDENT_DIR", "")
        self.watchdog_enabled = watchdog
        self.incident_dir = incident_dir
        self.watchdog = None
        self.incidents = None
        self.replica_index = replica_index
        self.replica = None
        if api is not None:
            self.api = api
        elif data_dir:
            from volcano_tpu.bus.wal import PersistentAPIServer

            self.api = PersistentAPIServer(
                data_dir, snapshot_every=snapshot_every,
                backlog_keep=backlog_size,
            )
            # the SIGKILL-mid-commit chaos point (bus.leader_kill):
            # crash-stop exactly like the federation's shard.kill
            self.api.kill_hook = lambda: os._exit(137)
        else:
            self.api = APIServer()
        if replicas and len(replicas) > 1:
            from volcano_tpu.bus.wal import PersistentAPIServer

            if not isinstance(self.api, PersistentAPIServer):
                raise ValueError(
                    "--replicas requires --data-dir (replication ships "
                    "WAL records; a volatile store has none)"
                )
            from volcano_tpu.bus.replication import ReplicaManager

            # the identity `role` label follows the replication role in
            # BOTH directions via metrics.update_repl_role — no daemon
            # hook needed for promotion OR demotion
            self.replica = ReplicaManager(
                self.api, replicas, replica_index,
                lease_ttl=repl_lease_ttl,
                on_became_leader=self._seed_if_configured,
            )
        if self.watchdog_enabled:
            from volcano_tpu.metrics.timeseries import TimeSeriesRing
            from volcano_tpu.obs.incident import IncidentManager
            from volcano_tpu.obs.slo import BurnRateWatchdog

            identity = f"apiserver-{replica_index}"
            ring = TimeSeriesRing()
            self.incidents = IncidentManager(
                self.api, identity,
                self.incident_dir
                or os.path.join("/tmp", f"vtpu-incidents-{identity}"),
                cooldown_s=float(
                    os.environ.get("VTPU_INCIDENT_COOLDOWN", "60")),
                boost_ttl_s=float(os.environ.get("VTPU_BOOST_TTL", "30")),
                metrics_ring=ring,
            )
            self.watchdog = BurnRateWatchdog(
                ring=ring,
                fast_window_s=float(
                    os.environ.get("VTPU_SLO_FAST_WINDOW", "60")),
                slow_window_s=float(
                    os.environ.get("VTPU_SLO_SLOW_WINDOW", "300")),
                period=float(os.environ.get("VTPU_WATCHDOG_PERIOD", "5")),
                on_breach=self.incidents.on_alert,
            )
        self.bus = BusServer(
            self.api, host=listen_host, port=bus_port,
            backlog_size=backlog_size, bookmark_interval=bookmark_interval,
            replica=self.replica,
        )
        self.serving = ServingServer(
            host=listen_host, port=listen_port,
            health_check=lambda: self.bus.running,
            debug_enabled=debug_enabled,
            degraded_source=self._degraded,
        )
        #: synthetic node pool + default queue on startup (idempotent).
        #: A real cluster's nodes arrive from kubelets; the standalone
        #: build's arrive from whoever owns the store — this daemon in
        #: the multi-process topology, vtpu-local-up otherwise.  In a
        #: replication group only the LEADER may write, so seeding runs
        #: from the became-leader hook instead of start().
        self.seed_nodes = seed_nodes
        self.seed_node_cpu = seed_node_cpu
        self.seed_node_mem = seed_node_mem

    #: a live voter lagging more than this many entries degrades the
    #: leader's /healthz (still 200 — the daemon serves; the body flags
    #: that a failover NOW would pay a snapshot resync)
    REPL_LAG_DEGRADED = 512

    def _degraded(self) -> Optional[str]:
        """``/healthz`` degraded body: replication state first (the
        breaker-registry convention every daemon follows — degraded,
        not dead), breaker registry second.

        * ``degraded: below-quorum`` — a leader that could not commit a
          write right now (live voters < quorum), or a follower that
          cannot name a leader (mid-election / partitioned): writes
          through this replica stall either way.
        * ``degraded: replica-lagging`` — quorum holds but the worst
          live voter trails by > REPL_LAG_DEGRADED entries.
        """
        rep = self.replica
        if rep is not None:
            with rep._lock:  # noqa: SLF001 — same-package status read
                role = rep.role
                coord = rep.coordinator
                leader = rep.leader_url
            if role == "leader" and coord is not None:
                health = coord.quorum_health(rep.lease_ttl)
                if health["live"] < health["quorum"]:
                    return "below-quorum"
                if health["max_lag"] > self.REPL_LAG_DEGRADED:
                    return "replica-lagging"
            elif role in ("follower", "init") and leader is None:
                return "below-quorum"
        from volcano_tpu.faults.breaker import degraded_reasons

        reasons = list(degraded_reasons())
        if self.watchdog is not None:
            reasons.extend(self.watchdog.degraded_reasons())
        return ", ".join(reasons) if reasons else None

    def _seed_if_configured(self) -> None:
        if self.seed_nodes <= 0:
            return
        import time

        from volcano_tpu.client.apiserver import ApiError
        from volcano_tpu.cmd.local_up import seed_cluster

        # quorum forms as followers attach; retry until the writes land
        # (idempotent — AlreadyExists is a no-op in seed_cluster).  The
        # loop never gives up silently: an unseeded cluster idles with
        # every job unschedulable and nothing pointing at the cause —
        # keep retrying (daemon thread, dies with the process) and get
        # LOUD about persistent failure.  If leadership moved on, the
        # new leader owns seeding and this attempt stands down.
        attempt = 0
        while True:
            if self.replica is not None and not self.replica.is_leader:
                log.info("seed attempt stands down: no longer the leader")
                return
            try:
                seed_cluster(self.api, self.seed_nodes,
                             self.seed_node_cpu, self.seed_node_mem)
                return
            except ApiError as e:
                attempt += 1
                level = log.error if attempt % 10 == 0 else log.warning
                level("cluster seeding failing (attempt %d): %s",
                      attempt, e)
                time.sleep(min(0.5 * attempt, 5.0))

    def start(self) -> "ApiServerDaemon":
        from volcano_tpu.metrics import metrics

        metrics.set_identity(
            daemon="apiserver",
            replica_index=str(self.replica_index),
            role="standalone" if self.replica is None else "follower",
        )
        if self.seed_nodes > 0 and self.replica is None:
            self._seed_if_configured()
        self.bus.start()
        self.serving.start()
        # advertised on bus_status so `vtctl top` can discover every
        # replica's /metrics by dialing the --bus endpoint list
        self.api.metrics_address = (
            f"{self.serving.host}:{self.serving.port}"
        )
        if self.flight_recorder:
            from volcano_tpu import obs

            self._obs_exporter = obs.enable(
                self.api, identity=f"apiserver-{self.replica_index}"
            )
        if self.watchdog is not None:
            self.watchdog.start()
        if self.replica is not None:
            self.replica.start()
        log.info(
            "apiserver up: bus on :%d, metrics on :%d%s",
            self.bus.port, self.serving.port,
            (f", replica {self.replica.identity} of "
             f"{self.replica.replica_count}") if self.replica else "",
        )
        return self

    def stop(self) -> None:
        if self.watchdog is not None:
            self.watchdog.stop()
        if self.replica is not None:
            self.replica.stop()
        self.bus.stop()
        self.serving.stop()
        close = getattr(self.api, "close", None)
        if close is not None:
            close()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="vtpu-apiserver")
    parser.add_argument("--listen-host", default="127.0.0.1")
    parser.add_argument(
        "--port", type=int, default=DEFAULT_BUS_PORT,
        help="bus TCP port the daemons and vtctl connect to",
    )
    parser.add_argument(
        "--listen-port", type=int, default=8083,
        help="healthz/metrics HTTP port",
    )
    parser.add_argument(
        "--backlog-size", type=int, default=4096,
        help="watch-event backlog depth; resumes older than this relist",
    )
    parser.add_argument("--bookmark-interval", type=float, default=2.0)
    parser.add_argument("--enable-debug-stacks", action="store_true")
    parser.add_argument(
        "--seed-nodes", type=int, default=0,
        help="create a synthetic node pool + default queue on startup "
        "(the standalone cluster's kubelet substitute; 0 = off; in a "
        "replication group the leader seeds after election)",
    )
    parser.add_argument("--seed-node-cpu", default="8")
    parser.add_argument("--seed-node-mem", default="32Gi")
    parser.add_argument(
        "--data-dir", default="",
        help="WAL + snapshot directory: store transactions are fsynced "
        "before acking and a restart resumes watch cursors (empty = "
        "volatile in-memory store, the pre-HA behavior)",
    )
    parser.add_argument(
        "--snapshot-every", type=int, default=256,
        help="rotate the WAL into a full snapshot every N records",
    )
    parser.add_argument(
        "--replicas", default="",
        help="comma-separated endpoint list of the WHOLE replication "
        "group (this replica included), e.g. tcp://a:7180,tcp://b:7180; "
        "requires --data-dir",
    )
    parser.add_argument(
        "--replica-index", type=int, default=0,
        help="this daemon's position in the --replicas list",
    )
    parser.add_argument(
        "--repl-lease-ttl", type=float, default=2.0,
        help="leader-liveness lease: a follower that cannot reach the "
        "leader for this long triggers an election",
    )
    parser.add_argument(
        "--faults", default="",
        help="deterministic fault-injection schedule (bus.* / wal.* / "
        "repl.* points fire server-side here; same grammar as "
        "VTPU_FAULTS)",
    )
    parser.add_argument(
        "--flight-recorder", action="store_true",
        help="record bus-op / WAL-fsync / quorum-wait spans for traced "
        "requests and export them as telemetry segments "
        "(volcano_tpu/obs; also VTPU_FLIGHT_RECORDER=1)",
    )
    parser.add_argument(
        "--watchdog", action="store_true",
        help="SLO burn-rate watchdog over this replica's own metrics "
        "(repl lag, commit failures, breaker state); breaches degrade "
        "/healthz and write incident bundles (also VTPU_WATCHDOG=1)",
    )
    parser.add_argument(
        "--incident-dir", default=None,
        help="incident-bundle ring directory (also VTPU_INCIDENT_DIR)",
    )
    parser.add_argument(
        "--shm", action="store_true",
        help="also listen on the same-host shared-memory ring "
        "transport (bus/shm.py; also VTPU_BUS_SHM=1 — what local_up "
        "--multiproc sets); clients fall back to TCP on attach failure",
    )
    args = parser.parse_args(argv)
    from volcano_tpu.cmd.daemon import apply_faults

    apply_faults(args.faults)
    if args.shm:
        os.environ["VTPU_BUS_SHM"] = "1"

    replicas = [u.strip() for u in args.replicas.split(",") if u.strip()]
    daemon = ApiServerDaemon(
        listen_host=args.listen_host,
        bus_port=args.port,
        listen_port=args.listen_port,
        backlog_size=args.backlog_size,
        bookmark_interval=args.bookmark_interval,
        debug_enabled=args.enable_debug_stacks,
        seed_nodes=args.seed_nodes,
        seed_node_cpu=args.seed_node_cpu,
        seed_node_mem=args.seed_node_mem,
        data_dir=args.data_dir,
        snapshot_every=args.snapshot_every,
        replicas=replicas,
        replica_index=args.replica_index,
        repl_lease_ttl=args.repl_lease_ttl,
        flight_recorder=True if args.flight_recorder else None,
        watchdog=True if args.watchdog else None,
        incident_dir=args.incident_dir,
    ).start()
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        daemon.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
