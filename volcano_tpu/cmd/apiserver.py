"""vtpu-apiserver — the standalone API-server daemon.

The reference deploys Kubernetes' API server as the bus all binaries
meet at; this is the standalone build's equivalent: the in-process
object store (client/apiserver.py) served over TCP by
``bus.BusServer``, plus the standard serving surface (healthz +
/metrics) every other daemon carries.

With this daemon up, every other binary — vtpu-scheduler,
vtpu-controllers, vtpu-admission, vtctl — connects with
``--bus tcp://host:port`` and the system runs as the reference's
multi-process deployment topology, including cross-process leader
election (the scheduler's ConfigMap lease lives on this store).
"""

from __future__ import annotations

import argparse
import threading
from typing import Optional

from volcano_tpu.bus.server import BusServer
from volcano_tpu.client.apiserver import APIServer
from volcano_tpu.serving import ServingServer
from volcano_tpu.utils.logging import get_logger

log = get_logger(__name__)

DEFAULT_BUS_PORT = 7180


class ApiServerDaemon:
    """The apiserver binary: store + bus listener + serving surface."""

    def __init__(
        self,
        api: Optional[APIServer] = None,
        listen_host: str = "127.0.0.1",
        bus_port: int = DEFAULT_BUS_PORT,
        listen_port: int = 0,
        backlog_size: int = 4096,
        bookmark_interval: float = 2.0,
        debug_enabled: bool = False,
        seed_nodes: int = 0,
        seed_node_cpu: str = "8",
        seed_node_mem: str = "32Gi",
    ):
        self.api = api if api is not None else APIServer()
        self.bus = BusServer(
            self.api, host=listen_host, port=bus_port,
            backlog_size=backlog_size, bookmark_interval=bookmark_interval,
        )
        self.serving = ServingServer(
            host=listen_host, port=listen_port,
            health_check=lambda: self.bus.running,
            debug_enabled=debug_enabled,
        )
        #: synthetic node pool + default queue on startup (idempotent).
        #: A real cluster's nodes arrive from kubelets; the standalone
        #: build's arrive from whoever owns the store — this daemon in
        #: the multi-process topology, vtpu-local-up otherwise.
        self.seed_nodes = seed_nodes
        self.seed_node_cpu = seed_node_cpu
        self.seed_node_mem = seed_node_mem

    def start(self) -> "ApiServerDaemon":
        if self.seed_nodes > 0:
            from volcano_tpu.cmd.local_up import seed_cluster

            seed_cluster(self.api, self.seed_nodes,
                         self.seed_node_cpu, self.seed_node_mem)
        self.bus.start()
        self.serving.start()
        log.info(
            "apiserver up: bus on :%d, metrics on :%d",
            self.bus.port, self.serving.port,
        )
        return self

    def stop(self) -> None:
        self.bus.stop()
        self.serving.stop()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="vtpu-apiserver")
    parser.add_argument("--listen-host", default="127.0.0.1")
    parser.add_argument(
        "--port", type=int, default=DEFAULT_BUS_PORT,
        help="bus TCP port the daemons and vtctl connect to",
    )
    parser.add_argument(
        "--listen-port", type=int, default=8083,
        help="healthz/metrics HTTP port",
    )
    parser.add_argument(
        "--backlog-size", type=int, default=4096,
        help="watch-event backlog depth; resumes older than this relist",
    )
    parser.add_argument("--bookmark-interval", type=float, default=2.0)
    parser.add_argument("--enable-debug-stacks", action="store_true")
    parser.add_argument(
        "--seed-nodes", type=int, default=0,
        help="create a synthetic node pool + default queue on startup "
        "(the standalone cluster's kubelet substitute; 0 = off)",
    )
    parser.add_argument("--seed-node-cpu", default="8")
    parser.add_argument("--seed-node-mem", default="32Gi")
    parser.add_argument(
        "--faults", default="",
        help="deterministic fault-injection schedule (bus.* points fire "
        "server-side here; same grammar as VTPU_FAULTS)",
    )
    args = parser.parse_args(argv)
    from volcano_tpu.cmd.daemon import apply_faults

    apply_faults(args.faults)

    daemon = ApiServerDaemon(
        listen_host=args.listen_host,
        bus_port=args.port,
        listen_port=args.listen_port,
        backlog_size=args.backlog_size,
        bookmark_interval=args.bookmark_interval,
        debug_enabled=args.enable_debug_stacks,
        seed_nodes=args.seed_nodes,
        seed_node_cpu=args.seed_node_cpu,
        seed_node_mem=args.seed_node_mem,
    ).start()
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        daemon.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
