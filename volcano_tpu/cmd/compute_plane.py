"""Compute-plane sidecar entry point.

The scheduler daemon runs the control plane; this process owns the
device and serves the packed kernels over the versioned socket protocol
(serving/compute_plane.py).  Colocate it with the accelerator and point
the scheduler at it via ``VTPU_COMPUTE_PLANE=<socket>``; if it dies the
scheduler's executors fall back in-process and re-probe.

Usage: python -m volcano_tpu.cmd.compute_plane --socket /run/vtpu.sock
"""

from __future__ import annotations

import argparse
import time

from volcano_tpu.serving.compute_plane import ComputePlaneServer
from volcano_tpu.utils.logging import get_logger

log = get_logger(__name__)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="vtpu-compute-plane")
    parser.add_argument("--socket", default="/tmp/vtpu-compute-plane.sock")
    parser.add_argument(
        "--warmup", action="store_true",
        help="compile the headline-bucket kernels before serving",
    )
    parser.add_argument(
        "--faults", default="",
        help="deterministic fault-injection schedule (compute.* and "
        "device.* points fire in this process; same grammar as "
        "VTPU_FAULTS)",
    )
    args = parser.parse_args(argv)
    from volcano_tpu.cmd.daemon import apply_faults

    apply_faults(args.faults)

    if args.warmup:
        # populate the jit cache so the first real session doesn't pay
        # compile latency (~20-40s on TPU)
        from volcano_tpu.ops.dispatch import warmup_kernels

        warmup_kernels()  # times and logs itself

    server = ComputePlaneServer(args.socket).start()
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        server.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
