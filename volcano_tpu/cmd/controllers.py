"""vtpu-controllers — the controller-manager daemon.

Reference: cmd/controllers/app/server.go:78-149 — leader-elected
``startControllers`` launching job/queue/garbage/podgroup controllers,
plus the healthz/metrics serving surface.
"""

from __future__ import annotations

import argparse
import threading
import time
import uuid
from typing import Optional

from volcano_tpu.client import APIServer
from volcano_tpu.cmd.scheduler import add_common_args
from volcano_tpu.controllers import (
    GarbageCollector,
    JobController,
    PodGroupController,
    QueueController,
)
from volcano_tpu.serving import LeaderElector, ServingServer
from volcano_tpu.utils.logging import get_logger

log = get_logger(__name__)

LOCK_NAME = "vtpu-controllers"


class ControllersDaemon:
    """The controller-manager binary: all controllers on one drain loop."""

    def __init__(
        self,
        api: APIServer,
        period: float = 0.2,
        listen_host: str = "127.0.0.1",
        listen_port: int = 0,
        leader_elect: bool = False,
        identity: Optional[str] = None,
        lease_duration: float = 2.0,
        retry_period: float = 0.2,
    ):
        self.api = api
        self.period = period
        self.identity = identity or f"vtpu-controllers-{uuid.uuid4().hex[:8]}"
        self.job_controller = JobController(api)
        self.queue_controller = QueueController(api)
        self.podgroup_controller = PodGroupController(api)
        self.gc = GarbageCollector(api)
        self.serving = ServingServer(host=listen_host, port=listen_port)
        self.elector: Optional[LeaderElector] = None
        if leader_elect:
            self.elector = LeaderElector(
                api,
                LOCK_NAME,
                self.identity,
                lease_duration=lease_duration,
                retry_period=retry_period,
            )
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.cycles = 0

    def drain(self) -> None:
        """One pass over every controller's work queue."""
        self.job_controller.drain()
        self.podgroup_controller.drain()
        self.queue_controller.drain()
        self.gc.process_expired()

    def _loop(self) -> None:
        while not self._stop.is_set():
            if self.elector is None or self.elector.is_leader:
                self.drain()
                self.cycles += 1
            self._stop.wait(self.period)

    def start(self) -> "ControllersDaemon":
        self.serving.start()
        if self.elector is not None:
            self.elector.start()
        self._thread = threading.Thread(
            target=self._loop, name=f"controllers-{self.identity}", daemon=True
        )
        self._thread.start()
        log.info(
            "controllers daemon %s serving on :%d", self.identity, self.serving.port
        )
        return self

    def stop(self, crash: bool = False) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=10)
        if self.elector is not None:
            self.elector.stop(release=not crash)
        self.serving.stop()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="vtpu-controllers")
    parser.add_argument("--period", type=float, default=0.2)
    add_common_args(parser)
    args = parser.parse_args(argv)
    daemon = ControllersDaemon(
        APIServer(),
        period=args.period,
        listen_host=args.listen_host,
        listen_port=args.listen_port,
        leader_elect=args.leader_elect,
        identity=args.leader_elect_id,
    )
    daemon.start()
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        daemon.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
