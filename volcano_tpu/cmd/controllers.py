"""vtpu-controllers — the controller-manager daemon.

Reference: cmd/controllers/app/server.go:78-149 — leader-elected
``startControllers`` launching job/queue/garbage/podgroup controllers,
plus the healthz/metrics serving surface.
"""

from __future__ import annotations

import argparse

from volcano_tpu.client import APIServer  # noqa: F401 — the in-process default
from volcano_tpu.cmd.daemon import BaseDaemon, serve_forever
from volcano_tpu.cmd.daemon import apply_faults
from volcano_tpu.cmd.scheduler import add_common_args, resolve_bus
from volcano_tpu.controllers import (
    GarbageCollector,
    JobController,
    PodGroupController,
    QueueController,
)


class ControllersDaemon(BaseDaemon):
    """The controller-manager binary: all controllers on one drain loop."""

    LOCK_NAME = "vtpu-controllers"
    NAME = "vtpu-controllers"

    def __init__(self, api: APIServer, period: float = 0.2, **daemon_kw):
        super().__init__(api, period=period, **daemon_kw)
        self.job_controller = JobController(api)
        self.queue_controller = QueueController(api)
        self.podgroup_controller = PodGroupController(api)
        self.gc = GarbageCollector(api)

    def drain(self) -> None:
        """One pass over every controller's work queue."""
        self.job_controller.drain()
        self.podgroup_controller.drain()
        self.queue_controller.drain()
        self.gc.process_expired()

    def _work(self) -> None:
        self.drain()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="vtpu-controllers")
    parser.add_argument("--period", type=float, default=0.2)
    add_common_args(parser)
    args = parser.parse_args(argv)
    apply_faults(args.faults)
    return serve_forever(
        ControllersDaemon(
            resolve_bus(args.bus),
            period=args.period,
            listen_host=args.listen_host,
            listen_port=args.listen_port,
            leader_elect=args.leader_elect,
            identity=args.leader_elect_id,
            debug_enabled=args.enable_debug_stacks,
            flight_recorder=True if args.flight_recorder else None,
            watchdog=True if args.watchdog else None,
            incident_dir=args.incident_dir,
        )
    )


if __name__ == "__main__":
    raise SystemExit(main())
