"""Shared daemon scaffolding: serving surface + leader election + the
guarded work loop.  SchedulerDaemon and ControllersDaemon differ only in
their work body and construction; everything else (loop, crash-stop
semantics, liveness) lives here once.
"""

from __future__ import annotations

import os
import threading
import time
import uuid
from typing import Optional

from volcano_tpu.client import APIServer
from volcano_tpu.serving import LeaderElector, ServingServer
from volcano_tpu.utils.logging import get_logger

log = get_logger(__name__)


class BaseDaemon:
    """Work loop + serving + optional leader election.

    Subclasses set ``LOCK_NAME``/``NAME`` and implement ``_work()`` (one
    cycle).  The loop is exception-guarded — a failing cycle is logged
    and retried, never silently killing the thread — and ``/healthz``
    reflects actual loop liveness, not just process liveness."""

    LOCK_NAME = "vtpu-daemon"
    NAME = "daemon"

    def __init__(
        self,
        api: APIServer,
        period: float = 0.2,
        listen_host: str = "127.0.0.1",
        listen_port: int = 0,
        leader_elect: bool = False,
        identity: Optional[str] = None,
        lease_duration: float = 2.0,
        retry_period: float = 0.2,
        debug_enabled: bool = False,
        explain_source=None,
        flight_recorder: Optional[bool] = None,
        watchdog: Optional[bool] = None,
        incident_dir: Optional[str] = None,
    ):
        self.api = api
        self.period = period
        self.identity = identity or f"{self.NAME}-{uuid.uuid4().hex[:8]}"
        #: cluster-wide flight recorder (volcano_tpu/obs): span batches
        #: export to the bus as telemetry segments.  None = follow
        #: VTPU_FLIGHT_RECORDER (so local_up/chaos harnesses flip every
        #: daemon with one env var)
        if flight_recorder is None:
            flight_recorder = os.environ.get(
                "VTPU_FLIGHT_RECORDER", ""
            ) not in ("", "0")
        self.flight_recorder = flight_recorder
        self._obs_exporter = None
        #: SLO burn-rate watchdog (obs/slo.py) + incident bundles
        #: (obs/incident.py).  None = follow VTPU_WATCHDOG /
        #: VTPU_INCIDENT_DIR so the drill harnesses flip every daemon
        #: with env vars, same shape as the flight recorder flag.
        if watchdog is None:
            watchdog = os.environ.get("VTPU_WATCHDOG", "") not in ("", "0")
        if incident_dir is None:
            incident_dir = os.environ.get("VTPU_INCIDENT_DIR", "")
        self.watchdog_enabled = watchdog
        self.incident_dir = incident_dir
        self.watchdog = None
        self.incidents = None
        #: uniform identity labels merged into every /metrics series
        #: (vtctl top's federation contract); subclasses refine
        self.identity_labels = {
            "daemon": self.NAME.replace("vtpu-", ""),
            "role": self.NAME.replace("vtpu-", ""),
        }
        if self.watchdog_enabled:
            from volcano_tpu.metrics.timeseries import TimeSeriesRing
            from volcano_tpu.obs.incident import IncidentManager
            from volcano_tpu.obs.slo import BurnRateWatchdog

            ring = TimeSeriesRing()
            self.incidents = IncidentManager(
                api,
                self.identity,
                self.incident_dir
                or os.path.join("/tmp", f"vtpu-incidents-{self.identity}"),
                cooldown_s=float(
                    os.environ.get("VTPU_INCIDENT_COOLDOWN", "60")),
                boost_ttl_s=float(os.environ.get("VTPU_BOOST_TTL", "30")),
                metrics_ring=ring,
                journal_dir=os.environ.get("VTPU_TRACE_JOURNAL", ""),
                explain_source=explain_source,
            )
            self.watchdog = BurnRateWatchdog(
                ring=ring,
                fast_window_s=float(
                    os.environ.get("VTPU_SLO_FAST_WINDOW", "60")),
                slow_window_s=float(
                    os.environ.get("VTPU_SLO_SLOW_WINDOW", "300")),
                period=float(os.environ.get("VTPU_WATCHDOG_PERIOD", "5")),
                on_breach=self.incidents.on_alert,
            )
        self.serving = ServingServer(
            host=listen_host, port=listen_port, health_check=self.healthy,
            debug_enabled=debug_enabled, explain_source=explain_source,
            degraded_source=self._degraded,
        )
        self.elector: Optional[LeaderElector] = None
        if leader_elect:
            self.elector = LeaderElector(
                api,
                self.LOCK_NAME,
                self.identity,
                lease_duration=lease_duration,
                retry_period=retry_period,
            )
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        #: cycles this instance actually ran (leadership observability)
        self.cycles = 0
        self.last_error: Optional[str] = None

    # ---- subclass API ----

    def _work(self) -> None:
        raise NotImplementedError

    def _on_start(self) -> None:
        """Hook before the loop thread starts (e.g. cache informers)."""

    # ---- loop ----

    def _loop(self) -> None:
        while not self._stop.is_set():
            if self.elector is None or self.elector.is_leader:
                try:
                    self._work()
                    self.cycles += 1
                    self.last_error = None
                except Exception as e:  # noqa: BLE001 — keep the loop alive
                    self.last_error = str(e)
                    log.error("%s cycle failed: %s", self.NAME, e)
            self._stop.wait(self.period)

    def _degraded(self) -> Optional[str]:
        """/healthz degraded body: open breakers (the serving default)
        plus the watchdog's active ``slo-burn:<name>`` breaches."""
        from volcano_tpu.serving.http import _default_degraded

        reasons = []
        breakers = _default_degraded()
        if breakers:
            reasons.append(breakers)
        if self.watchdog is not None:
            reasons.extend(self.watchdog.degraded_reasons())
        return "; ".join(reasons) if reasons else None

    def healthy(self) -> bool:
        """Liveness for /healthz: the loop thread must be running (or
        not yet started)."""
        return self._thread is None or self._thread.is_alive()

    def start(self):
        from volcano_tpu.metrics import metrics

        metrics.set_identity(**self.identity_labels)
        if self.flight_recorder:
            from volcano_tpu import obs

            self._obs_exporter = obs.enable(self.api, identity=self.identity)
        if self.watchdog is not None:
            self.watchdog.start()
        self.serving.start()
        self._on_start()
        if self.elector is not None:
            self.elector.start()
        self._thread = threading.Thread(
            target=self._loop, name=f"{self.NAME}-{self.identity}", daemon=True
        )
        self._thread.start()
        log.info("%s %s serving on :%d", self.NAME, self.identity, self.serving.port)
        return self

    def stop(self, crash: bool = False) -> None:
        """Stop the daemon.  ``crash=True`` skips the graceful lease
        release, leaving standbys to take over after expiry."""
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=10)
        if self.elector is not None:
            self.elector.stop(release=not crash)
        if self.watchdog is not None:
            self.watchdog.stop()
        if self._obs_exporter is not None:
            from volcano_tpu import obs

            if obs.get_exporter() is self._obs_exporter:
                obs.disable()  # final flush rides the exporter stop
            else:
                self._obs_exporter.stop()
            self._obs_exporter = None
        self.serving.stop()


def apply_faults(spec: str) -> None:
    """``--faults`` → the process-global fault plane (a parse error is
    a clean exit: a typo'd schedule must not run a different chaos
    plan).  An empty flag leaves VTPU_FAULTS env resolution intact.
    Lives here — not in cmd.scheduler — so store-only daemons
    (vtpu-apiserver, vtpu-compute-plane) don't drag the scheduler
    stack in for a flag helper."""
    if not spec:
        return
    from volcano_tpu import faults

    try:
        faults.configure(spec)
    except ValueError as e:
        raise SystemExit(f"--faults: {e}") from e


def serve_forever(daemon: BaseDaemon) -> int:
    """Blocking main body shared by the binaries."""
    daemon.start()
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        daemon.stop()
    return 0
