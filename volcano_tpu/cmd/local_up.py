"""vtpu-local-up — bring up the whole control plane in one process.

The standalone equivalent of hack/local-up-volcano.sh: one in-process
API server, admission + controllers + scheduler daemons, a synthetic
node pool, and a default queue — then an interactive prompt serving
``vtctl`` commands against the live cluster (or ``--demo`` which
submits a gang job and waits for it to run, then exits).
"""

from __future__ import annotations

import argparse
import signal
import sys
import threading
import time

from volcano_tpu.apis import core, scheduling
from volcano_tpu.client import APIServer, KubeClient, VolcanoClient
from volcano_tpu.cmd import AdmissionDaemon, ControllersDaemon, SchedulerDaemon


def _build_node(name: str, cpu: str, mem: str):
    alloc = {"cpu": cpu, "memory": mem, "pods": "110"}
    return core.Node(
        metadata=core.ObjectMeta(name=name, namespace=""),
        spec=core.NodeSpec(),
        status=core.NodeStatus(allocatable=dict(alloc), capacity=dict(alloc)),
    )


def local_up(nodes: int = 3, node_cpu: str = "8", node_mem: str = "16Gi",
             gate_pods: bool = False, scheduler_conf: str = "",
             listen_host: str = "127.0.0.1",
             admission_port: int = 0, controllers_port: int = 0,
             scheduler_port: int = 0):
    """Start the full control plane; returns (api, [daemons]).

    Ports default to 0 (ephemeral) for tests/interactive use; a real
    deployment (deploy/ renders this entry point as the pod command)
    passes fixed ports and a routable ``listen_host`` so probes and
    Services reach the daemons."""
    api = APIServer()
    admission = AdmissionDaemon(
        api, gate_pods=gate_pods,
        listen_host=listen_host, listen_port=admission_port,
    ).start()
    kube = KubeClient(api)
    vc = VolcanoClient(api)
    for i in range(nodes):
        kube.create_node(_build_node(f"node-{i}", node_cpu, node_mem))
    vc.create_queue(
        scheduling.Queue(metadata=core.ObjectMeta(name="default", namespace=""))
    )
    controllers = ControllersDaemon(
        api, period=0.1,
        listen_host=listen_host, listen_port=controllers_port,
    ).start()
    scheduler = SchedulerDaemon(
        api, schedule_period=0.2, scheduler_conf=scheduler_conf,
        listen_host=listen_host, listen_port=scheduler_port,
    ).start()
    return api, [admission, controllers, scheduler]


def _demo(api: APIServer) -> int:
    from volcano_tpu.apis import batch

    vc = VolcanoClient(api)
    kube = KubeClient(api)
    task = batch.TaskSpec(
        name="worker",
        replicas=3,
        template=core.PodTemplateSpec(
            spec=core.PodSpec(
                containers=[
                    core.Container(resources={"requests": {"cpu": "1", "memory": "1Gi"}})
                ]
            )
        ),
    )
    vc.create_job(
        batch.Job(
            metadata=core.ObjectMeta(name="demo", namespace="default"),
            spec=batch.JobSpec(min_available=3, tasks=[task]),
        )
    )
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        pods = kube.list_pods("default")
        if pods and all(p.spec.node_name for p in pods):
            print("demo job bound:", [(p.metadata.name, p.spec.node_name) for p in pods])
            return 0
        time.sleep(0.2)
    print("demo job did not bind within 30s", file=sys.stderr)
    return 1


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(prog="vtpu-local-up")
    parser.add_argument("--nodes", type=int, default=3)
    parser.add_argument("--node-cpu", default="8")
    parser.add_argument("--node-mem", default="16Gi")
    parser.add_argument("--demo", action="store_true",
                        help="submit a gang job, wait for it to run, exit")
    parser.add_argument("--serve", action="store_true",
                        help="run as a daemon until SIGTERM/SIGINT "
                        "(no interactive prompt; the container mode)")
    parser.add_argument("--listen-host", default="127.0.0.1")
    parser.add_argument("--scheduler-port", type=int, default=0)
    parser.add_argument("--controllers-port", type=int, default=0)
    parser.add_argument("--admission-port", type=int, default=0)
    parser.add_argument("--scheduler-conf", default="",
                        help="scheduler policy YAML, hot-reloaded per cycle")
    return parser


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    api, daemons = local_up(
        args.nodes, args.node_cpu, args.node_mem,
        scheduler_conf=args.scheduler_conf,
        listen_host=args.listen_host,
        admission_port=args.admission_port,
        controllers_port=args.controllers_port,
        scheduler_port=args.scheduler_port,
    )
    print(
        "control plane up: admission/controllers/scheduler serving on ports",
        [d.serving.port for d in daemons],
    )
    try:
        if args.demo:
            return _demo(api)
        if args.serve:
            stop = threading.Event()
            for sig in (signal.SIGTERM, signal.SIGINT):
                signal.signal(sig, lambda *_: stop.set())
            stop.wait()
            return 0
        from volcano_tpu.cli.vtctl import main as vtctl_main

        print("interactive vtctl — e.g. `job list` (ctrl-d to exit)")
        for line in sys.stdin:
            argv_line = line.split()
            if argv_line:
                vtctl_main(argv_line, api=api)
        return 0
    finally:
        for d in daemons:
            d.stop()


if __name__ == "__main__":
    raise SystemExit(main())
