"""vtpu-local-up — bring up the whole control plane in one process.

The standalone equivalent of hack/local-up-volcano.sh: one in-process
API server, admission + controllers + scheduler daemons, a synthetic
node pool, and a default queue — then an interactive prompt serving
``vtctl`` commands against the live cluster (or ``--demo`` which
submits a gang job and waits for it to run, then exits).
"""

from __future__ import annotations

import argparse
import sys
import time

from volcano_tpu.apis import core, scheduling
from volcano_tpu.client import APIServer, KubeClient, VolcanoClient
from volcano_tpu.cmd import AdmissionDaemon, ControllersDaemon, SchedulerDaemon


def _build_node(name: str, cpu: str, mem: str):
    alloc = {"cpu": cpu, "memory": mem, "pods": "110"}
    return core.Node(
        metadata=core.ObjectMeta(name=name, namespace=""),
        spec=core.NodeSpec(),
        status=core.NodeStatus(allocatable=dict(alloc), capacity=dict(alloc)),
    )


def local_up(nodes: int = 3, node_cpu: str = "8", node_mem: str = "16Gi",
             gate_pods: bool = False):
    """Start the full control plane; returns (api, [daemons])."""
    api = APIServer()
    admission = AdmissionDaemon(api, gate_pods=gate_pods).start()
    kube = KubeClient(api)
    vc = VolcanoClient(api)
    for i in range(nodes):
        kube.create_node(_build_node(f"node-{i}", node_cpu, node_mem))
    vc.create_queue(
        scheduling.Queue(metadata=core.ObjectMeta(name="default", namespace=""))
    )
    controllers = ControllersDaemon(api, period=0.1).start()
    scheduler = SchedulerDaemon(api, schedule_period=0.2).start()
    return api, [admission, controllers, scheduler]


def _demo(api: APIServer) -> int:
    from volcano_tpu.apis import batch

    vc = VolcanoClient(api)
    kube = KubeClient(api)
    task = batch.TaskSpec(
        name="worker",
        replicas=3,
        template=core.PodTemplateSpec(
            spec=core.PodSpec(
                containers=[
                    core.Container(resources={"requests": {"cpu": "1", "memory": "1Gi"}})
                ]
            )
        ),
    )
    vc.create_job(
        batch.Job(
            metadata=core.ObjectMeta(name="demo", namespace="default"),
            spec=batch.JobSpec(min_available=3, tasks=[task]),
        )
    )
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        pods = kube.list_pods("default")
        if pods and all(p.spec.node_name for p in pods):
            print("demo job bound:", [(p.metadata.name, p.spec.node_name) for p in pods])
            return 0
        time.sleep(0.2)
    print("demo job did not bind within 30s", file=sys.stderr)
    return 1


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="vtpu-local-up")
    parser.add_argument("--nodes", type=int, default=3)
    parser.add_argument("--node-cpu", default="8")
    parser.add_argument("--node-mem", default="16Gi")
    parser.add_argument("--demo", action="store_true",
                        help="submit a gang job, wait for it to run, exit")
    args = parser.parse_args(argv)

    api, daemons = local_up(args.nodes, args.node_cpu, args.node_mem)
    print(
        "control plane up: admission/controllers/scheduler serving on ports",
        [d.serving.port for d in daemons],
    )
    try:
        if args.demo:
            return _demo(api)
        from volcano_tpu.cli.vtctl import main as vtctl_main

        print("interactive vtctl — e.g. `job list` (ctrl-d to exit)")
        for line in sys.stdin:
            argv_line = line.split()
            if argv_line:
                vtctl_main(argv_line, api=api)
        return 0
    finally:
        for d in daemons:
            d.stop()


if __name__ == "__main__":
    raise SystemExit(main())
