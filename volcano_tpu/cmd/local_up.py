"""vtpu-local-up — bring up the whole control plane.

The standalone equivalent of hack/local-up-volcano.sh.  Three topologies:

* default: one in-process API server with admission + controllers +
  scheduler daemon threads (the original single-process simulation);
* ``--bus tcp://host:port``: the same daemon threads, but connected to
  an already-running external ``vtpu-apiserver``;
* ``--multiproc``: the reference's deployment topology — spawns
  ``vtpu-apiserver`` plus the scheduler / controllers / admission
  binaries as real OS processes talking TCP, optionally with a standby
  scheduler (``--standby-scheduler``) for cross-process HA takeover.

Then an interactive prompt serves ``vtctl`` commands against the live
cluster (or ``--demo`` submits a gang job, waits for it to run, and
exits).
"""

from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys
import threading
import time
from typing import List, Tuple

from volcano_tpu.apis import batch, core, scheduling
from volcano_tpu.client import AdmissionError, AlreadyExistsError, APIServer, KubeClient, VolcanoClient
from volcano_tpu.cmd import AdmissionDaemon, ControllersDaemon, SchedulerDaemon


def _build_node(name: str, cpu: str, mem: str):
    alloc = {"cpu": cpu, "memory": mem, "pods": "110"}
    return core.Node(
        metadata=core.ObjectMeta(name=name, namespace=""),
        spec=core.NodeSpec(),
        status=core.NodeStatus(allocatable=dict(alloc), capacity=dict(alloc)),
    )


def seed_cluster(api, nodes: int, node_cpu: str, node_mem: str) -> None:
    """Create the synthetic node pool + default queue (idempotent, so a
    re-run against a live external bus is safe)."""
    kube = KubeClient(api)
    vc = VolcanoClient(api)
    for i in range(nodes):
        try:
            kube.create_node(_build_node(f"node-{i}", node_cpu, node_mem))
        except AlreadyExistsError:
            pass
    try:
        vc.create_queue(
            scheduling.Queue(metadata=core.ObjectMeta(name="default", namespace=""))
        )
    except AlreadyExistsError:
        pass


def local_up(nodes: int = 3, node_cpu: str = "8", node_mem: str = "16Gi",
             gate_pods: bool = False, scheduler_conf: str = "",
             listen_host: str = "127.0.0.1",
             admission_port: int = 0, controllers_port: int = 0,
             scheduler_port: int = 0, api=None,
             micro_cycles: bool = False):
    """Start the full control plane; returns (api, [daemons]).

    Ports default to 0 (ephemeral) for tests/interactive use; a real
    deployment (deploy/ renders this entry point as the pod command)
    passes fixed ports and a routable ``listen_host`` so probes and
    Services reach the daemons.  ``api`` may be a RemoteAPIServer to run
    the daemon threads against an external bus."""
    if api is None:
        api = APIServer()
    admission = AdmissionDaemon(
        api, gate_pods=gate_pods,
        listen_host=listen_host, listen_port=admission_port,
    ).start()
    seed_cluster(api, nodes, node_cpu, node_mem)
    controllers = ControllersDaemon(
        api, period=0.1,
        listen_host=listen_host, listen_port=controllers_port,
    ).start()
    scheduler = SchedulerDaemon(
        api, schedule_period=0.2, scheduler_conf=scheduler_conf,
        listen_host=listen_host, listen_port=scheduler_port,
        micro_cycles=micro_cycles,
    ).start()
    return api, [admission, controllers, scheduler]


# ---- multi-process topology ----


def _free_port(host: str) -> int:
    with socket.socket() as s:
        s.bind((host, 0))
        return s.getsockname()[1]


def _spawn(module: str, *flags: str) -> subprocess.Popen:
    return subprocess.Popen(
        [sys.executable, "-m", module, *flags],
        env=dict(os.environ),
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
    )


def wait_for_admission(api, timeout: float = 60.0) -> bool:
    """Block until the (remote) admission webhook is answering reviews.

    The probe is semantic: an invalid job (minAvailable=0) must be
    DENIED.  While the webhook is still registering, the create
    succeeds — the probe object is deleted and the poll retries, so a
    workload submitted afterwards always passes through admission."""
    probe = batch.Job(
        metadata=core.ObjectMeta(name="admission-probe", namespace="default"),
        spec=batch.JobSpec(min_available=0, tasks=[]),
    )
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            api.create(probe.clone())
        except AdmissionError:
            return True
        except AlreadyExistsError:
            # a probe leaked by an earlier attempt whose delete failed —
            # clear it so the next iteration can probe again, instead of
            # spinning on the conflict until the timeout
            try:
                api.delete("Job", "default", "admission-probe")
            except Exception:  # noqa: BLE001
                pass
            time.sleep(0.2)
            continue
        except Exception:  # noqa: BLE001 — bus still coming up
            time.sleep(0.2)
            continue
        try:
            api.delete("Job", "default", "admission-probe")
        except Exception:  # noqa: BLE001
            pass
        time.sleep(0.2)
    return False


def multiproc_up(nodes: int = 3, node_cpu: str = "8", node_mem: str = "16Gi",
                 gate_pods: bool = False, scheduler_conf: str = "",
                 listen_host: str = "127.0.0.1", bus_port: int = 0,
                 standby_scheduler: bool = False,
                 schedule_period: float = 0.2,
                 micro_cycles: bool = False,
                 apiserver_replicas: int = 1,
                 apiserver_data_dir: str = "",
                 repl_lease_ttl: float = 2.0,
                 flight_recorder: bool = False,
                 ) -> Tuple[object, List[subprocess.Popen]]:
    """The reference's deployment topology as real OS processes:
    vtpu-apiserver + vtpu-admission + vtpu-controllers + vtpu-scheduler
    (two schedulers with leader election when ``standby_scheduler``).
    ``apiserver_replicas > 1`` spawns the replicated persistent bus —
    N ``vtpu-apiserver`` processes with per-replica WAL dirs forming a
    leader/follower group, every daemon dialed to the full endpoint
    list.

    Returns ``(RemoteAPIServer, [Popen, ...])``; the caller owns
    process teardown (``shutdown_procs``)."""
    import tempfile

    from volcano_tpu.bus import connect_bus

    # same-host topology ⇒ engage the shared-memory ring transport for
    # every daemon (they inherit the environment via _spawn) AND for
    # this process's own bus client.  VTPU_BUS_SHM=0 opts out; any
    # attach failure falls back to TCP silently, so this is a fast
    # path, never a new failure mode.
    os.environ.setdefault("VTPU_BUS_SHM", "1")

    if bus_port == 0:
        bus_port = _free_port(listen_host)
    procs: List[subprocess.Popen] = []
    #: appended to EVERY daemon so the whole topology records into one
    #: flight-recorder namespace (`vtctl trace pod` spans ≥3 processes)
    fr_flags = ["--flight-recorder"] if flight_recorder else []

    if apiserver_replicas > 1:
        ports = [bus_port] + [
            _free_port(listen_host) for _ in range(apiserver_replicas - 1)
        ]
        endpoints = [f"tcp://{listen_host}:{p}" for p in ports]
        bus_url = ",".join(endpoints)
        base_dir = apiserver_data_dir or tempfile.mkdtemp(
            prefix="vtpu-apiserver-"
        )
        for i, port in enumerate(ports):
            procs.append(_spawn(
                "volcano_tpu.cmd.apiserver",
                "--listen-host", listen_host, "--port", str(port),
                "--listen-port", "0",
                "--data-dir", os.path.join(base_dir, f"replica-{i}"),
                "--replicas", bus_url,
                "--replica-index", str(i),
                "--repl-lease-ttl", str(repl_lease_ttl),
                # the LEADER seeds after election (followers are
                # read-only), so every replica carries the flag
                "--seed-nodes", str(nodes),
                "--seed-node-cpu", node_cpu, "--seed-node-mem", node_mem,
                *fr_flags,
            ))
    else:
        bus_url = f"tcp://{listen_host}:{bus_port}"
        apiserver_flags = [
            "--listen-host", listen_host, "--port", str(bus_port),
            "--listen-port", "0",
        ]
        if apiserver_data_dir:
            apiserver_flags += ["--data-dir", apiserver_data_dir]
        apiserver_flags += fr_flags
        procs.append(_spawn("volcano_tpu.cmd.apiserver", *apiserver_flags))
    api = None
    try:
        # BusError after the wait means the spawned apiserver never came
        # up; the except below reaps it
        api = connect_bus(bus_url, wait=60.0)

        admission_flags = ["--bus", bus_url, "--listen-port", "0",
                           *fr_flags]
        if gate_pods:
            admission_flags.append("--gate-pods")
        procs.append(_spawn("volcano_tpu.cmd.admission", *admission_flags))
        procs.append(_spawn(
            "volcano_tpu.cmd.controllers",
            "--bus", bus_url, "--listen-port", "0", "--period", "0.1",
            *fr_flags,
        ))

        scheduler_flags = [
            "--bus", bus_url, "--listen-port", "0",
            "--schedule-period", str(schedule_period),
            *fr_flags,
        ]
        if micro_cycles:
            scheduler_flags.append("--micro-cycles")
        if scheduler_conf:
            scheduler_flags += ["--scheduler-conf", scheduler_conf]
        n_schedulers = 2 if standby_scheduler else 1
        for i in range(n_schedulers):
            flags = list(scheduler_flags)
            if standby_scheduler:
                flags += ["--leader-elect", "--leader-elect-id", f"sched-{i}"]
            procs.append(_spawn("volcano_tpu.cmd.scheduler", *flags))

        if apiserver_replicas > 1:
            # the elected leader seeds (followers are read-only); wait
            # for the pool to appear instead of racing the election
            deadline = time.monotonic() + 60.0
            while time.monotonic() < deadline:
                try:
                    if len(api.list("Node")) >= nodes and api.list("Queue"):
                        break
                except Exception:  # noqa: BLE001 — group still electing
                    pass
                time.sleep(0.2)
            else:
                raise RuntimeError(
                    "replicated apiserver group never seeded the cluster"
                )
        else:
            seed_cluster(api, nodes, node_cpu, node_mem)
    except BaseException:
        # a failure mid-setup must not strand the daemons it already
        # spawned (the caller never gets a handle to clean them up)
        if api is not None:
            api.close()
        shutdown_procs(procs)
        raise
    return api, procs


def shutdown_procs(procs: List[subprocess.Popen], grace: float = 5.0) -> None:
    for p in procs:
        if p.poll() is None:
            p.terminate()
    deadline = time.monotonic() + grace
    for p in procs:
        remaining = max(deadline - time.monotonic(), 0.1)
        try:
            p.wait(timeout=remaining)
        except subprocess.TimeoutExpired:
            p.kill()


def _demo(api, timeout: float = 30.0) -> int:
    vc = VolcanoClient(api)
    kube = KubeClient(api)
    task = batch.TaskSpec(
        name="worker",
        replicas=3,
        template=core.PodTemplateSpec(
            spec=core.PodSpec(
                containers=[
                    core.Container(
                        image="registry.k8s.io/pause:3.9",
                        resources={"requests": {"cpu": "1", "memory": "1Gi"}},
                    )
                ]
            )
        ),
    )
    vc.create_job(
        batch.Job(
            metadata=core.ObjectMeta(name="demo", namespace="default"),
            spec=batch.JobSpec(min_available=3, tasks=[task]),
        )
    )
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        pods = kube.list_pods("default")
        if pods and all(p.spec.node_name for p in pods):
            print("demo job bound:", [(p.metadata.name, p.spec.node_name) for p in pods])
            return 0
        time.sleep(0.2)
    print(f"demo job did not bind within {timeout:.0f}s", file=sys.stderr)
    return 1


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(prog="vtpu-local-up")
    parser.add_argument("--nodes", type=int, default=3)
    parser.add_argument("--node-cpu", default="8")
    parser.add_argument("--node-mem", default="16Gi")
    parser.add_argument("--demo", action="store_true",
                        help="submit a gang job, wait for it to run, exit")
    parser.add_argument("--serve", action="store_true",
                        help="run as a daemon until SIGTERM/SIGINT "
                        "(no interactive prompt; the container mode)")
    parser.add_argument("--bus", default="",
                        help="connect the daemons to an external "
                        "vtpu-apiserver at tcp://host:port instead of "
                        "an in-process store")
    parser.add_argument("--multiproc", action="store_true",
                        help="spawn vtpu-apiserver + the three daemons "
                        "as real OS processes over TCP (the reference's "
                        "deployment topology)")
    parser.add_argument("--standby-scheduler", action="store_true",
                        help="with --multiproc: run a second scheduler "
                        "process under leader election (HA takeover)")
    parser.add_argument("--bus-port", type=int, default=0,
                        help="with --multiproc: fixed bus port "
                        "(0 = pick a free one)")
    parser.add_argument("--apiserver-replicas", type=int, default=1,
                        help="with --multiproc: spawn N vtpu-apiserver "
                        "replicas forming the replicated persistent bus "
                        "(WAL + leader/follower log shipping); daemons "
                        "dial the full endpoint list")
    parser.add_argument("--apiserver-data-dir", default="",
                        help="with --multiproc: WAL/snapshot directory "
                        "(per-replica subdirs when replicated; empty = "
                        "a temp dir for replicas, volatile store for a "
                        "single apiserver)")
    parser.add_argument("--repl-lease-ttl", type=float, default=2.0,
                        help="apiserver leader-liveness lease TTL")
    parser.add_argument("--listen-host", default="127.0.0.1")
    parser.add_argument("--scheduler-port", type=int, default=0)
    parser.add_argument("--controllers-port", type=int, default=0)
    parser.add_argument("--admission-port", type=int, default=0)
    parser.add_argument("--micro-cycles", action="store_true",
                        help="event-driven scheduler: wake on watch "
                        "events and run debounced micro-cycles between "
                        "the periodic full cycles")
    parser.add_argument("--scheduler-conf", default="",
                        help="scheduler policy YAML, hot-reloaded per cycle")
    parser.add_argument("--flight-recorder", action="store_true",
                        help="enable the cluster-wide flight recorder "
                        "on every spawned daemon (vtctl trace pod/gang "
                        "renders the cross-process waterfall)")
    return parser


def _interact_or_wait(args, api) -> int:
    if args.serve:
        stop = threading.Event()
        for sig in (signal.SIGTERM, signal.SIGINT):
            signal.signal(sig, lambda *_: stop.set())
        stop.wait()
        return 0
    from volcano_tpu.cli.vtctl import main as vtctl_main

    print("interactive vtctl — e.g. `job list` (ctrl-d to exit)")
    for line in sys.stdin:
        argv_line = line.split()
        if argv_line:
            vtctl_main(argv_line, api=api)
    return 0


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    if args.multiproc:
        api, procs = multiproc_up(
            args.nodes, args.node_cpu, args.node_mem,
            scheduler_conf=args.scheduler_conf,
            listen_host=args.listen_host,
            bus_port=args.bus_port,
            standby_scheduler=args.standby_scheduler,
            micro_cycles=args.micro_cycles,
            apiserver_replicas=args.apiserver_replicas,
            apiserver_data_dir=args.apiserver_data_dir,
            repl_lease_ttl=args.repl_lease_ttl,
            flight_recorder=args.flight_recorder,
        )
        print(f"multi-process control plane up: bus {api.address}, "
              f"{len(procs)} daemons "
              f"(pids {[p.pid for p in procs]})")
        try:
            if not wait_for_admission(api):
                print("admission daemon never registered", file=sys.stderr)
                return 1
            if args.demo:
                return _demo(api, timeout=120.0)
            return _interact_or_wait(args, api)
        finally:
            api.close()
            shutdown_procs(procs)

    remote = None
    if args.bus:
        from volcano_tpu.bus import BusError, connect_bus

        try:
            remote = connect_bus(args.bus)
        except BusError as e:
            print(str(e), file=sys.stderr)
            return 1

    api, daemons = local_up(
        args.nodes, args.node_cpu, args.node_mem,
        scheduler_conf=args.scheduler_conf,
        listen_host=args.listen_host,
        admission_port=args.admission_port,
        controllers_port=args.controllers_port,
        scheduler_port=args.scheduler_port,
        micro_cycles=args.micro_cycles,
        api=remote,
    )
    print(
        "control plane up: admission/controllers/scheduler serving on ports",
        [d.serving.port for d in daemons],
    )
    try:
        if args.demo:
            return _demo(api)
        return _interact_or_wait(args, api)
    finally:
        for d in daemons:
            d.stop()
        if remote is not None:
            remote.close()


if __name__ == "__main__":
    raise SystemExit(main())
