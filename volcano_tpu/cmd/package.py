"""vtpu-package — render the deploy manifests from a values tree.

The ``helm template`` / ``helm install`` equivalent for this build
(reference: installer/helm/chart/volcano/).  Subcommands:

  vtpu-package template [--values f] [--set a.b=c ...]
      print the rendered multi-document YAML stream to stdout
  vtpu-package render -o DIR [--values f] [--set a.b=c ...]
      write one file per manifest into DIR
  vtpu-package values
      print the default values tree (the chart's values.yaml)
"""

from __future__ import annotations

import argparse
import os
import sys

from volcano_tpu.deploy.package import (
    apply_set,
    DEFAULT_VALUES,
    load_values,
    render,
    render_yaml,
)


def _resolve_values(args) -> dict:
    values = DEFAULT_VALUES
    if args.values:
        with open(args.values, "r", encoding="utf-8") as fh:
            values = load_values(fh.read())
    for assignment in args.set or []:
        values = apply_set(values, assignment)
    for assignment in getattr(args, "set_string", None) or []:
        values = apply_set(values, assignment, coerce=False)
    return values


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="vtpu-package")
    sub = parser.add_subparsers(dest="command", required=True)

    for name in ("template", "render"):
        p = sub.add_parser(name)
        p.add_argument("--values", help="values YAML file merged over defaults")
        p.add_argument("--set", action="append", metavar="KEY=VALUE",
                       help="override one values path (repeatable)")
        p.add_argument("--set-string", action="append", metavar="KEY=VALUE",
                       help="like --set but the value is never coerced "
                       "(stays a string)")
        if name == "render":
            p.add_argument("-o", "--output-dir", required=True)

    sub.add_parser("values")

    args = parser.parse_args(argv)

    if args.command == "values":
        import yaml

        sys.stdout.write(yaml.safe_dump(DEFAULT_VALUES, sort_keys=False))
        return 0

    import yaml

    try:
        values = _resolve_values(args)
    except (ValueError, OSError, yaml.YAMLError) as e:
        # user-input errors get the one-line CLI treatment, not a trace
        print(f"error: {e}", file=sys.stderr)
        return 2

    try:
        if args.command == "template":
            sys.stdout.write(render_yaml(values))
            return 0

        os.makedirs(args.output_dir, exist_ok=True)
        for fname, manifest in render(values):
            path = os.path.join(args.output_dir, fname)
            with open(path, "w", encoding="utf-8") as fh:
                yaml.safe_dump(manifest, fh, sort_keys=False,
                               default_flow_style=False)
            print(path)
        return 0
    except OSError as e:
        # e.g. basic.scheduler_config_file pointing at a missing policy
        print(f"error: {e}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    raise SystemExit(main())
