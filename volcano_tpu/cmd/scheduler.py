"""vtpu-scheduler — the scheduler daemon.

Reference: cmd/scheduler/app/server.go:77-157 — metrics HTTP server
(:96-99), healthz (:101), optional ConfigMap-lock leader election
(:110-156) around ``Scheduler.Run``.  Options mirror
cmd/scheduler/app/options/options.go:44-66.
"""

from __future__ import annotations

import argparse
import threading
import time
import uuid
from typing import Optional

from volcano_tpu.cache import SchedulerCache
from volcano_tpu.client import APIServer, SchedulerClient
from volcano_tpu.scheduler.scheduler import Scheduler
from volcano_tpu.serving import LeaderElector, ServingServer
from volcano_tpu.utils.logging import get_logger

log = get_logger(__name__)

LOCK_NAME = "vtpu-scheduler"


class SchedulerDaemon:
    """The scheduler binary: cache + session loop + serving surface."""

    def __init__(
        self,
        api: APIServer,
        scheduler_conf: str = "",
        schedule_period: float = 1.0,
        scheduler_name: str = "volcano-tpu",
        listen_host: str = "127.0.0.1",
        listen_port: int = 0,
        leader_elect: bool = False,
        identity: Optional[str] = None,
        lease_duration: float = 2.0,
        retry_period: float = 0.2,
    ):
        self.api = api
        self.period = schedule_period
        self.identity = identity or f"vtpu-scheduler-{uuid.uuid4().hex[:8]}"
        self.cache = SchedulerCache(
            client=SchedulerClient(api), scheduler_name=scheduler_name
        )
        self.scheduler = Scheduler(
            self.cache, scheduler_conf_path=scheduler_conf, period=schedule_period
        )
        self.serving = ServingServer(host=listen_host, port=listen_port)
        self.elector: Optional[LeaderElector] = None
        if leader_elect:
            self.elector = LeaderElector(
                api,
                LOCK_NAME,
                self.identity,
                lease_duration=lease_duration,
                retry_period=retry_period,
            )
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        #: sessions this instance actually ran (leadership observability)
        self.cycles = 0

    def _loop(self) -> None:
        while not self._stop.is_set():
            if self.elector is None or self.elector.is_leader:
                self.scheduler.run_once()
                self.cycles += 1
            self._stop.wait(self.period)

    def start(self) -> "SchedulerDaemon":
        self.serving.start()
        self.cache.run()
        if self.elector is not None:
            self.elector.start()
        self._thread = threading.Thread(
            target=self._loop, name=f"scheduler-{self.identity}", daemon=True
        )
        self._thread.start()
        log.info(
            "scheduler daemon %s serving on :%d", self.identity, self.serving.port
        )
        return self

    def stop(self, crash: bool = False) -> None:
        """Stop the daemon.  ``crash=True`` skips the graceful lease
        release, leaving standbys to take over after expiry."""
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=10)
        if self.elector is not None:
            self.elector.stop(release=not crash)
        self.serving.stop()


def add_common_args(parser: argparse.ArgumentParser) -> None:
    parser.add_argument("--listen-host", default="127.0.0.1")
    parser.add_argument("--listen-port", type=int, default=8080)
    parser.add_argument("--leader-elect", action="store_true")
    parser.add_argument("--leader-elect-id", default=None)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="vtpu-scheduler")
    parser.add_argument("--scheduler-conf", default="")
    parser.add_argument("--schedule-period", type=float, default=1.0)
    parser.add_argument("--scheduler-name", default="volcano-tpu")
    add_common_args(parser)
    args = parser.parse_args(argv)

    daemon = SchedulerDaemon(
        APIServer(),
        scheduler_conf=args.scheduler_conf,
        schedule_period=args.schedule_period,
        scheduler_name=args.scheduler_name,
        listen_host=args.listen_host,
        listen_port=args.listen_port,
        leader_elect=args.leader_elect,
        identity=args.leader_elect_id,
    )
    daemon.start()
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        daemon.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
